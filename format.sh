#!/usr/bin/env bash
# Lint / format gate (reference format.sh: yapf + flake8; here ruff, which
# subsumes both). Usage:
#   ./format.sh          # fix in place
#   ./format.sh --check  # CI mode: fail on violations, change nothing
set -euo pipefail
cd "$(dirname "$0")"

TARGETS=(ray_shuffling_data_loader_tpu tests benchmarks examples bench.py __graft_entry__.py)

if ! command -v ruff >/dev/null 2>&1; then
    echo "ruff not installed; running syntax check only" >&2
    python -m compileall -q "${TARGETS[@]}"
    if [[ "${1:-}" == "--check" ]]; then
        # Invariant lint rides the check gate even without ruff
        # (ISSUE 14; pure stdlib/AST).
        python tools/rsdl_lint.py
    fi
    exit 0
fi

if [[ "${1:-}" == "--check" ]]; then
    ruff check "${TARGETS[@]}"
    # Style clean isn't invariant clean: chain the repo's own
    # static-analysis suite (gate/knob/vocab/determinism/lock/barrier
    # checkers — see docs/static-analysis.md) into the same gate.
    python tools/rsdl_lint.py
else
    ruff check --fix "${TARGETS[@]}"
fi
