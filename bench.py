"""Benchmark: per-epoch shuffle -> HBM-staged batches -> real train step.

Measures the north-star metric (BASELINE.json): shuffle+delivery throughput
per chip and trainer stall fraction on the synthetic DATA_SPEC workload,
with the flagship DLRM train step consuming mesh-sharded HBM batches on the
real chip. Prints ONE JSON line:

    {"metric": ..., "value": <GB/s/chip>, "unit": ..., "vs_baseline": ...}

``vs_baseline`` is the achieved fraction of the driver target (0.8 × the
measured peak host->HBM ``device_put`` bandwidth on this chip — BASELINE.md
"≥80% of host→HBM staging bandwidth"); ≥1.0 means target met. Extra keys
carry stall%, peak bandwidth, and phase timings.

Workload knobs are fixed so values are comparable across rounds. Generated
Parquet is cached under ``.bench_cache/``.
"""

from __future__ import annotations

import json
import os
import sys
import time

NUM_ROWS = 1_000_000
NUM_FILES = 8
ROW_GROUPS_PER_FILE = 2
BATCH_SIZE = 65_536
NUM_EPOCHS = 4
NUM_REDUCERS = 4
EMBED_DIM = 32
SEED = 0

CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")


def _get_data():
    from ray_shuffling_data_loader_tpu.data_generation import (
        cached_generate_data,
    )

    data_dir = os.path.join(
        CACHE_DIR, f"r{NUM_ROWS}_f{NUM_FILES}_g{ROW_GROUPS_PER_FILE}_s{SEED}"
    )
    os.makedirs(data_dir, exist_ok=True)
    t0 = time.perf_counter()
    filenames, num_bytes = cached_generate_data(
        NUM_ROWS, NUM_FILES, ROW_GROUPS_PER_FILE, data_dir, seed=SEED
    )
    if time.perf_counter() - t0 > 1.0:
        print(
            f"[bench] generated {num_bytes/1e9:.2f} GB in "
            f"{time.perf_counter()-t0:.1f}s",
            file=sys.stderr,
        )
    return list(filenames), num_bytes


def _measure_peak_h2d_gbps() -> float:
    """Peak blocking host->HBM bandwidth via a large pinned-size device_put."""
    import jax
    import numpy as np

    arr = np.ones((256, 1024, 1024), dtype=np.uint8)  # 256 MB
    jax.block_until_ready(jax.device_put(arr))  # warm up
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(arr))
        dt = time.perf_counter() - t0
        best = max(best, arr.nbytes / dt)
    return best / 1e9


def main() -> None:
    import jax

    import numpy as np
    import optax

    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.data_generation import (
        DATA_SPEC,
        LABEL_COLUMN,
    )
    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
    from ray_shuffling_data_loader_tpu.models import TabularDLRM
    from ray_shuffling_data_loader_tpu.parallel import (
        init_state,
        make_mesh,
        make_train_step,
    )

    num_chips = max(1, len(jax.devices()))
    runtime.init()
    filenames, dataset_bytes = _get_data()

    peak_gbps = _measure_peak_h2d_gbps()
    print(f"[bench] peak H2D: {peak_gbps:.2f} GB/s", file=sys.stderr)

    feature_columns = [c for c in DATA_SPEC if c != LABEL_COLUMN]
    mesh = make_mesh(model_parallelism=1)
    model = TabularDLRM(
        vocab_sizes={c: DATA_SPEC[c][1] for c in feature_columns},
        embed_dim=EMBED_DIM,
        # Explicit reference interaction: bench must run on any TPU
        # plugin; the Pallas kernel is opt-in until validated on the
        # target runtime (interaction is <1% of bench wall-clock).
        use_pallas_interaction=False,
    )
    optimizer = optax.adam(1e-3)

    import jax.numpy as jnp

    example = {c: jnp.zeros((BATCH_SIZE,), jnp.int32) for c in feature_columns}
    state, shardings = init_state(model, optimizer, mesh, example)
    step_fn = make_train_step(model, optimizer, mesh, shardings)

    # Warm up compilation off the clock — with the warm-up batch placed
    # exactly as real batches arrive (committed, mesh-sharded): input
    # sharding is part of the jit cache key, so an uncommitted warm-up
    # would leave the first timed step to recompile.
    from ray_shuffling_data_loader_tpu.parallel import batch_sharding

    bsh = batch_sharding(mesh, 1)
    example_dev = {k: jax.device_put(v, bsh) for k, v in example.items()}
    labels0 = jax.device_put(jnp.zeros((BATCH_SIZE,), jnp.float32), bsh)
    state, _ = step_fn(state, example_dev, labels0)
    jax.block_until_ready(state.params)

    ds = JaxShufflingDataset(
        filenames,
        num_epochs=NUM_EPOCHS,
        num_trainers=1,
        batch_size=BATCH_SIZE,
        rank=0,
        feature_columns=feature_columns,
        label_column=LABEL_COLUMN,
        num_reducers=NUM_REDUCERS,
        mesh=mesh,
        seed=SEED,
        queue_name="bench-queue",
    )

    # Optional trace (SURVEY §5 tracing): RSDL_PROFILE_DIR=/tmp/trace
    # wraps the measured region in a jax.profiler trace for xprof.
    profile_dir = os.environ.get("RSDL_PROFILE_DIR")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)

    t_start = time.perf_counter()
    step_time = 0.0
    num_steps = 0
    for epoch in range(NUM_EPOCHS):
        ds.set_epoch(epoch)
        for features, label in ds:
            t0 = time.perf_counter()
            state, metrics = step_fn(state, features, label)
            jax.block_until_ready(state.step)
            step_time += time.perf_counter() - t0
            num_steps += 1
    total_s = time.perf_counter() - t_start
    jax.block_until_ready(state.params)
    if profile_dir:
        jax.profiler.stop_trace()

    stats = ds.stats.as_dict()
    staged_gb = stats["bytes_staged"] / 1e9
    # Pipeline throughput: logical dataset bytes moved per epoch, per chip.
    pipeline_gbps = dataset_bytes * NUM_EPOCHS / 1e9 / total_s / num_chips
    stall_pct = 100.0 * stats["stall_s"] / total_s
    target = 0.8 * peak_gbps

    result = {
        "metric": "Shuffle GB/s/chip + trainer stall % on synthetic Parquet",
        "value": round(pipeline_gbps, 4),
        "unit": "GB/s/chip",
        "vs_baseline": round(pipeline_gbps / target, 4) if target else 0.0,
        "stall_pct": round(stall_pct, 2),
        "peak_h2d_gbps": round(peak_gbps, 2),
        "staged_gb": round(staged_gb, 3),
        "steps": num_steps,
        "step_time_s": round(step_time, 2),
        "total_s": round(total_s, 2),
        "loss": round(float(metrics["loss"]), 4),
        "num_chips": num_chips,
        "peak_hbm_gb": round(
            stats.get("peak_device_bytes_in_use", 0) / 1e9, 3
        ),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
