"""Benchmark: per-epoch shuffle -> HBM-staged batches -> real train step.

Measures the north-star metric (BASELINE.json): shuffle+delivery throughput
per chip and trainer stall fraction on the synthetic DATA_SPEC workload,
with the flagship DLRM train step consuming mesh-sharded HBM batches on the
real chip. Prints ONE JSON line:

    {"metric": ..., "value": <GB/s/chip>, "unit": ..., "vs_baseline": ...}

``vs_baseline`` is the achieved fraction of the driver target (0.8 x the
measured peak host->HBM ``device_put`` bandwidth on this chip — BASELINE.md
">=80% of host->HBM staging bandwidth"); >=1.0 means target met. Extra keys
carry stall%, peak bandwidth, phase timings, and peak /dev/shm + HBM
occupancy.

TPU bring-up is hardened (round-1 lesson: the axon plugin's init call can
raise UNAVAILABLE *or hang for minutes*, and one transient error cost the
round its number):

* backend init is **probed in a subprocess** with a hard timeout, retried
  with backoff (``RSDL_BENCH_INIT_ATTEMPTS``/``RSDL_BENCH_INIT_TIMEOUT_S``);
* on exhaustion the bench **fails over to CPU** and still prints a parsed
  JSON line, with ``backend: "cpu"`` and the TPU error recorded in
  ``tpu_error`` — never a bare traceback;
* any later failure prints ``{"metric": ..., "value": 0.0, "error": ...}``.

Workload (reference sweep: 4e8 rows ~64 GB, ``benchmark_batch.sh:9``): a
>=10 GB DATA_SPEC dataset by default (``RSDL_BENCH_GB``), auto-shrunk only
if /dev/shm headroom demands it. Generated Parquet is cached under
``.bench_cache/`` keyed by the workload knobs.

Quick mode (``RSDL_BENCH_QUICK=1``): a <5-minute on-chip capture for short
tunnel windows — ~2 GB dataset, 2 epochs, plus compiled Pallas kernel
microchecks (flash fwd/bwd + dot interaction vs their XLA references)
recorded under ``"kernels"``. Same one-line JSON contract with
``"quick": true``. Rationale: three rounds lost their TPU number to a
tunnel that was never up for the ~30+ min the full bench needs; any >=5
min window must still produce an on-chip artifact.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import tempfile
import threading
import time

# -- workload knobs (fixed so values are comparable across rounds) -----------

# Quick mode: small-but-real workload for short accelerator windows. The
# 2 GB / 2-epoch shape still exercises the full pipeline (resident staging
# amortized over >1 epoch, fused scan, real train steps) in a few minutes.
QUICK = os.environ.get("RSDL_BENCH_QUICK", "") == "1"

BYTES_PER_ROW = 168  # 21 int64/float64 columns (DATA_SPEC)
TARGET_GB = float(os.environ.get("RSDL_BENCH_GB", "2" if QUICK else "10"))
NUM_FILES = int(os.environ.get("RSDL_BENCH_FILES", "16"))
ROW_GROUPS_PER_FILE = 2
BATCH_SIZE = 250_000  # reference benchmark_batch.sh:11
# 10 epochs — the reference sweep's own count (benchmark_batch.sh:12-13).
# Epoch 1 pays cold decode (+ cache publish / resident staging); the rest
# are the steady state the per-epoch metric is meant to capture, and the
# resident loader's one-time staging amortizes exactly as it would in a
# real multi-epoch job.
NUM_EPOCHS = int(os.environ.get("RSDL_BENCH_EPOCHS", "2" if QUICK else "10"))
NUM_REDUCERS = int(os.environ.get("RSDL_BENCH_REDUCERS", "8"))
EMBED_DIM = 32
SEED = 0

CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _mean(xs) -> float:
    return float(sum(xs) / len(xs)) if xs else 0.0


METRIC = "Shuffle GB/s/chip + trainer stall % on synthetic Parquet"


_TARGET_CONTEXTS = ("cpu-failover", "tunneled-tpu", "direct-tpu")


def _target_context(platform: str, strict: bool = True) -> str:
    """Which of the three measurement regimes produced this number, so
    ``vs_baseline`` cannot be misread across rounds (VERDICT r4 item 7):

    * ``cpu-failover`` — TPU unavailable; target is 0.8x the CPU's own
      measured H2D. A portable ratio, NOT evidence against the v5e target.
    * ``tunneled-tpu`` — real chip behind the axon tunnel; peak H2D is
      tunnel-throttled (r2 measured 1.2 GB/s vs real v5e tens of GB/s),
      so vs_baseline is against the tunnel ceiling, not silicon's.
    * ``direct-tpu`` — local TPU runtime; vs_baseline is the real
      BASELINE.md claim.

    ``strict=False`` (the error-JSON path) falls back to the heuristic on
    a bad ``RSDL_BENCH_TARGET_CONTEXT`` instead of raising: the watchdogs
    call this while PRINTING the failure artifact, and a typo'd override
    must never be able to break the error-JSON contract (ADVICE r5).
    main() validates the override up front, so strict failures surface
    before any work runs.
    """
    forced = os.environ.get("RSDL_BENCH_TARGET_CONTEXT")
    if forced:
        # Operator override for deployments the heuristic below misreads
        # (it only knows this box's axon markers). Validated so a typo
        # cannot stamp an unknown regime into the evidence record.
        if forced in _TARGET_CONTEXTS:
            return forced
        if strict:
            raise ValueError(
                f"RSDL_BENCH_TARGET_CONTEXT={forced!r} is not one of "
                f"{_TARGET_CONTEXTS}"
            )
        # Non-strict: ignore the bad override and classify heuristically.
    if platform != "tpu":
        return "cpu-failover"
    # Deliberate ACTIVE tunnel markers only — exact tokens/basenames, not
    # substring scans (a stray "jaxon"/"saxonpy" path must never demote a
    # real direct-TPU capture to the tunnel regime), and not mere
    # existence of ~/.axon_site on disk (ADVICE r5: a tunnel-equipped
    # host running a genuine direct TPU runtime must not be permanently
    # labeled tunnel-throttled). The PYTHONPATH leg catches a relocated
    # axon site dir (the tunnel injects itself via a sitecustomize.py on
    # PYTHONPATH and may set no env markers at all).
    platforms = (os.environ.get("JAX_PLATFORMS") or "").split(",")
    pythonpath = (os.environ.get("PYTHONPATH") or "").split(os.pathsep)
    axon = (
        "axon" in [p.strip().lower() for p in platforms]
        or (os.environ.get("PJRT_DEVICE") or "").strip().lower() == "axon"
        or any(
            os.path.basename(os.path.normpath(e)) == ".axon_site"
            for e in pythonpath
            if e
        )
    )
    return "tunneled-tpu" if axon else "direct-tpu"


def _attach_obs_summaries(result: dict) -> None:
    """End-of-run straggler/skew summary + structured-event counts
    (ISSUE 7), embedded on success AND watchdog/error paths (the PR-4
    telemetry_final convention). Publishes the rsdl_straggler_* gauges
    into the registry FIRST, so the subsequent aggregate() (the
    telemetry_final embed) carries them; the compact dicts ride
    alongside for humans. Pure file reads — safe on error paths."""
    from ray_shuffling_data_loader_tpu.telemetry import metrics as _m

    if not _m.enabled():
        return
    try:
        from ray_shuffling_data_loader_tpu.telemetry import stragglers

        analysis = stragglers.analyze()
        stragglers.publish_metrics(analysis)
        if analysis.get("tasks_total"):
            result["stragglers"] = {
                "tasks_total": analysis["tasks_total"],
                "wedged": len(analysis.get("wedged", [])),
                "flagged": analysis.get("flagged_total", 0),
                "stages": {
                    stage: {
                        "count": st.get("count"),
                        "median_s": st.get("median_s"),
                        "p99_s": st.get("p99_s"),
                        "skew_ratio": st.get("skew_ratio"),
                        "slowest_host": st.get("slowest_host"),
                    }
                    for stage, st in analysis.get("stages", {}).items()
                },
            }
    except Exception:
        pass
    try:
        from ray_shuffling_data_loader_tpu.telemetry import events

        by_kind = events.counts()
        if by_kind:
            result["events"] = by_kind
            for kind, count in by_kind.items():
                # Gauges (recomputed totals), so telemetry_final and a
                # final scrape show rsdl_events_total{kind=...} too.
                _m.registry.gauge("events.total", kind=kind).set(count)
    except Exception:
        pass
    # The decision plane (ISSUE 9): capacity watermarks + fired-alert
    # counts, published as gauges FIRST (same ordering contract as the
    # straggler block) so the aggregate() embed carries rsdl_capacity_*
    # and rsdl_alert_* alongside the compact human dicts.
    try:
        from ray_shuffling_data_loader_tpu.telemetry import capacity

        cap = capacity.view()
        capacity.publish_metrics(cap)
        if cap.get("ops"):
            result["capacity"] = {
                "totals": cap.get("totals"),
                "shm_used_frac": cap.get("shm_used_frac"),
                "hwm_by_epoch": {
                    epoch: {
                        tier: cell.get("hwm_bytes", 0)
                        for tier, cell in tiers.items()
                    }
                    for epoch, tiers in cap.get("epochs", {}).items()
                },
            }
    except Exception:
        pass
    try:
        from ray_shuffling_data_loader_tpu.telemetry import slo

        fired = slo.fired_counts()
        if fired:
            result["alerts_fired"] = fired
    except Exception:
        pass
    # The decode plane (ISSUE 11/12): row-group + pushdown counters
    # from the cluster-wide aggregate (worker decode tasks spool them
    # at task-done), compacted for humans next to telemetry_final. The
    # counters carry {schedule, plan} labels since ISSUE 12, so the
    # summary keeps the totals AND the per-(schedule, plan) breakdown —
    # decode amplification is attributable per run, and an audit-key
    # side sweep never masquerades as data-path decode work.
    try:
        from ray_shuffling_data_loader_tpu.telemetry import (
            export as _export,
        )

        flat = _export.aggregate()

        def _labeled_sum(name):
            total, by_label = _export.labeled_sum(flat, name)
            return int(total), {k: int(v) for k, v in by_label.items()}

        rowgroups, rowgroups_by = _labeled_sum("shuffle.decode_rowgroups")
        rows_pruned, _ = _labeled_sum("shuffle.decode_rows_pruned")
        bytes_pruned, _ = _labeled_sum("shuffle.decode_bytes_pruned")
        decode = {
            "rowgroups": rowgroups,
            # Data-path decode only: the selective plan's audit-key
            # side read is real decode work but not stream decode —
            # the acceptance comparison against the dataset's physical
            # row-group count keys on this figure.
            "rowgroups_data": rowgroups
            - sum(
                v
                for k, v in rowgroups_by.items()
                if "schedule=audit-key" in k
            ),
            "rows_pruned": rows_pruned,
            "bytes_pruned": bytes_pruned,
        }
        if rowgroups_by:
            decode["rowgroups_by"] = rowgroups_by
        if any(
            decode[k] for k in ("rowgroups", "rows_pruned", "bytes_pruned")
        ):
            try:
                import importlib

                _sh = importlib.import_module(
                    "ray_shuffling_data_loader_tpu.shuffle"
                )
                from ray_shuffling_data_loader_tpu.utils import (
                    shuffle_plan_label,
                )

                engaged, reason = _sh.selective_reads_decision()
                decode["plan"] = shuffle_plan_label()
                # The decline is documented, not silent (ISSUE 12):
                # under RSDL_SELECTIVE_READS=auto with a rowwise plan
                # the reason string says the schedule fell back to the
                # materialized path and why.
                decode["selective"] = {
                    "engaged": engaged,
                    "reason": reason,
                }
            except Exception:
                pass
            result["decode"] = decode
    except Exception:
        pass
    # The elastic control plane (ISSUE 10): scale/evict/drain lifetime
    # totals. sys.modules lookup, never an import — the plane only
    # exists when RSDL_ELASTIC brought it up; its elastic.* counters/
    # gauges already ride the registry into telemetry_final, the
    # compact fields land here for humans (success AND error paths).
    try:
        import sys as _sys

        elastic = _sys.modules.get(
            "ray_shuffling_data_loader_tpu.runtime.elastic"
        )
        if elastic is not None:
            summary = elastic.summary()
            if summary:
                result["scale_events"] = summary.get("scale_events", 0)
                result["evicted_gb"] = summary.get("evicted_gb", 0.0)
                result["drains"] = summary.get("drains", 0)
    except Exception:
        pass


def _ledger_append(result: dict) -> None:
    """Append this bench invocation to the durable run ledger (ISSUE
    16, telemetry/runledger.py) and embed the record id in the bench
    JSON (``ledger_record``) so an artifact line and its ledger row
    cross-reference each other. Called on success AND the watchdog/
    error paths — a failed capture is exactly what the next run's
    ``--regress`` comparison needs to see. Check-then-import keeps the
    plane zero-overhead with RSDL_RUN_LEDGER unset; never raises."""
    if not os.environ.get("RSDL_RUN_LEDGER"):
        return
    try:
        from ray_shuffling_data_loader_tpu.telemetry import runledger

        if not runledger.enabled():
            return
        extra = {
            "bench": {
                k: result.get(k)
                for k in ("metric", "value", "unit", "plane",
                          "vs_baseline", "backend", "target_context")
                if result.get(k) is not None
            }
        }
        value = result.get("value")
        unit = str(result.get("unit") or "")
        if isinstance(value, (int, float)) and value and "GB/s" in unit:
            extra["throughput"] = {"bytes_per_s": float(value) * 1e9}
        rec_id = runledger.record_run(
            "failed" if result.get("error") else "done",
            kind="bench",
            error=result.get("error"),
            extra=extra,
        )
        if rec_id:
            result["ledger_record"] = rec_id
    except Exception:
        pass


def _error_result(platform, msg: str) -> dict:
    """The failure shape of the one-JSON-line contract (shared by the
    stall watchdog and main()'s last-resort handler so the contract has
    exactly one definition). When telemetry/audit are on, the artifact
    carries their last-known state: the final LOCAL metrics snapshot (no
    cross-process sources — a wedged actor must not hang the error path)
    and the audit verdicts folded from whatever records reached the
    spool, so a wedged run still reports its counters and digests."""
    result = {
        "metric": METRIC,
        "value": 0.0,
        "unit": "GB/s/chip",
        "vs_baseline": 0.0,
        "backend": platform,
        "target_context": _target_context(platform, strict=False),
        "error": msg[:300],
    }
    if QUICK:
        result["quick"] = True
    try:
        from ray_shuffling_data_loader_tpu.telemetry import export as _e
        from ray_shuffling_data_loader_tpu.telemetry import metrics as _m

        if _m.enabled():
            # Straggler/event summaries FIRST so their gauges land in
            # the aggregate below (success path mirrors this ordering).
            _attach_obs_summaries(result)
            # The CLUSTER view, not the driver-local one: worker/actor
            # registries already spooled at task-done/quiescence, and
            # aggregate() is a pure file read plus the local registry —
            # no RPCs, so a wedged actor cannot hang this error path.
            try:
                result["telemetry_final"] = _e.aggregate()
            except Exception:
                result["telemetry_final"] = _m.registry.snapshot()
    except Exception:
        pass
    try:
        from ray_shuffling_data_loader_tpu.telemetry import audit as _a

        if _a.enabled():
            result["audit"] = _a.summary()
    except Exception:
        pass
    _attach_profile(result)
    return result


def _attach_profile(result: dict) -> None:
    """Embed the cluster-merged sampling-profile digest (ISSUE 17) in
    the bench JSON — success AND error paths, like telemetry_final: the
    profile of a wedged run is the artifact that names where the time
    went. The env check precedes the import so RSDL_PROFILE unset
    stays exactly zero-cost; never raises (one-JSON-line contract)."""
    if not os.environ.get("RSDL_PROFILE"):
        return
    try:
        from ray_shuffling_data_loader_tpu.telemetry import profiler

        digest = profiler.digest()
        if digest:
            result["profile"] = digest
    except Exception:
        pass


# -- hardened backend bring-up ----------------------------------------------


def _probe_backend_once(timeout_s: float):
    """Try ``jax.devices()`` PLUS a bulk-transfer round-trip in a
    THROWAWAY subprocess.

    The axon plugin can hang (not fail) for minutes; probing in-process
    would wedge the bench with no recourse. The probe moves 64 MB H2D and
    reads a scalar back because the control plane can be live while the
    bulk path is dead (observed 2026-07-31: ``jax.devices()`` returned in
    3 s, then a 256 MB ``device_put`` hung forever with ~0 B/s on the
    wire). Returns ``(platform, num_devices, None)`` or
    ``(None, 0, error_string)``.
    """
    code = (
        "import jax, numpy as np; d = jax.devices(); "
        "a = np.ones((64, 1024, 1024), np.uint8); "
        "x = jax.block_until_ready(jax.device_put(a)); "
        "assert int(jax.numpy.max(x)) == 1; "
        "print('RSDL_PROBE', d[0].platform, len(d))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, 0, f"backend init hung >{timeout_s:.0f}s (killed probe)"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()
        return None, 0, tail[-1][:300] if tail else f"rc={proc.returncode}"
    for line in (proc.stdout or "").splitlines():
        if line.startswith("RSDL_PROBE"):
            _, platform, n = line.split()
            return platform, int(n), None
    return None, 0, "probe produced no marker line"


def init_backend():
    """Bring up the JAX backend with retry + CPU failover.

    Returns ``(platform, num_devices, tpu_error)``; ``tpu_error`` is None
    when the accelerator came up, else the last probe failure (and the
    process is pinned to CPU).
    """
    attempts = int(os.environ.get("RSDL_BENCH_INIT_ATTEMPTS", "3"))
    timeout_s = float(os.environ.get("RSDL_BENCH_INIT_TIMEOUT_S", "240"))
    last_err = None
    for attempt in range(attempts):
        t0 = time.perf_counter()
        platform, n, err = _probe_backend_once(timeout_s)
        if err is None:
            _log(
                f"backend up: {platform} x{n} "
                f"(probe {time.perf_counter()-t0:.0f}s, attempt {attempt+1})"
            )
            return platform, n, None
        last_err = err
        _log(f"backend probe failed (attempt {attempt+1}/{attempts}): {err}")
        if attempt + 1 < attempts:
            backoff = min(60.0, 10.0 * (2**attempt))
            _log(f"retrying in {backoff:.0f}s (UNAVAILABLE is often transient)")
            time.sleep(backoff)
    # Failover: a CPU-measured number with the failure recorded beats no
    # number (VERDICT r1 item 1). CPU must be pinned BEFORE importing jax.
    _log(f"TPU backend unavailable after {attempts} attempts; CPU failover")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return "cpu", len(jax.devices()), last_err


# -- workload ----------------------------------------------------------------


def _shm_free_bytes() -> int:
    try:
        st = os.statvfs("/dev/shm")
        return st.f_bavail * st.f_frsize
    except OSError:
        return 1 << 62


def _sized_workload(platform: str, full_size: bool = False):
    """Pick (num_rows, dataset_gb): TARGET_GB unless /dev/shm headroom
    forces smaller. Peak store residency is ~2x dataset (one epoch's map
    partitions + reducer outputs) x up to 2 epochs in flight; require 5x
    so the bench never ENOSPCs mid-epoch.

    CPU runs keep the full TARGET_GB when the train step is mocked
    (``full_size`` — loader-isolation methodology, where the pipeline is
    the thing measured) and shrink to ``RSDL_BENCH_CPU_GB`` (default
    0.1 GB) when a REAL step runs on CPU: a real train step is ~3 orders
    slower without the MXU and 10 GB of real steps would blow any
    reasonable window."""
    target_gb = TARGET_GB
    if platform == "cpu" and not full_size:
        target_gb = min(
            target_gb, float(os.environ.get("RSDL_BENCH_CPU_GB", "0.1"))
        )
    target_bytes = int(target_gb * 1e9)
    headroom = _shm_free_bytes()
    budget = int(headroom / 5)
    scaled = min(target_bytes, budget)
    if scaled < target_bytes:
        _log(
            f"shrinking workload {target_bytes/1e9:.1f} -> {scaled/1e9:.1f} GB"
            f" (/dev/shm free {headroom/1e9:.1f} GB / 5)"
        )
    num_rows = max(BATCH_SIZE, scaled // BYTES_PER_ROW)
    return int(num_rows), scaled < target_bytes


def _get_data(num_rows: int):
    from ray_shuffling_data_loader_tpu.data_generation import (
        cached_generate_data,
    )

    data_dir = os.path.join(
        CACHE_DIR, f"r{num_rows}_f{NUM_FILES}_g{ROW_GROUPS_PER_FILE}_s{SEED}"
    )
    os.makedirs(data_dir, exist_ok=True)
    t0 = time.perf_counter()
    filenames, num_bytes = cached_generate_data(
        num_rows, NUM_FILES, ROW_GROUPS_PER_FILE, data_dir, seed=SEED
    )
    if time.perf_counter() - t0 > 1.0:
        _log(
            f"generated {num_bytes/1e9:.2f} GB in "
            f"{time.perf_counter()-t0:.1f}s"
        )
    return list(filenames), num_bytes


def _measure_peak_h2d_gbps(platform: str, budget_s: float = 300.0) -> float:
    """Peak blocking host->HBM bandwidth via a large pinned-size device_put.

    Runs on a watchdog thread: the tunnel can die BETWEEN the init_backend
    probe and this first in-process transfer (observed 2026-07-31 — probe
    passed at 03:48:54, this device_put then hung >15 min with zero bytes
    on the wire). A hung transfer here would otherwise burn the entire
    capture window before the mid-run stall watchdog is even armed, so on
    timeout we emit the error-JSON contract and exit: the watch loop reads
    an error JSON as "not captured" and retries on the next window.
    """
    import jax
    import numpy as np

    out = []
    err = []

    def _run():
        try:
            arr = np.ones((256, 1024, 1024), dtype=np.uint8)  # 256 MB
            jax.block_until_ready(jax.device_put(arr))  # warm up
            best = 0.0
            for _ in range(3):
                t0 = time.perf_counter()
                jax.block_until_ready(jax.device_put(arr))
                dt = time.perf_counter() - t0
                best = max(best, arr.nbytes / dt)
            out.append(best / 1e9)
        except Exception as exc:  # noqa: BLE001 — recorded in the artifact
            err.append(exc)

    t = threading.Thread(target=_run, name="h2d-probe", daemon=True)
    t.start()
    t.join(budget_s)
    if not out:
        # Crash vs hang matters: a raised error propagates to main()'s
        # last-resort handler (same error-JSON contract, normal cleanup of
        # the already-spawned worker pool); only a still-alive thread is a
        # tunnel wedge, where cleanup could itself hang — that branch
        # hard-exits after printing the artifact.
        if err:
            raise err[0]
        msg = (
            f"H2D probe hung >{budget_s:.0f}s after a healthy backend "
            "probe (tunnel died between bring-up and first transfer)"
            if t.is_alive()
            else "H2D probe thread exited without a result"
        )
        result = _error_result(platform, msg)
        _ledger_append(result)
        print(json.dumps(result), flush=True)
        _export_telemetry_for_exit()
        # Nonzero so rc-keyed tooling (tpu_watch.sh's "rc=$?" log) records
        # the failed capture truthfully; the JSON error field is still the
        # primary signal. os._exit because cleanup may wedge on a dead tunnel.
        os._exit(1)
    return out[0]


def _kernel_microchecks(budget_s: float = 240.0) -> dict:
    """Compiled Pallas kernel correctness proofs on the live backend.

    Runs the same checks as the TPU-gated tests (``tests/test_ops_tpu.py``)
    at microcheck scale: dot-interaction fwd+grad and flash-attention
    fwd+bwd, each compiled (not interpreted) and compared to its XLA
    reference. Each check is individually guarded; the whole batch runs on
    a watchdog thread because a Mosaic compile can HANG, not just raise,
    and a wedged microcheck must not cost the window its bench number.
    """
    out = {}

    def _run_all():
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_shuffling_data_loader_tpu.ops import (
            attention_reference,
            dot_interaction,
            dot_interaction_reference,
        )
        from ray_shuffling_data_loader_tpu.ops.flash_attention import (
            flash_attention,
        )

        rng = np.random.default_rng(0)

        def _check(name, fn):
            t0 = time.perf_counter()
            try:
                err = fn()
                out[name] = {
                    "ok": True,
                    "max_err": float(f"{err:.3e}"),
                    "s": round(time.perf_counter() - t0, 1),
                }
            except Exception as exc:  # noqa: BLE001 — recorded, not fatal
                out[name] = {
                    "ok": False,
                    "error": f"{type(exc).__name__}: {exc}"[:200],
                    "s": round(time.perf_counter() - t0, 1),
                }
            _log(f"kernel microcheck {name}: {out[name]}")

        def _interaction():
            # Ragged batch exercises the padded tail tile; block_batch=256
            # is the VMEM-validated tile for v5e (test_ops_tpu.py).
            x = jnp.asarray(rng.standard_normal((500, 27, 16)), jnp.float32)
            ref = dot_interaction_reference(x)
            got = jax.jit(
                lambda x: dot_interaction(x, use_pallas=True, block_batch=256)
            )(x)
            err = float(jnp.max(jnp.abs(got - ref)))
            assert err < 1e-4, err
            return err

        def _flash_fwd():
            q, k, v = (
                jnp.asarray(rng.standard_normal((2, 256, 4, 32)), jnp.float32)
                for _ in range(3)
            )
            got = jax.jit(
                lambda q, k, v: flash_attention(
                    q, k, v, causal=True, use_pallas=True, interpret=False
                )
            )(q, k, v)
            want = attention_reference(q, k, v, causal=True)
            err = float(jnp.max(jnp.abs(got - want)))
            assert err < 1e-3, err
            return err

        def _flash_bwd():
            q, k, v = (
                jnp.asarray(rng.standard_normal((2, 256, 4, 32)), jnp.float32)
                for _ in range(3)
            )
            g_f = jax.jit(
                jax.grad(
                    lambda q, k, v: jnp.sum(
                        flash_attention(
                            q, k, v, causal=True, use_pallas=True,
                            interpret=False,
                        )
                        ** 2
                    ),
                    (0, 1, 2),
                )
            )(q, k, v)
            g_d = jax.grad(
                lambda q, k, v: jnp.sum(
                    attention_reference(q, k, v, causal=True) ** 2
                ),
                (0, 1, 2),
            )(q, k, v)
            err = max(
                float(jnp.max(jnp.abs(gf - gd))) for gf, gd in zip(g_f, g_d)
            )
            assert err < 1e-2, err
            return err

        _check("interaction", _interaction)
        _check("flash_fwd", _flash_fwd)
        _check("flash_bwd", _flash_bwd)

    t = threading.Thread(target=_run_all, name="kernel-checks", daemon=True)
    t.start()
    t.join(budget_s)
    if t.is_alive():
        # Snapshot: the leaked thread may still mutate `out`, and a dict
        # that changes during the final json.dumps would kill the one
        # JSON line the whole bench exists to print.
        snap = {k: dict(v) if isinstance(v, dict) else v
                for k, v in out.items()}
        snap["hung"] = f">{budget_s:.0f}s (left on watchdog thread)"
        return snap
    return out


# Stop callables for the sampler threads run_bench starts. run_bench pops
# them on its straight-line teardown; main()'s error path pops whatever is
# left BEFORE exporting the trace/metrics artifacts, so an orphaned 1 Hz
# sampler cannot race the export of exactly the failed run whose artifacts
# matter most.
_LIVE_SAMPLERS: list = []


def _stop_live_samplers() -> None:
    # pop-until-empty, not check-then-pop: main's error path and a
    # watchdog thread can drain this list concurrently (both react to the
    # same wedge), and the loser of a check/pop race must exit the loop,
    # not die on IndexError before its export/JSON contract work.
    while True:
        try:
            stop = _LIVE_SAMPLERS.pop()
        except IndexError:
            return
        try:
            stop()
        except Exception:
            pass


# Artifact paths for the watchdogs' hard-exit path, set by main() when
# --trace-out is given: os._exit skips atexit and main()'s export block,
# and the trace of a wedged run is the one artifact that shows WHERE it
# wedged. [trace_out, metrics_out].
_TELEMETRY_EXIT_PATHS: list = [None, None]


def _export_telemetry_for_exit() -> None:
    """Best-effort trace/metrics export before a watchdog os._exit. Never
    touches cross-process metrics sources (the wedged actor could hang
    this very exit) — the trace spool and sampled timeline are local."""
    from ray_shuffling_data_loader_tpu import telemetry
    from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics

    # Uphold stop-before-export on the watchdog paths too (sampler stops
    # join with a timeout, so this cannot wedge the exit). The two
    # artifacts are guarded independently, like main()'s export block —
    # a full/read-only trace volume must not also cost the metrics dump.
    _stop_live_samplers()
    try:
        if telemetry.enabled():
            telemetry.flush()
            if _TELEMETRY_EXIT_PATHS[0]:
                telemetry.trace_export(_TELEMETRY_EXIT_PATHS[0])
    except Exception:
        pass
    try:
        if _metrics.enabled() and _TELEMETRY_EXIT_PATHS[1]:
            _metrics.dump_json(
                _TELEMETRY_EXIT_PATHS[1], include_sources=False
            )
    except Exception:
        pass


class _ShmSampler(threading.Thread):
    """Samples this session's /dev/shm occupancy; reports the peak
    (the reference samples its object store every 5 s via raylet gRPC,
    reference ``stats.py:686-699``)."""

    def __init__(self, store, period_s: float = 0.5):
        super().__init__(name="shm-sampler", daemon=True)
        self._store = store
        self._period = period_s
        # NB: not "_stop" — threading.Thread uses that name internally.
        self._halt = threading.Event()
        self.peak_bytes = 0
        self.peak_spill_bytes = 0

    def run(self):
        while not self._halt.wait(self._period):
            try:
                s = self._store.store_stats()
                # shm residency only — spilled bytes live on disk and are
                # tracked separately (capacity-budget evidence).
                self.peak_bytes = max(
                    self.peak_bytes, s.total_bytes - s.spill_bytes
                )
                self.peak_spill_bytes = max(
                    self.peak_spill_bytes, s.spill_bytes
                )
            except OSError:
                pass

    def stop(self):
        self._halt.set()
        self.join(timeout=2)


# -- main --------------------------------------------------------------------


def run_bench(platform: str, num_chips: int, tpu_error):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.data_generation import (
        DATA_SPEC,
        LABEL_COLUMN,
    )
    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
    from ray_shuffling_data_loader_tpu.models import TabularDLRM
    from ray_shuffling_data_loader_tpu.parallel import (
        batch_sharding,
        init_state,
        make_mesh,
        make_step_body,
        make_train_step,
    )

    num_chips = max(1, num_chips)
    # Pool sizing: one worker per core, floor 2 so shuffle stages overlap
    # the TPU-side train steps even on a 1-core host. Wider pools on small
    # hosts only add spawn latency and context-switch thrash (measured:
    # same steady-state GB/s at 1/2/4 workers on 1 core, but +5s cold
    # start at 4).
    ctx = runtime.init(num_workers=max(2, os.cpu_count() or 1))
    # CPU-failover methodology: mock the train step (the reference's own
    # harness measures the loader this way — --mock-train-step-time,
    # ray_torch_shuffle.py:214) and run the FULL workload. A real DLRM
    # step without an MXU is ~3 orders slower, so r3's real-step CPU
    # number was ~95% CPU matmul time — a liveness check mislabeled as a
    # loader measurement (VERDICT r3 "what's weak" #1). The TPU path
    # keeps the real step; RSDL_BENCH_REAL_STEP=1 forces it on CPU too.
    mock_step_s = None
    env_mock = os.environ.get("RSDL_BENCH_MOCK_STEP_S")
    # Calibrated-step config (VERDICT r5 item 5): measure ONE real
    # compiled step on this backend, then pin the mock step to that
    # duration (x RSDL_BENCH_CALIBRATED_SCALE) — so the stall claim
    # rests on a realistic consumer cadence over many steps instead of
    # 4 real steps at 0.1 GB. Calibration runs after the model is built
    # (below); sizing treats it as loader-isolation (full workload).
    calibrate = os.environ.get("RSDL_BENCH_CALIBRATED") == "1"
    calibrated_from_s = None
    if calibrate and env_mock is not None:
        # An explicit RSDL_BENCH_MOCK_STEP_S (value OR the empty-string
        # real-step opt-out) outranks a lingering calibrate flag — the
        # per-run knob must never be silently overridden.
        _log(
            "RSDL_BENCH_MOCK_STEP_S is set explicitly; ignoring "
            "RSDL_BENCH_CALIBRATED"
        )
        calibrate = False
    if env_mock is not None:
        # Explicitly set: a value mocks at that duration; the empty
        # string is the established real-step opt-out.
        mock_step_s = float(env_mock) if env_mock else None
    elif (
        platform == "cpu"
        and os.environ.get("RSDL_BENCH_REAL_STEP") != "1"
    ):
        mock_step_s = 0.002  # the r3-calibrated loader-isolation step
    num_rows, scaled_down = _sized_workload(
        platform, full_size=calibrate or mock_step_s is not None
    )
    filenames, dataset_bytes = _get_data(num_rows)

    peak_gbps = _measure_peak_h2d_gbps(platform)
    _log(f"peak H2D: {peak_gbps:.2f} GB/s on {platform}")

    # Compiled-kernel proofs, cheap and early: if the tunnel dies mid-run,
    # the (a) H2D probe and (c) kernel results above/below still land in
    # the watchdog's error JSON path via the quick artifact ordering in
    # tools/tpu_watch.sh. CPU runs skip them — the interpret-mode tests
    # already cover CPU, and compiling Mosaic kernels needs the real chip.
    kernels = None
    if platform == "tpu" and os.environ.get("RSDL_BENCH_KERNELCHECKS") != "off":
        kernels = _kernel_microchecks()

    feature_columns = [c for c in DATA_SPEC if c != LABEL_COLUMN]
    mesh = make_mesh(model_parallelism=1)
    optimizer = optax.adam(1e-3)
    example = {c: jnp.zeros((BATCH_SIZE,), jnp.int32) for c in feature_columns}
    bsh = batch_sharding(mesh, 1)
    example_dev = {k: jax.device_put(v, bsh) for k, v in example.items()}
    labels0 = jax.device_put(jnp.zeros((BATCH_SIZE,), jnp.float32), bsh)

    def build_and_warm(use_pallas):
        """Init state, jit the step, and execute one warm-up step — with
        the warm-up batch placed exactly as real batches arrive
        (committed, mesh-sharded): input sharding is part of the jit
        cache key, so an uncommitted warm-up would leave the first timed
        step to recompile. Returns the post-warm-up (state, step_fn)."""
        model = TabularDLRM(
            vocab_sizes={c: DATA_SPEC[c][1] for c in feature_columns},
            embed_dim=EMBED_DIM,
            use_pallas_interaction=use_pallas,
        )
        state, shardings = init_state(model, optimizer, mesh, example)
        step_fn = make_train_step(model, optimizer, mesh, shardings)
        state, _ = step_fn(state, example_dev, labels0)
        jax.block_until_ready(state.params)
        return state, step_fn, make_step_body(model, optimizer)

    if calibrate:
        # Measure the real compiled step, pin the mock to it, drop the
        # model. min-of-3 (not mean): post-warm-up step time is stable
        # and the minimum rejects scheduler noise on a loaded host.
        scale = float(os.environ.get("RSDL_BENCH_CALIBRATED_SCALE", "1"))
        cal_state, cal_step, _ = build_and_warm(False)
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            cal_state, _cal_metrics = cal_step(
                cal_state, example_dev, labels0
            )
            jax.block_until_ready(cal_state.step)
            samples.append(time.perf_counter() - t0)
        calibrated_from_s = min(samples)
        mock_step_s = max(1e-4, calibrated_from_s * scale)
        del cal_state, cal_step
        _log(
            f"calibrated step: measured {calibrated_from_s:.3f}s real "
            f"x scale {scale} -> mock {mock_step_s:.3f}s"
        )

    # Auto: fused Pallas interaction on single-chip TPU, XLA reference
    # elsewhere. A Mosaic/libtpu compile failure must not cost the round
    # its number — and a compile can HANG (wedged remote-compile helper),
    # not just raise — so the pallas build runs on a watchdog thread with
    # a hard deadline; on timeout or error the main process builds the
    # reference-interaction step instead. The thread owns its OWN state
    # (no donation race with the fallback's), and checks the abandoned
    # flag before publishing so a late-completing compile frees its HBM
    # immediately instead of pinning a dead duplicate for the whole run.
    # RSDL_BENCH_PALLAS=off skips the attempt, =on disables the fallback.
    # Loader-isolation mode (reference --mock-train-step-time,
    # ray_torch_shuffle.py:214): the train step is a fixed sleep, so skip
    # model build + compile + warm-up entirely — they would cost ~10 s of
    # startup (CPU backend) to produce a step_fn the loop never calls.
    # mock_step_s decided above (env override, else CPU-failover default).
    pallas_env = os.environ.get("RSDL_BENCH_PALLAS", "auto")
    pallas_mode = "off"
    state = step_fn = step_body = None
    warm_flag = False  # the build_and_warm arg the run settled on
    if mock_step_s is not None:
        pallas_mode = "mocked-step"
    elif pallas_env != "off":
        pallas_mode = "auto"
        budget_s = float(os.environ.get("RSDL_BENCH_PALLAS_TIMEOUT_S", "300"))
        box = {}
        # One mutex serializes publish vs abandon: without it the thread
        # could pass its flag check, get preempted, and publish AFTER the
        # main thread chose the fallback — pinning a dead duplicate state
        # in HBM for the whole run.
        decision = threading.Lock()
        abandoned = threading.Event()

        def _warm_pallas():
            try:
                result = build_and_warm(None)
            except Exception as exc:  # noqa: BLE001 — recorded, fallback
                box["error"] = exc
                return
            with decision:
                if not abandoned.is_set():
                    box["result"] = result
                # else: drop the refs — state/executable free immediately.

        warm_thread = threading.Thread(
            target=_warm_pallas, name="pallas-warm", daemon=True
        )
        warm_thread.start()
        warm_thread.join(budget_s)
        with decision:
            if "result" not in box:
                # A result that landed before this point is used; after
                # the flag no publish can occur.
                abandoned.set()
        if "result" in box:
            state, step_fn, step_body = box["result"]
            warm_flag = None  # auto: the pallas-interaction build
        elif pallas_env == "on":
            raise RuntimeError(
                f"pallas warm-up failed with RSDL_BENCH_PALLAS=on: "
                f"{box.get('error', f'hung >{budget_s:.0f}s')!r}"
            )
        else:
            why = (
                f"{box['error']!r:.2000}"
                if "error" in box
                else f"hung >{budget_s:.0f}s (left on watchdog thread)"
            )
            _log(f"pallas warm-up failed ({why}); reference interaction")
            pallas_mode = "fallback-reference"
    if step_fn is None and mock_step_s is None:
        state, step_fn, step_body = build_and_warm(False)

    # Loader choice: the device-resident shuffle (epoch permutation +
    # gather in HBM, one staging pass total — resident.py) when the packed
    # dataset fits the device budget, else the general host map/reduce
    # pipeline. RSDL_BENCH_RESIDENT=on|off|auto overrides.
    from ray_shuffling_data_loader_tpu import resident as resident_mod

    resident_env = os.environ.get("RSDL_BENCH_RESIDENT", "auto")
    if resident_env == "on":
        use_resident = True
    elif resident_env == "off":
        use_resident = False
    else:
        # The bench is SPMD on pods (every process runs this same line),
        # so pod-consistent auto-selection is safe: resident engages on
        # the target topology when every host's budget agrees.
        use_resident = resident_mod.fits_device(
            filenames,
            len(feature_columns),
            mesh=mesh,
            num_rows=num_rows,
            pod_consistent=True,
        )
    _log(f"loader: {'device-resident' if use_resident else 'map/reduce'}")

    from ray_shuffling_data_loader_tpu.stats import TrialStatsCollector

    # Both loaders report through the same collector vocabulary; the
    # resident loader maps its stages onto it (map = epoch permutation,
    # reduce = epoch materialization/gather, consume = batch delivery),
    # with one map and one reduce per epoch.
    collector = runtime.spawn_actor(
        TrialStatsCollector,
        NUM_EPOCHS,
        len(filenames) if not use_resident else 1,
        NUM_REDUCERS if not use_resident else 1,
        num_rows,
        BATCH_SIZE,
        1,
        name="bench-stats",
    )

    def make_dataset(resident_now=None):
        if resident_now is None:
            resident_now = use_resident
        if resident_now:
            if os.environ.get("RSDL_BENCH_FAULT") == "resident":
                # Test hook: the resident->map/reduce failover must be
                # exercisable without a backend that actually breaks.
                raise RuntimeError("injected resident fault")
            return resident_mod.DeviceResidentShufflingDataset(
                filenames,
                num_epochs=NUM_EPOCHS,
                batch_size=BATCH_SIZE,
                feature_columns=feature_columns,
                label_column=LABEL_COLUMN,
                mesh=mesh,
                seed=SEED,
                num_rows=num_rows,
                # The one-time staging pass can exceed the per-batch
                # stall timeout on a slow host; every staged piece is
                # liveness progress for the watchdog.
                progress_cb=lambda: last_progress.__setitem__(
                    0, time.monotonic()
                ),
                stats_collector=collector,
            )
        return JaxShufflingDataset(
            filenames,
            num_epochs=NUM_EPOCHS,
            num_trainers=1,
            batch_size=BATCH_SIZE,
            rank=0,
            feature_columns=feature_columns,
            label_column=LABEL_COLUMN,
            num_reducers=NUM_REDUCERS,
            mesh=mesh,
            seed=SEED,
            queue_name=f"bench-queue-{int(time.time() * 1000) % 10 ** 9}",
            stats_collector=collector,
        )

    sampler = _ShmSampler(ctx.store)
    sampler.start()
    _LIVE_SAMPLERS.append(sampler.stop)

    # Live-metrics sampler (telemetry): only when the metrics half is on
    # (bench --trace-out / RSDL_METRICS=1). Feeds the batch-queue depth
    # source + store gauges into the sampled timeline that
    # telemetry.metrics.dump_json() writes next to the trace artifact.
    from ray_shuffling_data_loader_tpu.stats import ObjectStoreStatsCollector
    from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics

    metrics_sampler = None
    if _metrics.enabled():
        metrics_sampler = ObjectStoreStatsCollector(
            collector, sample_period_s=1.0
        )
        metrics_sampler.__enter__()
        _LIVE_SAMPLERS.append(
            lambda: metrics_sampler.__exit__(None, None, None)
        )

    # Optional trace (SURVEY §5 tracing): RSDL_BENCH_XPROF_DIR=/tmp/trace
    # wraps the measured region in a jax.profiler trace for xprof.
    # (RSDL_PROFILE_DIR now names the sampling-profiler spool override —
    # ISSUE 17 — a different artifact entirely.)
    profile_dir = os.environ.get("RSDL_BENCH_XPROF_DIR")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)

    # Mid-run stall watchdog: the accelerator tunnel can wedge AFTER
    # bring-up (observed: device_put/step hang indefinitely mid-session).
    # A hung bench loses the round's number entirely — the watchdog
    # prints a machine-readable error JSON and exits instead. The
    # timeout is per-batch progress, sized to survive a full cold epoch
    # gap on a slow host.
    stall_timeout_s = float(
        os.environ.get("RSDL_BENCH_STALL_TIMEOUT_S", "900")
    )
    # <= 0 disables the watchdog (the conventional env-knob off switch).
    watchdog_enabled = math.isfinite(stall_timeout_s) and stall_timeout_s > 0
    last_progress = [time.monotonic()]

    check_s = min(30.0, max(1.0, stall_timeout_s / 4))

    def _stall_watchdog():
        while True:
            time.sleep(check_s)
            idle = time.monotonic() - last_progress[0]
            if idle > stall_timeout_s:
                result = _error_result(
                    platform,
                    f"no batch progress for {idle:.0f}s "
                    "(accelerator wedged mid-run?); watchdog exit",
                )
                if tpu_error is not None:
                    result["tpu_error"] = str(tpu_error)[:300]
                _ledger_append(result)
                print(json.dumps(result), flush=True)
                if profile_dir:
                    # The trace of the wedged run is the one artifact
                    # that shows WHERE it wedged; flush it if possible.
                    try:
                        jax.profiler.stop_trace()
                    except Exception:
                        pass
                _export_telemetry_for_exit()
                # Nonzero rc: same contract as the H2D-probe watchdog —
                # rc-keyed tooling must record the failed capture
                # truthfully (the JSON error field stays the primary
                # signal for bench_ok()-style consumers).
                os._exit(1)

    if watchdog_enabled:
        threading.Thread(
            target=_stall_watchdog, name="stall-watchdog", daemon=True
        ).start()

    resident_error = None
    run_ds = {}  # the current attempt's dataset, for failover cleanup

    def timed_run(resident_now):
        nonlocal state, metrics, step_time, num_steps
        t0_run = time.perf_counter()
        # Constructed INSIDE the timed window: the resident loader's
        # one-time decode+stage pass is part of the pipeline cost the
        # metric reports (the map/reduce loader's constructor is cheap —
        # its shuffle work already overlaps the timed loop).
        ds = make_dataset(resident_now)
        run_ds["ds"] = ds
        step_time = 0.0
        num_steps = 0
        # Fusion gate (r4 root-cause of the r3 "8-device compile wedge"):
        # compile is fine multi-device (~70 s at full DLRM scale); the
        # wedge was at RUN time — XLA-CPU executes virtual devices as
        # threads on shared cores, and the fused program's per-step
        # collectives starve the collective rendezvous ("Expected 8
        # threads to join..." stalls, observed r4) while 8x-replicated
        # big-model compute serializes onto one core. Both are virtual-
        # mesh artifacts, so fusion engages on any REAL accelerator
        # topology and on single-device CPU, and is declined only on
        # multi-device CPU meshes — with the reason logged.
        fused = (
            resident_now
            and mock_step_s is None
            and (jax.device_count() == 1 or platform != "cpu")
            and os.environ.get("RSDL_BENCH_FUSED", "on") != "off"
        )
        if (
            resident_now
            and mock_step_s is None
            and not fused
            and os.environ.get("RSDL_BENCH_FUSED", "on") != "off"
        ):
            _log(
                "epoch fusion declined: multi-device CPU mesh (virtual "
                "devices share host cores; XLA-CPU collective rendezvous "
                "starves under load — see resident.make_fused_epoch)"
            )
        if fused:
            # Epoch fusion: the dataset is HBM-resident, so the entire
            # epoch (batch slice + unpack + train step) runs as ONE
            # jitted lax.scan — one dispatch per epoch instead of one+
            # host round-trips per batch, the delivery cost that
            # dominates on high-latency links (resident.make_fused_epoch).
            # The scanned module is much bigger than the per-batch step
            # build_and_warm probed, so a compile-time rejection here is
            # plausible on experimental toolchains — degrade to the
            # per-batch RESIDENT loop below, not all the way to
            # map/reduce.
            try:
                run_epoch = resident_mod.make_fused_epoch(
                    ds, step_body, donate_state=False
                )
                per_epoch = ds._rank_rows // BATCH_SIZE
                epoch_bytes = (
                    (len(feature_columns) + 1) * 4 * per_epoch * BATCH_SIZE
                )
                for epoch in range(NUM_EPOCHS):
                    t0 = time.perf_counter()
                    if epoch == 0:
                        # The first fused call compiles the whole scanned
                        # step; grant the stall watchdog one compile's
                        # worth of extra budget (a future "last progress"
                        # = more headroom) without disarming wedge
                        # detection.
                        last_progress[0] = time.monotonic() + 900
                    collector.call_oneway("epoch_start", epoch)
                    collector.call_oneway("map_start", epoch)
                    collector.call_oneway("map_done", epoch, 0.0, 0.0)
                    collector.call_oneway("reduce_start", epoch)
                    state, losses = run_epoch(state, epoch)
                    jax.block_until_ready(losses)
                    dur = time.perf_counter() - t0
                    collector.call_oneway("reduce_done", epoch, dur)
                    collector.call_oneway("consume", 0, epoch, epoch_bytes)
                    metrics = {"loss": losses[-1]}
                    step_time += dur
                    num_steps += per_epoch
                    last_progress[0] = time.monotonic()
                return time.perf_counter() - t0_run, ds
            except Exception:
                _log(
                    "fused epoch failed; degrading to the per-batch "
                    "resident loop"
                )
                import traceback

                traceback.print_exc(file=sys.stderr)
                step_time = 0.0
                num_steps = 0
                last_progress[0] = time.monotonic()
        for epoch in range(NUM_EPOCHS):
            ds.set_epoch(epoch)
            for features, label in ds:
                t0 = time.perf_counter()
                if mock_step_s is not None:
                    time.sleep(mock_step_s)
                else:
                    state, metrics = step_fn(state, features, label)
                    jax.block_until_ready(state.step)
                step_time += time.perf_counter() - t0
                num_steps += 1
                last_progress[0] = time.monotonic()
        return time.perf_counter() - t0_run, ds

    step_time = 0.0
    num_steps = 0
    metrics = {"loss": float("nan")}
    try:
        total_s, ds = timed_run(use_resident)
    except Exception as exc:  # noqa: BLE001 — fall back, don't sink the run
        if not use_resident:
            raise
        # The resident path auto-selected but failed on this backend (it
        # has corners only a real chip exercises). The bench's contract
        # is a perf number: restart the timed window on the map/reduce
        # loader and record WHY.
        resident_error = f"{type(exc).__name__}: {exc}"
        _log(f"resident loader failed ({resident_error}); "
             "re-running on the map/reduce loader")
        # Release the failed attempt's staged HBM buffers before the
        # rerun competes for device memory (the OOM-on-mis-admission
        # case is exactly why this failover exists).
        failed = run_ds.pop("ds", None)
        if failed is not None:
            try:
                failed.close()
            except Exception:
                pass
        # A fresh collector sized for the map/reduce stage counts — the
        # resident-sized one (1 map/1 reduce per epoch) would latch the
        # fallback's stage windows after the first task and mix in the
        # failed attempt's partial events.
        collector = runtime.spawn_actor(
            TrialStatsCollector,
            NUM_EPOCHS,
            len(filenames),
            NUM_REDUCERS,
            num_rows,
            BATCH_SIZE,
            1,
            name="bench-stats-fallback",
        )
        if metrics_sampler is not None:
            # The 1 Hz metrics sampler captured the ORIGINAL collector
            # handle; re-point it so the failover run — exactly the one
            # whose live-metrics series is diagnostically interesting —
            # doesn't forward samples to the abandoned actor.
            metrics_sampler.set_collector(collector)
        use_resident = False
        # Fresh model/optimizer state: the failed resident attempt already
        # trained on some batches (donate_state=False keeps its state
        # object alive), so reusing it would report a fallback loss
        # trajectory that is not from a clean start. The re-jit hits the
        # compile cache; only init + one warm step is repaid.
        if mock_step_s is None:
            state, step_fn, step_body = build_and_warm(warm_flag)
        last_progress[0] = time.monotonic()
        total_s, ds = timed_run(False)
    # Finalization below (device sync, profiler stop, stats snapshot) can
    # wedge exactly like the loop can, so the watchdog stays armed; it
    # cannot double-print because it os._exit()s right after its line.
    last_progress[0] = time.monotonic()
    if state is not None:
        jax.block_until_ready(state.params)
    if profile_dir:
        jax.profiler.stop_trace()
    _stop_live_samplers()

    stats = ds.stats.as_dict()
    staged_gb = stats["bytes_staged"] / 1e9
    staged_direct_gb = stats.get("bytes_staged_direct", 0) / 1e9
    # Per-stage shuffle timings (diagnosability of the headline number):
    # wall-clock stage windows and mean task durations per epoch.
    phase = {}
    try:
        # Resident runs report permutation/materialization through the
        # same map/reduce event names, so this covers both loaders.
        epochs = collector.call("snapshot").epochs
        if epochs:
            phase = {
                "map_stage_s": round(
                    sum(e.map_stage_duration or 0.0 for e in epochs), 2
                ),
                "reduce_stage_s": round(
                    sum(e.reduce_stage_duration or 0.0 for e in epochs), 2
                ),
                "map_task_avg_s": round(_mean(
                    [d for e in epochs for d in e.map_durations]
                ), 3),
                "reduce_task_avg_s": round(_mean(
                    [d for e in epochs for d in e.reduce_durations]
                ), 3),
                "throttle_s": round(
                    sum(e.throttle_duration or 0.0 for e in epochs), 2
                ),
            }
    except Exception as exc:  # diagnostics must never sink the number
        _log(f"stage-stats snapshot failed: {exc!r:.200}")
    # Pipeline throughput: logical dataset bytes moved per epoch, per chip.
    pipeline_gbps = dataset_bytes * NUM_EPOCHS / 1e9 / total_s / num_chips
    stall_pct = 100.0 * stats["stall_s"] / total_s
    target = 0.8 * peak_gbps

    result = {
        "metric": METRIC,
        "value": round(pipeline_gbps, 4),
        "unit": "GB/s/chip",
        "vs_baseline": round(pipeline_gbps / target, 4) if target else 0.0,
        "stall_pct": round(stall_pct, 2),
        # Attribution (VERDICT r4 item 2): upstream = consumer waited while
        # the loader had no host batch (epoch window closed / shuffle still
        # producing); staging = host batch existed, H2D pipeline was behind.
        # Cross-check against throttle_s (driver-side window-gating time).
        "stall_upstream_pct": round(
            100.0 * stats.get("stall_upstream_s", 0.0) / total_s, 2
        ),
        "stall_staging_pct": round(
            100.0 * stats.get("stall_staging_s", 0.0) / total_s, 2
        ),
        "peak_h2d_gbps": round(peak_gbps, 2),
        "dataset_gb": round(dataset_bytes / 1e9, 3),
        "scaled_down": scaled_down,
        # staged_gb counts HOST-COPIED staging bytes (the rebatch+pack
        # amplification ISSUE 8 kills); staged_direct_gb counts bytes
        # device_put shipped straight off mmapped packed segments with
        # no host copy. Their sum is total H2D traffic. device_direct
        # records whether the path actually ENGAGED (at least one batch
        # shipped direct), not merely whether the env requested it — a
        # non-engaging run must not read as "optimization was on".
        "staged_gb": round(staged_gb, 3),
        "staged_direct_gb": round(staged_direct_gb, 3),
        "batches_staged_direct": int(
            stats.get("batches_staged_direct", 0)
        ),
        "device_direct": stats.get("batches_staged_direct", 0) > 0,
        "steps": num_steps,
        "step_time_s": round(step_time, 2),
        "total_s": round(total_s, 2),
        # None (-> JSON null) when no real step ran: json.dumps would
        # otherwise emit the literal NaN, which strict parsers reject.
        "loss": (
            round(float(metrics["loss"]), 4)
            if math.isfinite(float(metrics["loss"]))
            else None
        ),
        "num_chips": num_chips,
        "host_cpus": os.cpu_count(),
        "backend": platform,
        "target_context": _target_context(platform),
        "step": (
            f"calibrated-{mock_step_s:.3f}s"
            if calibrated_from_s is not None
            else f"mock-{mock_step_s}s"
            if mock_step_s is not None
            else "real"
        ),
        **(
            {"calibrated_from_s": round(calibrated_from_s, 4)}
            if calibrated_from_s is not None
            else {}
        ),
        "loader": "resident" if use_resident else "mapreduce",
        **({"resident_error": resident_error[:300]} if resident_error else {}),
        "pallas": pallas_mode,
        # Resident loader: the one-time decode+pack+H2D staging pass;
        # map/reduce loader: time to the first delivered batch.
        "first_batch_s": round(stats.get("first_batch_s", 0.0), 2),
        "peak_hbm_gb": round(
            stats.get("peak_device_bytes_in_use", 0) / 1e9, 3
        ),
        "peak_shm_gb": round(sampler.peak_bytes / 1e9, 3),
        "peak_spill_gb": round(sampler.peak_spill_bytes / 1e9, 3),
        **phase,
    }
    if QUICK:
        result["quick"] = True
    if kernels is not None:
        result["kernels"] = kernels
    if tpu_error is not None:
        result["tpu_error"] = str(tpu_error)[:300]
    # Disarm only now: everything after this is pure host-side printing.
    last_progress[0] = float("inf")
    return result


# -- TCP-plane bench (two-process loopback "two hosts") ----------------------
#
# The DCN stand-in measurement the r5 VERDICT flagged as missing (#2): the
# reference's cross-host plane (plasma + gRPC) ran on 4-node deployments;
# this repo's StoreServer windowed fetch had no GB/s, latency, or
# protocol-overhead number at all. `bench.py --plane tcp` starts a cluster
# head on 127.0.0.1, joins ONE worker host in a subprocess with its own
# shm dir (so nothing short-circuits through a shared /dev/shm), and then:
#
#   (a) windowed-fetch microbench — a publisher actor ON THE WORKER HOST
#       publishes hardlinked row-window segments; the driver pulls every
#       window over TCP through the real remote-fetch path, once with the
#       legacy pickle framing and once with the zero-copy vectored plane
#       (RSDL_TCP_ZEROCOPY), against a local-shm read of the same shape
#       and a raw loopback-socket ceiling;
#   (b) a mini end-to-end shuffle with locality DISABLED, so map/reduce
#       tasks scatter across both hosts and reducers/trainers pull their
#       inputs over TCP — with the audit plane on, proving exactly-once
#       delivery over the new transport path (`audit.ok`).


class _TcpPublisher:
    """Actor placed on the WORKER host: publishes window segments into
    that host's store so the driver's fetches must cross TCP."""

    def publish(self, num_windows: int, window_bytes: int):
        import numpy as np

        from ray_shuffling_data_loader_tpu import runtime

        ctx = runtime.ensure_initialized()
        rows_per = max(1, window_bytes // 16)  # two 8-byte columns
        total = rows_per * num_windows
        pending = ctx.store.create_columns(
            {
                "a": ((total,), np.dtype(np.int64)),
                "b": ((total,), np.dtype(np.float64)),
            }
        )
        try:
            pending.columns["a"][:] = np.arange(total, dtype=np.int64)
            pending.columns["b"][:] = 0.5
            refs = pending.publish_slices(
                [
                    (i * rows_per, (i + 1) * rows_per)
                    for i in range(num_windows)
                ]
            )
        finally:
            pending.abort()
        return refs

    def free(self, refs):
        from ray_shuffling_data_loader_tpu import runtime

        runtime.ensure_initialized().store.free(list(refs))


def _publisher_cls():
    """The publisher class via the importable `bench` module (pickle by
    reference must resolve on the worker host's agent, where __main__ is
    the actor bootstrap, not this script)."""
    try:
        import bench as _self  # noqa: PLW0406 — self-import on purpose

        return _self._TcpPublisher
    except ImportError:
        return _TcpPublisher


_TCP_WORKER_SRC = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.runtime import cluster
ctx = runtime.init(address={address!r}, num_workers=2)
print("[tcp-bench-worker] joined", ctx.cluster.host_id, flush=True)
cluster.serve_forever()
runtime.shutdown()
"""


def _raw_loopback_gbps(nbytes: int = 256 << 20) -> float:
    """Throughput of a plain sendall/recv_into stream over one loopback
    TCP connection — the kernel-path ceiling any framing overhead is
    measured against."""
    import socket

    server = socket.create_server(("127.0.0.1", 0))
    port = server.getsockname()[1]
    chunk = bytearray(4 << 20)

    def _sink():
        conn, _ = server.accept()
        with conn:
            buf = memoryview(bytearray(8 << 20))
            got = 0
            while got < nbytes:
                n = conn.recv_into(buf)
                if not n:
                    break
                got += n

    t = threading.Thread(target=_sink, daemon=True)
    t.start()
    out = socket.create_connection(("127.0.0.1", port))
    out.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    t0 = time.perf_counter()
    sent = 0
    while sent < nbytes:
        out.sendall(chunk)
        sent += len(chunk)
    out.close()
    t.join(30)
    server.close()
    return sent / 1e9 / max(1e-9, time.perf_counter() - t0)


def _lat_stats(lat_s) -> dict:
    lat_ms = sorted(1e3 * x for x in lat_s)
    n = len(lat_ms)
    return {
        "mean": round(sum(lat_ms) / n, 3),
        "p50": round(lat_ms[n // 2], 3),
        "min": round(lat_ms[0], 3),
        "max": round(lat_ms[-1], 3),
    }


def run_tcp_plane_bench() -> dict:
    import tempfile as _tempfile

    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.runtime import transport
    from ray_shuffling_data_loader_tpu.telemetry import audit as _audit
    from ray_shuffling_data_loader_tpu.telemetry import metrics as _m

    windows = int(os.environ.get("RSDL_BENCH_TCP_WINDOWS", "64"))
    window_mb = float(os.environ.get("RSDL_BENCH_TCP_WINDOW_MB", "4"))
    window_bytes = int(window_mb * 1e6)
    shuffle_gb = float(os.environ.get("RSDL_BENCH_TCP_SHUFFLE_GB", "0.2"))

    # Arm metrics + audit BEFORE the cluster comes up: worker-host agents
    # fix their env at spawn, and the mini shuffle's exactly-once verdict
    # needs every remote task folding digests.
    _m.enable()
    audit_dir = _tempfile.mkdtemp(prefix="rsdl-tcpbench-audit-")
    _audit.enable(spool_dir=audit_dir)
    # The mini shuffle must SCATTER (locality would keep reduces next to
    # their inputs and off the wire — the opposite of what this bench
    # exists to measure).
    os.environ["RSDL_DISABLE_LOCALITY"] = "1"
    # Telemetry federation (ISSUE 19) rides this leg by default: the
    # worker host joins with its OWN runtime dir, so without the relay
    # the driver-side telemetry_final/audit would silently lose every
    # remote record. setdefault so RSDL_RELAY=off A/Bs the overhead.
    os.environ.setdefault("RSDL_RELAY", "auto")
    # Worker-host processes fix their env at spawn: arm the zero-copy
    # plane cluster-wide NOW so the shuffle leg's remote reducers ride
    # it; the windowed-fetch microbench below toggles the DRIVER's gate
    # per plane (the client side chooses the framing). Striping
    # (RSDL_TCP_STREAMS) rides the same spawn-time env so the shuffle
    # leg's worker-side fetches stripe too.
    os.environ["RSDL_TCP_ZEROCOPY"] = "1"
    # Default 2: stream count should track cores devoted to recv — on
    # this 2-core host more streams just oversubscribe (BENCHLOG r7).
    # Clamped to the transport's own [1, 16] range so the JSON records
    # the stream count that actually ran (an uncapped env value would be
    # silently re-clamped inside transport.tcp_streams()).
    streams = min(
        16, max(1, int(os.environ.get("RSDL_BENCH_TCP_STREAMS", "2")))
    )
    os.environ["RSDL_TCP_STREAMS"] = str(streams)

    worker_shm = _tempfile.mkdtemp(prefix="rsdl-tcpbench-shm-")
    worker_spill = _tempfile.mkdtemp(prefix="rsdl-tcpbench-spill-")
    ctx = runtime.init_cluster(
        listen_host="127.0.0.1",
        advertise_host="127.0.0.1",
        num_workers=2,
    )
    worker_env = dict(
        os.environ,
        RSDL_SHM_DIR=worker_shm,
        RSDL_SPILL_DIR=worker_spill,
        RSDL_ADVERTISE_HOST="127.0.0.1",
        JAX_PLATFORMS="cpu",
    )
    worker = subprocess.Popen(
        [
            sys.executable,
            "-c",
            _TCP_WORKER_SRC.format(
                repo=os.path.dirname(os.path.abspath(__file__)),
                address=ctx.cluster.address,
            ),
        ],
        env=worker_env,
    )
    result = {
        "metric": (
            "Cross-host TCP plane: StoreServer windowed fetch GB/s + "
            "two-host shuffle (loopback stand-in for DCN)"
        ),
        "plane": "tcp",
        "unit": "GB/s",
        "backend": "cpu",
        "host_cpus": os.cpu_count(),
        "windows": windows,
        "window_mb": window_mb,
    }

    def _embed_final(res: dict) -> None:
        """Federated final counters + relay status — success AND error
        paths, and BEFORE the finally below tears the session down
        (shutdown removes the spool tree the relayed records live in).
        Never raises (one-JSON-line contract)."""
        if _m.enabled():
            try:
                from ray_shuffling_data_loader_tpu.telemetry import (
                    export as _export,
                )

                res["telemetry_final"] = _export.aggregate()
                res["telemetry_source_hosts"] = sorted(
                    {
                        str((rec.get("source") or {}).get("host"))
                        for rec in _export.load_records()
                    }
                )
            except Exception:
                pass
        _relay = sys.modules.get(
            "ray_shuffling_data_loader_tpu.telemetry.relay"
        )
        if _relay is not None:
            try:
                res["relay"] = {
                    "mode": os.environ.get("RSDL_RELAY", ""),
                    "status": _relay.status_section(),
                }
            except Exception:
                pass

    try:
        deadline = time.monotonic() + 120
        while len(ctx.cluster.registry.call("hosts")) < 2:
            if worker.poll() is not None:
                raise RuntimeError(
                    f"worker host exited rc={worker.returncode}"
                )
            if time.monotonic() > deadline:
                raise RuntimeError("worker host never joined")
            time.sleep(0.2)
        worker_host_id = next(
            hid
            for hid in ctx.cluster.registry.call("hosts")
            if hid != ctx.cluster.host_id
        )
        pub = runtime.spawn_actor(
            _publisher_cls(), host_id=worker_host_id
        )
        refs = pub.call("publish", windows, window_bytes)
        store = ctx.store
        total_bytes = sum(
            16 * (r.rows[1] - r.rows[0]) for r in refs
        )

        def _timed_tcp_fetch():
            lat = []
            t0 = time.perf_counter()
            for ref in refs:
                s = time.perf_counter()
                cb = store.get_columns(ref)
                assert cb.num_rows > 0
                del cb
                lat.append(time.perf_counter() - s)
            dt = time.perf_counter() - t0
            # Drop the fetched caches OUTSIDE the timed window so the
            # next plane re-fetches over the wire.
            store.drop_cache(refs)
            return total_bytes / 1e9 / dt, lat

        # Plane 1: legacy pickle framing.
        os.environ.pop("RSDL_TCP_ZEROCOPY", None)
        transport.refresh_zerocopy_from_env()
        pickle_gbps, pickle_lat = _timed_tcp_fetch()
        # Plane 2: zero-copy vectored framing, single stream.
        os.environ["RSDL_TCP_ZEROCOPY"] = "1"
        os.environ["RSDL_TCP_STREAMS"] = "1"
        transport.refresh_zerocopy_from_env()
        transport.refresh_tcp_streams_from_env()
        zc_gbps, zc_lat = _timed_tcp_fetch()
        # Plane 3: zero-copy striped over RSDL_TCP_STREAMS persistent
        # connections — each window's payload split by byte range with
        # parallel recv_into disjoint regions of one mmapped cache file
        # (the single-stream framing + single-core recv gap, ROADMAP 2).
        os.environ["RSDL_TCP_STREAMS"] = str(streams)
        transport.refresh_tcp_streams_from_env()
        striped_gbps, striped_lat = _timed_tcp_fetch()

        def _timed_pipelined_fetch(depth: int = 8):
            """Windowed fetch the way the reduce plane actually runs it:
            ``store.prefetch`` keeps ``depth`` windows in flight, so
            per-window costs (cache-file lifecycle, recv, server send)
            overlap across windows instead of serializing — the
            DELIVERED fetch bandwidth, vs the serial loop's per-window
            latency view."""
            t0 = time.perf_counter()
            futs = store.prefetch(refs, max_parallel=depth)
            if not futs:  # nothing was foreign/uncached: no real measure
                return None
            for f in futs:
                f.result()
            dt = time.perf_counter() - t0
            missing = [r for r in refs if store._find_cache(r) is None]
            store.drop_cache(refs)
            if missing:  # a swallowed prefetch failure: don't fake a number
                return None
            return total_bytes / 1e9 / dt

        # Pipelined rows, both framings (same windows, prefetch depth 8).
        os.environ["RSDL_TCP_STREAMS"] = "1"
        transport.refresh_tcp_streams_from_env()
        zc_pipe_gbps = _timed_pipelined_fetch()
        os.environ["RSDL_TCP_STREAMS"] = str(streams)
        transport.refresh_tcp_streams_from_env()
        striped_pipe_gbps = _timed_pipelined_fetch()

        # Baseline: the same windows living in LOCAL shm, reading every
        # byte (the mmap is lazy; the sum forces the pages).
        import numpy as np

        rows_per = max(1, window_bytes // 16)
        local_pending = store.create_columns(
            {
                "a": ((rows_per * windows,), np.dtype(np.int64)),
                "b": ((rows_per * windows,), np.dtype(np.float64)),
            }
        )
        local_pending.columns["a"][:] = 1
        local_pending.columns["b"][:] = 0.5
        local_refs = local_pending.publish_slices(
            [(i * rows_per, (i + 1) * rows_per) for i in range(windows)]
        )
        local_pending.abort()
        del local_pending
        shm_lat = []
        t0 = time.perf_counter()
        for ref in local_refs:
            s = time.perf_counter()
            cb = store.get_columns(ref)
            for col in cb.columns.values():
                col.sum()
            del cb
            shm_lat.append(time.perf_counter() - s)
        shm_gbps = total_bytes / 1e9 / (time.perf_counter() - t0)
        store.free(local_refs)
        pub.call("free", refs)

        raw_gbps = _raw_loopback_gbps()
        # HMAC challenge-response cost: full authed TCP connection setup
        # to the worker's store server, amortized per connection.
        store_addr = tuple(
            ctx.cluster.registry.call("hosts")[worker_host_id]["store"]
        )
        t0 = time.perf_counter()
        n_conn = 20
        for _ in range(n_conn):
            conn = transport.Connection(store_addr, timeout=10.0)
            conn.close()
        hmac_ms = 1e3 * (time.perf_counter() - t0) / n_conn

        result["fetch"] = {
            "total_gb": round(total_bytes / 1e9, 3),
            "shm_gbps": round(shm_gbps, 3),
            "tcp_pickle_gbps": round(pickle_gbps, 3),
            "tcp_zerocopy_gbps": round(zc_gbps, 3),
            "tcp_zerocopy_striped_gbps": round(striped_gbps, 3),
            "tcp_zerocopy_pipelined_gbps": (
                round(zc_pipe_gbps, 3) if zc_pipe_gbps else None
            ),
            "tcp_zerocopy_striped_pipelined_gbps": (
                round(striped_pipe_gbps, 3) if striped_pipe_gbps else None
            ),
            "tcp_streams": streams,
            "raw_loopback_gbps": round(raw_gbps, 3),
            "window_ms": {
                "shm": _lat_stats(shm_lat),
                "tcp_pickle": _lat_stats(pickle_lat),
                "tcp_zerocopy": _lat_stats(zc_lat),
                "tcp_zerocopy_striped": _lat_stats(striped_lat),
            },
            "hmac_handshake_ms": round(hmac_ms, 3),
            # Framing+pickle+copy overhead vs the raw socket ceiling,
            # per plane (what fraction of achievable loopback bandwidth
            # the protocol costs).
            "overhead_vs_raw_pct": {
                "tcp_pickle": round(100 * (1 - pickle_gbps / raw_gbps), 1),
                "tcp_zerocopy": round(100 * (1 - zc_gbps / raw_gbps), 1),
                "tcp_zerocopy_striped": round(
                    100 * (1 - striped_gbps / raw_gbps), 1
                ),
            },
        }

        # -- (b) two-host end-to-end shuffle over TCP ---------------------
        import importlib

        from ray_shuffling_data_loader_tpu.data_generation import (
            cached_generate_data,
        )

        # The package re-exports shuffle() the FUNCTION under the module
        # name; resolve the module explicitly.
        shuffle_mod = importlib.import_module(
            "ray_shuffling_data_loader_tpu.shuffle"
        )

        num_rows = max(4000, int(shuffle_gb * 1e9) // BYTES_PER_ROW)
        data_dir = os.path.join(CACHE_DIR, f"tcp_r{num_rows}_f8")
        os.makedirs(data_dir, exist_ok=True)
        filenames, dataset_bytes = cached_generate_data(
            num_rows, 8, 1, data_dir, seed=SEED
        )

        class _Drain(shuffle_mod.BatchConsumer):
            def __init__(self):
                self.nbytes = 0
                self.rows = 0

            def consume(self, rank, epoch, batches):
                for ref in batches:
                    cb = store.get_columns(ref)
                    self.rows += cb.num_rows
                    self.nbytes += cb.nbytes
                    del cb
                    store.free(ref)

            def producer_done(self, rank, epoch):
                pass

            def wait_until_ready(self, epoch):
                pass

            def wait_until_all_epochs_done(self):
                pass

        consumer = _Drain()
        schedule_log = []
        t0 = time.perf_counter()
        shuffle_mod.shuffle(
            list(filenames),
            consumer,
            num_epochs=2,
            num_reducers=8,
            num_trainers=1,
            seed=SEED,
            schedule_log=schedule_log,
        )
        shuffle_s = time.perf_counter() - t0
        served = {}
        for hid, info in ctx.cluster.registry.call("hosts").items():
            from ray_shuffling_data_loader_tpu.runtime.actor import (
                ActorHandle,
            )

            role = "head" if hid == ctx.cluster.host_id else "worker"
            served[role] = ActorHandle(tuple(info["store"])).call(
                "fetch_stats"
            )
        audit_summary = _audit.summary()
        # summary().ok is None when zero epochs actually reconciled —
        # that must read as NOT verified, never as a pass.
        audit_ok = audit_summary.get("ok") is True
        shuffle_gbps = consumer.nbytes / 1e9 / shuffle_s
        result["value"] = round(shuffle_gbps, 4)
        result["shuffle"] = {
            "dataset_gb": round(dataset_bytes / 1e9, 3),
            "delivered_gb": round(consumer.nbytes / 1e9, 3),
            "seconds": round(shuffle_s, 2),
            "gbps": round(shuffle_gbps, 4),
            "audit_ok": audit_ok,
            "zerocopy": True,
            "tcp_streams": streams,
            "served_cross_host": served,
            "schedules": [s for _, s in schedule_log],
        }
        if not audit_ok:
            result["error"] = "audit mismatch over the TCP plane"
        if _m.enabled():
            try:
                from ray_shuffling_data_loader_tpu.telemetry import (
                    export as _export,
                )

                flat = _export.aggregate()
                result["fetch_window_metrics"] = {
                    k: v
                    for k, v in flat.items()
                    if k.startswith("store.fetch_window")
                }
            except Exception:
                pass
        _embed_final(result)
        return result
    except Exception as exc:
        # Error path: same federated embed — the remote counters of a
        # failed run are the artifact that shows what the worker host
        # was doing when it died. Embed BEFORE the finally's shutdown
        # removes the spool tree, then return the error result (main
        # exits non-zero on any "error" key).
        import traceback

        traceback.print_exc(file=sys.stderr)
        result.setdefault("error", f"{type(exc).__name__}: {exc}"[:300])
        _embed_final(result)
        return result
    finally:
        try:
            runtime.shutdown()
        except Exception:
            pass
        if worker.poll() is None:
            worker.terminate()
            try:
                worker.wait(10)
            except subprocess.TimeoutExpired:
                worker.kill()
        import shutil as _shutil

        for d in (worker_shm, worker_spill):
            _shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------------
# Suspend/resume leg (ISSUE 13): SIGKILL the driver mid-window, resume
# from the write-ahead journal, and price the recovery.
# ---------------------------------------------------------------------------

_RESUME_CHILD = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["RSDL_BENCH_RESUME_REPO"])
from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle
from ray_shuffling_data_loader_tpu.telemetry import audit as _audit
from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics

mode = os.environ["RSDL_BENCH_RESUME_MODE"]
files = json.loads(os.environ["RSDL_BENCH_RESUME_FILES"])
epochs = int(os.environ["RSDL_BENCH_RESUME_EPOCHS"])
reducers = int(os.environ["RSDL_BENCH_RESUME_REDUCERS"])
seed = int(os.environ["RSDL_BENCH_RESUME_SEED"])

runtime.init(num_workers=2)
t0 = time.perf_counter()
first = []


class Drain(BatchConsumer):
    def consume(self, rank, epoch, batches, seq=None):
        if not first:
            first.append(time.perf_counter() - t0)
            print("FIRST_BATCH %.4f" % first[0], flush=True)
        store = runtime.get_context().store
        for ref in batches:
            store.free(ref)
        print("DELIVERED %d %s" % (epoch, seq), flush=True)
        if mode == "victim":
            time.sleep(0.15)  # widen the kill window

    def producer_done(self, rank, epoch):
        pass

    def wait_until_ready(self, epoch):
        pass

    def wait_until_all_epochs_done(self):
        pass


shuffle(files, Drain(), num_epochs=epochs, num_reducers=reducers,
        num_trainers=1, seed=seed)
verdicts = _audit.reconcile(range(epochs)) if _audit.enabled() else []
snap = _metrics.registry.snapshot() if _metrics.enabled() else {}
print("RESULT " + json.dumps({
    "first_batch_s": first[0] if first else None,
    "verdicts": [
        {"epoch": v["epoch"], "ok": v["ok"],
         "delivered_seq": v.get("delivered_seq")} for v in verdicts
    ],
    "recovery": {k: v for k, v in snap.items()
                 if k.startswith("recovery.")},
}), flush=True)
runtime.shutdown()
"""


def run_resume_bench() -> dict:
    """The ``--resume`` leg: a journal-armed driver is SIGKILLed
    mid-epoch-window, a fresh driver resumes from the write-ahead
    journal (``RSDL_RESUME=auto``), and the JSON records resume-to-
    first-batch latency against a cold epoch start plus the resume
    counters — with per-epoch ``delivered_seq`` digests proven
    bit-identical to an uninterrupted same-seed control run."""
    import shutil
    import signal as _signal

    from ray_shuffling_data_loader_tpu.data_generation import (
        cached_generate_data,
    )

    epochs, reducers, seed = 3, 4, SEED
    num_rows = max(20_000, int(0.05e9) // BYTES_PER_ROW)
    data_dir = os.path.join(CACHE_DIR, f"resume_r{num_rows}_f4")
    os.makedirs(data_dir, exist_ok=True)
    filenames, dataset_bytes = cached_generate_data(
        num_rows, 4, 1, data_dir, seed=seed
    )
    # Data generation brought up a pool in THIS process; the leg's
    # drivers are child processes with their own runtimes — drop ours
    # so the kill/resume measurements run against an idle parent.
    from ray_shuffling_data_loader_tpu import runtime as _runtime

    _runtime.shutdown()
    work = tempfile.mkdtemp(prefix="rsdl-resume-bench-")
    journal_dir = os.path.join(work, "journal")
    spool_ctrl = os.path.join(work, "audit-control")
    spool_run = os.path.join(work, "audit-run")
    shm_dir = os.path.join(work, "shm")
    for d in (journal_dir, spool_ctrl, spool_run, shm_dir):
        os.makedirs(d, exist_ok=True)

    base_env = dict(
        os.environ,
        RSDL_BENCH_RESUME_REPO=os.path.dirname(os.path.abspath(__file__)),
        RSDL_BENCH_RESUME_FILES=json.dumps(list(filenames)),
        RSDL_BENCH_RESUME_EPOCHS=str(epochs),
        RSDL_BENCH_RESUME_REDUCERS=str(reducers),
        RSDL_BENCH_RESUME_SEED=str(seed),
        RSDL_SHM_DIR=shm_dir,
        RSDL_AUDIT="1",
        RSDL_METRICS="1",
        JAX_PLATFORMS="cpu",
    )
    base_env.pop("RSDL_JOURNAL", None)
    base_env.pop("RSDL_RESUME", None)

    def _child(mode, extra, kill_after=None):
        env = dict(base_env, RSDL_BENCH_RESUME_MODE=mode, **extra)
        proc = subprocess.Popen(
            [sys.executable, "-c", _RESUME_CHILD],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True,
        )
        first_batch, result, delivered = None, None, 0
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("FIRST_BATCH "):
                first_batch = float(line.split()[1])
            elif line.startswith("DELIVERED "):
                delivered += 1
                if kill_after is not None and delivered >= kill_after:
                    os.kill(proc.pid, _signal.SIGKILL)
                    break
            elif line.startswith("RESULT "):
                result = json.loads(line[len("RESULT "):])
        proc.wait()
        return first_batch, result, delivered

    result = {
        "metric": "Suspend/resume (driver SIGKILLed mid-window)",
        "plane": "resume",
        "unit": "s",
        "dataset_gb": round(dataset_bytes / 1e9, 3),
        "epochs": epochs,
    }
    try:
        # Control: uninterrupted same-seed run — the digest truth and
        # the cold first-batch latency.
        cold_first, ctrl, _ = _child(
            "control", {"RSDL_AUDIT_DIR": spool_ctrl}
        )
        if ctrl is None:
            result["error"] = "control run died"
            return result
        # Victim: journal armed, SIGKILLed after epoch 0's window plus
        # a couple of epoch-1 deliveries (mid-epoch-window).
        _child(
            "victim",
            {"RSDL_AUDIT_DIR": spool_run, "RSDL_JOURNAL": journal_dir},
            kill_after=reducers + 2,
        )
        # Resume: fresh driver, RSDL_RESUME=auto, strict audit.
        resume_first, res, _ = _child(
            "resume",
            {"RSDL_AUDIT_DIR": spool_run, "RSDL_JOURNAL": journal_dir,
             "RSDL_RESUME": "auto", "RSDL_AUDIT_STRICT": "1"},
        )
        if res is None:
            result["error"] = "resumed run died"
            return result
        ctrl_seq = {v["epoch"]: v["delivered_seq"]
                    for v in ctrl["verdicts"]}
        res_seq = {v["epoch"]: v["delivered_seq"]
                   for v in res["verdicts"]}
        recovery = res.get("recovery", {})

        def _sum(prefix):
            return int(sum(v for k, v in recovery.items()
                           if k.startswith(prefix)))

        result.update({
            "value": round(resume_first, 4) if resume_first else None,
            "cold_first_batch_s": (
                round(cold_first, 4) if cold_first else None
            ),
            "resume_to_first_batch_s": (
                round(resume_first, 4) if resume_first else None
            ),
            "resumed_epochs": _sum("recovery.resumed_epochs"),
            "resumed_epochs_skipped": _sum(
                "recovery.resume_epochs_skipped"
            ),
            "replayed_stages": _sum("recovery.resume_reexecuted"),
            "reattached_map_stages": _sum("recovery.resume_map_skipped"),
            "reattached_reduce_stages": _sum(
                "recovery.resume_reduce_skipped"
            ),
            "digest_match": ctrl_seq == res_seq and len(ctrl_seq) == epochs,
            "audit_ok": all(v["ok"] for v in res["verdicts"]),
        })
        if not result["digest_match"]:
            result["error"] = (
                f"delivered_seq diverged: control={ctrl_seq} "
                f"resumed={res_seq}"
            )
        elif not result["audit_ok"]:
            result["error"] = "resumed run audit mismatch"
        elif not (result["resumed_epochs"]
                  or result["resumed_epochs_skipped"]):
            result["error"] = "resume found no journaled progress"
        return result
    finally:
        shutil.rmtree(work, ignore_errors=True)


def run_service_bench() -> dict:
    """The ``--plane service`` leg (ISSUE 15): two concurrent shuffle
    jobs against one service session — a same-dataset leg (job 2 rides
    job 1's decoded segments: cache-hot first epoch) and a
    disjoint-dataset leg (pure capacity sharing) — reporting aggregate
    wall vs the serial sum of the cold solo runs, job 2's first-batch
    latency vs its cold solo first batch, and per-job delivered-rows
    fairness over the overlap window. Each leg owns a fresh runtime
    session so every "cold" is honestly cold."""
    import threading as _threading

    from ray_shuffling_data_loader_tpu.data_generation import (
        cached_generate_data,
    )
    from ray_shuffling_data_loader_tpu import runtime as _runtime
    from ray_shuffling_data_loader_tpu.shuffle import (
        BatchConsumer as _BC,
        shuffle as _shuffle,
    )
    from ray_shuffling_data_loader_tpu.telemetry import (
        metrics as _metrics_mod,
    )

    os.environ["RSDL_SERVICE"] = "auto"
    os.environ["RSDL_METRICS"] = "1"
    _metrics_mod.refresh_from_env()
    from ray_shuffling_data_loader_tpu.runtime import service as _service

    epochs, reducers, seed = 2, 4, SEED
    num_rows = max(20_000, int(0.05e9) // BYTES_PER_ROW)
    dirs = [
        os.path.join(CACHE_DIR, f"service_r{num_rows}_f4_d{i}")
        for i in (0, 1)
    ]
    for d in dirs:
        os.makedirs(d, exist_ok=True)
    files1, bytes1 = cached_generate_data(
        num_rows, 4, 1, dirs[0], seed=seed
    )
    files2, bytes2 = cached_generate_data(
        num_rows, 4, 1, dirs[1], seed=seed + 1
    )
    _runtime.shutdown()  # data gen's pool; each leg owns its session

    class TimingConsumer(_BC):
        def __init__(self):
            self.t0 = time.perf_counter()
            self.first_batch = None
            self.deliveries = []  # (monotonic ts, rows)
            self.epoch_done = {}

        def consume(self, rank, epoch, batches):
            now = time.perf_counter()
            if self.first_batch is None:
                self.first_batch = now - self.t0
            nbytes = sum(int(ref.nbytes) for ref in batches)
            self.deliveries.append((now, nbytes))
            _runtime.get_context().store.free(list(batches))

        def producer_done(self, rank, epoch):
            self.epoch_done[epoch] = time.perf_counter()

        def wait_until_ready(self, epoch):
            pass

        def wait_until_all_epochs_done(self):
            pass

    def run_job(name, files, job_seed, out, schedule_log=None):
        job = _service.register_job(name=name)
        try:
            with _service.job_context(job):
                consumer = TimingConsumer()
                out[name] = consumer
                _shuffle(
                    files, consumer, num_epochs=epochs,
                    num_reducers=reducers, num_trainers=1,
                    seed=job_seed, cache_decoded=True,
                    schedule_log=schedule_log,
                )
        finally:
            _service.end_job(job)

    def solo(files, job_seed):
        _runtime.init()
        _service.cache_registry_clear()
        out = {}
        t0 = time.perf_counter()
        run_job("solo", files, job_seed, out)
        wall = time.perf_counter() - t0
        consumer = out["solo"]
        _runtime.shutdown()
        _service.reset_state()
        return wall, consumer.first_batch

    def _cache_hits_job2() -> int:
        snap = _metrics_mod.registry.snapshot()
        return int(
            sum(
                v
                for k, v in snap.items()
                if k.startswith("service.cache_hits") and "job2" in k
            )
        )

    def concurrent(files_a, files_b, stagger_on_epoch0):
        """Job A starts; job B starts either after A's epoch-0 window
        (same-dataset: A's decode segments are published then) or
        immediately (disjoint). Returns walls + consumers + fairness."""
        _runtime.init()
        _service.cache_registry_clear()
        # Per-LEG counter baseline: the registry is process-global and
        # both legs' job ids start with "job2" — without the delta the
        # disjoint leg would inherit the same-dataset leg's hits.
        hits2_before = _cache_hits_job2()
        out = {}
        log_b = []
        t0 = time.perf_counter()
        ta = _threading.Thread(
            target=run_job, args=("job1", files_a, seed, out)
        )
        ta.start()
        if stagger_on_epoch0:
            # Same-dataset leg: start job 2 once job 1's epoch-0 decode
            # segments are PUBLISHED in the content registry (promoted
            # as each publishing map resolves) — the "second job joins
            # a warm service" shape; most of job 1's run still
            # overlaps.
            deadline = time.perf_counter() + 120
            while time.perf_counter() < deadline:
                published = (
                    _service.status_section().get("cache_entries") or 0
                )
                if published >= len(files_a):
                    break
                time.sleep(0.02)
        t_b0 = time.perf_counter()
        tb = _threading.Thread(
            target=run_job,
            args=("job2", files_b, seed + 7, out),
            kwargs={"schedule_log": log_b},
        )
        tb.start()
        ta.join(timeout=600)
        tb.join(timeout=600)
        t_end = time.perf_counter()
        c1, c2 = out["job1"], out["job2"]
        # Cross-job cache proof: every lookup hit job 2 scored against
        # the content registry THIS leg (>= one per file when it rode
        # job 1's segments — its own decode would score zero).
        hits2 = _cache_hits_job2() - hits2_before
        # Fairness over the window where BOTH jobs are delivering
        # (first common delivery to last common delivery): delivered-
        # BYTES rate per job, min/max ratio. A window under 0.3 s (the
        # staggered same-dataset leg can leave almost none) reports
        # null rather than a noise ratio.
        fair = None
        overlap = 0.0
        if c1.deliveries and c2.deliveries:
            lo = max(c1.deliveries[0][0], c2.deliveries[0][0])
            hi = min(c1.deliveries[-1][0], c2.deliveries[-1][0])
            overlap = max(hi - lo, 0.0)
            if overlap > 0.3:
                rates = []
                for c in (c1, c2):
                    nbytes = sum(
                        b for ts, b in c.deliveries if lo <= ts <= hi
                    )
                    rates.append(nbytes / overlap)
                if max(rates) > 0:
                    fair = round(min(rates) / max(rates), 4)
        _runtime.shutdown()
        _service.reset_state()
        return {
            "wall_s": round(t_end - t0, 3),
            "job2_first_batch_s": (
                round(c2.first_batch, 3)
                if c2.first_batch is not None
                else None
            ),
            "job2_epoch0_schedule": dict(log_b).get(0),
            "job2_cache_hits": hits2,
            "fairness_min_over_max": fair,
            "overlap_s": round(overlap, 3),
            "job1_gb": round(
                sum(b for _, b in c1.deliveries) / 1e9, 4
            ),
            "job2_gb": round(
                sum(b for _, b in c2.deliveries) / 1e9, 4
            ),
        }

    result = {
        "metric": "Disaggregated shuffle service (two concurrent jobs)",
        "plane": "service",
        "unit": "s",
        "dataset_gb": round((bytes1 + bytes2) / 1e9, 3),
        "epochs": epochs,
        "reducers": reducers,
    }
    wall_a, first_a = solo(files1, seed)
    wall_b, _first_b = solo(files2, seed + 7)
    same = concurrent(files1, files1, stagger_on_epoch0=True)
    disjoint = concurrent(files1, files2, stagger_on_epoch0=False)
    serial_sum_same = wall_a + wall_a  # two cold solos over D1
    serial_sum_disjoint = wall_a + wall_b
    result.update({
        "solo_cold_wall_s": round(wall_a, 3),
        "solo_cold_first_batch_s": (
            round(first_a, 3) if first_a is not None else None
        ),
        "solo_cold_wall_b_s": round(wall_b, 3),
        "same_dataset": dict(
            same, serial_sum_s=round(serial_sum_same, 3),
            speedup_vs_serial=round(serial_sum_same / same["wall_s"], 3),
        ),
        "disjoint_dataset": dict(
            disjoint, serial_sum_s=round(serial_sum_disjoint, 3),
            speedup_vs_serial=round(
                serial_sum_disjoint / disjoint["wall_s"], 3
            ),
        ),
        "value": same["wall_s"],
    })
    checks = []
    if same.get("job2_cache_hits", 0) < len(files1):
        checks.append(
            "job2 epoch-0 did not ride job1's decode cache "
            f"(cache_hits={same.get('job2_cache_hits')}, "
            f"schedule={same.get('job2_epoch0_schedule')!r})"
        )
    if first_a and same.get("job2_first_batch_s"):
        result["job2_first_batch_speedup_vs_cold"] = round(
            first_a / same["job2_first_batch_s"], 2
        )
        if same["job2_first_batch_s"] > first_a / 2:
            checks.append(
                "job2 first batch not >=2x faster than cold solo"
            )
    if same["wall_s"] >= serial_sum_same:
        checks.append("same-dataset concurrent wall >= serial sum")
    if disjoint["wall_s"] >= serial_sum_disjoint:
        checks.append("disjoint concurrent wall >= serial sum")
    for leg in (same, disjoint):
        fair = leg.get("fairness_min_over_max")
        if fair is not None and fair < (1.0 / 3.0):
            checks.append(
                f"fairness ratio {fair} below 1/3 at equal weights"
            )
    if checks:
        result["error"] = "; ".join(checks)[:400]
    return result


# Every knob the plan compiler owns (planner TERM_KNOBS) plus the gate
# itself: each planner-bench leg starts from a clean slate of these so a
# stray shell export can't contaminate a "stock defaults" leg.
_PLANNER_KNOBS = (
    "RSDL_PLAN",
    "RSDL_SHUFFLE_PLAN",
    "RSDL_SELECTIVE_READS",
    "RSDL_DECODE_PUSHDOWN",
    "RSDL_DECODE_ROWGROUPS",
    "RSDL_FETCH_WINDOW_DEPTH",
    "RSDL_NATIVE_THREADS",
)


def run_planner_bench() -> dict:
    """The ``--plane planner`` leg (ISSUE 20): A/B the cost-based plan
    compiler against a hand-tuned knob set and stock defaults at two
    shapes — the r12 decode-bound shape (0.4 GB decoded x 4 files x 9
    skewed row groups, R=4, cache off, 2 epochs: block+selective is the
    documented win) and a mock-step delivery-bound shape (few blocks per
    file, so rowwise/stock is already right and the planner must not
    lose). Each leg owns a fresh runtime session so the workers' env
    snapshots honestly reflect the leg's knobs; the planner leg embeds
    the chosen plan terms (snapshotted from ``runtime.plan`` at first
    delivery) in the JSON."""
    from ray_shuffling_data_loader_tpu.data_generation import generate_data
    from ray_shuffling_data_loader_tpu import runtime as _runtime
    from ray_shuffling_data_loader_tpu.shuffle import (
        BatchConsumer as _BC,
        shuffle as _shuffle,
    )

    trials = int(os.environ.get("RSDL_BENCH_PLANNER_TRIALS", "3"))
    decode_gb = float(os.environ.get("RSDL_BENCH_PLANNER_GB", "0.4"))
    # Sized so the mock step dominates the delivery-bound wall (~8
    # deliveries x step >> pipeline noise on a loaded 2-core host):
    # the shape's claim is "the planner must not LOSE when the loader
    # is not the bottleneck", which a noise-dominated wall can't test.
    step_s = float(os.environ.get("RSDL_BENCH_PLANNER_STEP_S", "0.15"))

    def _dataset(tag, num_rows, files, groups, skew):
        """generate_data with a manifest cache keyed on the full shape
        (cached_generate_data can't: it pins skew to 0)."""
        data_dir = os.path.join(
            CACHE_DIR, f"planner_{tag}_r{num_rows}_f{files}_g{groups}"
        )
        os.makedirs(data_dir, exist_ok=True)
        key = {
            "num_rows": num_rows, "files": files, "groups": groups,
            "skew": skew, "seed": SEED,
        }
        manifest = os.path.join(data_dir, "planner_manifest.json")
        if os.path.exists(manifest):
            try:
                with open(manifest) as f:
                    m = json.load(f)
                if m.get("key") == key and all(
                    os.path.exists(p) for p in m["filenames"]
                ):
                    return m["filenames"], m["num_bytes"]
            except (json.JSONDecodeError, OSError, KeyError):
                pass
        filenames, num_bytes = generate_data(
            num_rows, files, groups, skew, data_dir, seed=SEED
        )
        tmp = f"{manifest}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"key": key, "filenames": filenames, "num_bytes": num_bytes},
                f,
            )
        os.replace(tmp, manifest)
        return filenames, num_bytes

    reducers = 4
    shapes = {
        # r12 shape: 9 skewed groups/file >= 2R -> planner should choose
        # block:1 + selective; stock rowwise pays the materialized path.
        "decode_bound": {
            "rows": max(BATCH_SIZE, int(decode_gb * 1e9) // BYTES_PER_ROW),
            "files": 4, "groups": 9, "skew": 0.5, "epochs": 2,
            "step_s": 0.0,
            "hand": {
                "RSDL_SHUFFLE_PLAN": "block:1",
                "RSDL_SELECTIVE_READS": "auto",
                "RSDL_DECODE_ROWGROUPS": "auto",
            },
        },
        # 2 groups/file < 2R: the quality bound forbids block, stock
        # rowwise is already optimal, and a mock train step dominates the
        # wall — the planner's job here is to decline cleverness.
        "delivery_bound": {
            "rows": max(BATCH_SIZE // 4, int(0.05e9) // BYTES_PER_ROW),
            "files": 4, "groups": 2, "skew": 0.0, "epochs": 2,
            "step_s": step_s,
            "hand": {
                "RSDL_SHUFFLE_PLAN": "rowwise",
                "RSDL_FETCH_WINDOW_DEPTH": "4",
            },
        },
    }
    configs = ("stock", "hand", "planner")

    class StepConsumer(_BC):
        """Frees refs on delivery; optionally burns a mock train step per
        delivered batch (the delivery-bound regime); snapshots the
        resolved plan terms the first time a batch lands (the run is
        still live, so ``runtime.plan`` holds the current plan)."""

        def __init__(self, step_s):
            self.t0 = time.perf_counter()
            self.step_s = step_s
            self.first_batch = None
            self.nbytes = 0
            self.plan_terms = None

        def consume(self, rank, epoch, batches):
            now = time.perf_counter()
            if self.first_batch is None:
                self.first_batch = now - self.t0
                planmod = sys.modules.get(
                    "ray_shuffling_data_loader_tpu.runtime.plan"
                )
                if planmod is not None:
                    try:
                        self.plan_terms = planmod.current_terms()
                    except Exception:
                        pass
            self.nbytes += sum(int(ref.nbytes) for ref in batches)
            _runtime.get_context().store.free(list(batches))
            if self.step_s > 0:
                time.sleep(self.step_s)

        def producer_done(self, rank, epoch):
            pass

        def wait_until_ready(self, epoch):
            pass

        def wait_until_all_epochs_done(self):
            pass

    def run_once(files, shape, env):
        """One measured run under the leg's knobs (every planner knob
        cleared first so a stray shell export can't contaminate a
        'stock defaults' leg; restored after)."""
        saved = {k: os.environ.pop(k, None) for k in _PLANNER_KNOBS}
        try:
            os.environ.update(env)
            _runtime.init()
            try:
                consumer = StepConsumer(shape["step_s"])
                t0 = time.perf_counter()
                _shuffle(
                    files, consumer, num_epochs=shape["epochs"],
                    num_reducers=reducers, num_trainers=1,
                    seed=SEED, cache_decoded=False,
                )
                wall = time.perf_counter() - t0
            finally:
                _runtime.shutdown()
            # Delivered-volume sanity (ref.nbytes includes column
            # padding, so bytes-exact is the wrong assert): every
            # leg must deliver the full dataset each epoch +-2%.
            expected = shape["rows"] * BYTES_PER_ROW * shape["epochs"]
            if not (0.98 * expected <= consumer.nbytes <= 1.02 * expected):
                raise RuntimeError(
                    f"delivered {consumer.nbytes} bytes, expected "
                    f"~{expected}"
                )
            return wall, consumer.first_batch, consumer.plan_terms
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    result = {
        "metric": "Self-tuning plan compiler A/B (planner vs hand vs stock)",
        "plane": "planner",
        "unit": "s",
        "reducers": reducers,
        "trials": trials,
        "shapes": {},
    }
    checks = []
    beats_stock = []
    for shape_name, shape in shapes.items():
        files, num_bytes = _dataset(
            shape_name, shape["rows"], shape["files"], shape["groups"],
            shape["skew"],
        )
        _runtime.shutdown()  # data gen's pool; each leg owns its session
        envs = {
            "stock": {},
            "hand": dict(shape["hand"]),
            "planner": {"RSDL_PLAN": "auto"},
        }
        # Trials are INTERLEAVED round-robin across configs: background
        # load drifts on shared hosts at the tens-of-seconds scale, and
        # back-to-back per-config trials would hand whichever config ran
        # in the quiet window an unearned win. Per-config best-of-N.
        walls = {c: [] for c in configs}
        firsts = {c: [] for c in configs}
        terms_by = {c: None for c in configs}
        for trial in range(max(1, trials)):
            for config in configs:
                _log(
                    f"planner bench: {shape_name}/{config} trial {trial}"
                )
                wall, first, terms = run_once(files, shape, envs[config])
                walls[config].append(wall)
                if first is not None:
                    firsts[config].append(first)
                if terms:
                    terms_by[config] = terms
        legs = {}
        for config in configs:
            legs[config] = {
                "wall_s": round(min(walls[config]), 3),
                "wall_trials_s": [round(w, 3) for w in walls[config]],
                "first_batch_s": (
                    round(min(firsts[config]), 3)
                    if firsts[config]
                    else None
                ),
                "env": dict(envs[config]),
            }
            if terms_by[config] is not None:
                legs[config]["plan_terms"] = {
                    name: {"value": t.get("value"), "source": t.get("source")}
                    for name, t in terms_by[config].items()
                }
        legs["dataset_gb"] = round(num_bytes / 1e9, 3)
        legs["epochs"] = shape["epochs"]
        legs["mock_step_s"] = shape["step_s"]
        result["shapes"][shape_name] = legs
        planner_w = legs["planner"]["wall_s"]
        hand_w = legs["hand"]["wall_s"]
        stock_w = legs["stock"]["wall_s"]
        legs["planner_vs_hand"] = round(hand_w / planner_w, 3)
        legs["planner_vs_stock"] = round(stock_w / planner_w, 3)
        if legs["planner"].get("plan_terms") is None:
            checks.append(f"{shape_name}: planner leg recorded no plan terms")
        # >= 0.95x hand-tuned on BOTH shapes (issue acceptance bound).
        if planner_w > hand_w / 0.95:
            checks.append(
                f"{shape_name}: planner wall {planner_w:.2f}s worse than "
                f"0.95x hand-tuned {hand_w:.2f}s"
            )
        fb_p = legs["planner"]["first_batch_s"]
        fb_s = legs["stock"]["first_batch_s"]
        beats_stock.append(
            planner_w < stock_w
            or (fb_p is not None and fb_s is not None and fb_p < 0.8 * fb_s)
        )
    if not any(beats_stock):
        checks.append("planner beat stock defaults on neither shape")
    result["value"] = result["shapes"]["decode_bound"]["planner"]["wall_s"]
    if checks:
        result["error"] = "; ".join(checks)[:400]
    return result


def _parse_args(argv=None):
    import argparse

    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument(
        "--trace-out",
        default=os.environ.get("RSDL_TRACE_OUT") or None,
        help="write a merged Chrome-trace/Perfetto JSON of the whole run "
        "here (enables tracing + live metrics; see docs/observability.md)",
    )
    parser.add_argument(
        "--metrics-out",
        default=os.environ.get("RSDL_METRICS_OUT") or None,
        help="write the sampled metrics timeline + final snapshot JSON "
        "here (default: <trace-out>.metrics.json when --trace-out is set)",
    )
    parser.add_argument(
        "--plane",
        choices=("local", "tcp", "service", "planner"),
        default="local",
        help="'tcp' runs the two-process loopback cross-host plane bench "
        "instead of the training bench: a worker host joins over TCP "
        "(own shm dir), reducers/trainers fetch inputs through the "
        "StoreServer windowed-fetch path, and the JSON records GB/s, "
        "per-window latency, and HMAC/framing/pickle overhead vs the "
        "same shape on local shm (plane: \"tcp\" artifact; see "
        "docs/observability.md); 'service' runs two concurrent shuffle "
        "jobs against one RSDL_SERVICE session (same-dataset and "
        "disjoint-dataset legs) and records aggregate wall vs the "
        "serial solo sum, job 2's cache-hot first batch, and the "
        "delivered-rows fairness ratio (plane: \"service\" artifact; "
        "see docs/service.md); 'planner' A/Bs the RSDL_PLAN cost-based "
        "plan compiler against hand-tuned knobs and stock defaults at a "
        "decode-bound and a mock-step delivery-bound shape, with the "
        "chosen plan terms embedded (plane: \"planner\" artifact; see "
        "docs/TUNING.md planner section)",
    )
    parser.add_argument(
        "--resume",
        action="store_true",
        help="run the suspend/resume leg instead of the training bench: "
        "a journal-armed driver child (RSDL_JOURNAL) is SIGKILLed "
        "mid-epoch-window, a fresh child resumes with RSDL_RESUME=auto "
        "under strict audit, and the JSON records resume-to-first-batch "
        "latency vs the cold start, resumed_epochs/replayed_stages "
        "counters, and per-epoch delivered_seq digest equality against "
        "an uninterrupted same-seed control run (plane: \"resume\" "
        "artifact; see docs/robustness.md)",
    )
    parser.add_argument(
        "--audit",
        action="store_true",
        default=os.environ.get("RSDL_BENCH_AUDIT", "") == "1",
        help="run with the data-correctness audit layer on (RSDL_AUDIT): "
        "per-epoch exactly-once digest verdicts are embedded under "
        "\"audit\" in the result JSON (including on watchdog/error "
        "exits); forces the map/reduce loader unless RSDL_BENCH_RESIDENT "
        "is set explicitly (the resident loader bypasses the audited "
        "host pipeline)",
    )
    try:
        return parser.parse_args(argv)
    except SystemExit as exc:
        # argparse already printed usage to stderr; keep the one-JSON-line
        # stdout contract for genuine errors (--help exits 0, no JSON).
        if exc.code not in (0, None):
            print(
                json.dumps(
                    _error_result(
                        "unknown",
                        "bad command line: "
                        + " ".join(sys.argv[1:])[:200],
                    )
                ),
                flush=True,
            )
        raise


def main() -> None:
    args = _parse_args()
    # Fail fast on a typo'd regime override (ADVICE r5): before this ran
    # only at result-assembly time — after the full benchmark on a healthy
    # run, and inside the watchdogs' error paths on a wedged one, where
    # the raise broke the error-JSON contract entirely.
    forced = os.environ.get("RSDL_BENCH_TARGET_CONTEXT")
    if forced and forced not in _TARGET_CONTEXTS:
        print(
            json.dumps(
                _error_result(
                    "unknown",
                    f"RSDL_BENCH_TARGET_CONTEXT={forced!r} is not one of "
                    f"{_TARGET_CONTEXTS}",
                )
            ),
            flush=True,
        )
        sys.exit(1)

    if args.resume:
        # The suspend/resume leg: self-contained child drivers (own
        # runtimes, journals, audit spools), same one-JSON-line
        # contract; a non-zero exit marks a failed capture.
        try:
            result = run_resume_bench()
        except BaseException as exc:  # noqa: BLE001 — the JSON line matters
            import traceback

            traceback.print_exc(file=sys.stderr)
            result = {
                "metric": "Suspend/resume (driver SIGKILLed mid-window)",
                "plane": "resume",
                "unit": "s",
                "error": f"{type(exc).__name__}: {exc}"[:300],
            }
        _ledger_append(result)
        print(json.dumps(result), flush=True)
        sys.exit(1 if "error" in result else 0)

    if args.plane == "service":
        # The two-concurrent-jobs service bench: self-contained (owns
        # its sessions, service registry, metrics) and the same
        # one-JSON-line contract; a non-zero exit marks a failed
        # capture for the CI lane's check.
        try:
            result = run_service_bench()
        except BaseException as exc:  # noqa: BLE001 — the JSON line matters
            import traceback

            traceback.print_exc(file=sys.stderr)
            result = {
                "metric": (
                    "Disaggregated shuffle service (two concurrent jobs)"
                ),
                "plane": "service",
                "unit": "s",
                "error": f"{type(exc).__name__}: {exc}"[:300],
            }
        _ledger_append(result)
        print(json.dumps(result), flush=True)
        sys.exit(1 if "error" in result else 0)

    if args.plane == "planner":
        # The plan-compiler A/B bench: self-contained (owns its
        # sessions and the planner env knobs, restored on exit) and the
        # same one-JSON-line contract; a non-zero exit marks a failed
        # capture OR a planner that lost to hand-tuned/stock beyond the
        # acceptance bounds.
        try:
            result = run_planner_bench()
        except BaseException as exc:  # noqa: BLE001 — the JSON line matters
            import traceback

            traceback.print_exc(file=sys.stderr)
            result = {
                "metric": (
                    "Self-tuning plan compiler A/B "
                    "(planner vs hand vs stock)"
                ),
                "plane": "planner",
                "unit": "s",
                "error": f"{type(exc).__name__}: {exc}"[:300],
            }
        _ledger_append(result)
        print(json.dumps(result), flush=True)
        sys.exit(1 if "error" in result else 0)

    if args.plane == "tcp":
        # The loopback two-host plane bench: self-contained (owns its
        # cluster, metrics, audit) and same one-JSON-line contract; a
        # non-zero exit marks a failed capture for the CI lane's check.
        try:
            result = run_tcp_plane_bench()
        except BaseException as exc:  # noqa: BLE001 — the JSON line matters
            import traceback

            traceback.print_exc(file=sys.stderr)
            result = {
                "metric": "Cross-host TCP plane (two-process loopback)",
                "plane": "tcp",
                "value": 0.0,
                "unit": "GB/s",
                "error": f"{type(exc).__name__}: {exc}"[:300],
            }
        _ledger_append(result)
        print(json.dumps(result), flush=True)
        sys.exit(1 if "error" in result else 0)

    from ray_shuffling_data_loader_tpu import telemetry
    from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics

    metrics_out = args.metrics_out
    if args.trace_out:
        # Enable BEFORE any runtime bring-up so every spawned worker and
        # actor inherits the spool dir through the environment.
        spool = args.trace_out + ".spool"
        # Drop spool files left by a previous run with the same
        # --trace-out: flush appends and trace_export merges every
        # trace-*.jsonl it finds, so stale files would splice the old
        # run's spans (possibly under reused pids) into the new artifact.
        if os.path.isdir(spool):
            for fname in os.listdir(spool):
                if fname.startswith("trace-") and fname.endswith(".jsonl"):
                    try:
                        os.unlink(os.path.join(spool, fname))
                    except OSError:
                        pass
        telemetry.enable(spool_dir=spool)
        _metrics.enable()
        telemetry.set_process_name("bench-driver")
        telemetry.set_context(trial=0)
        if metrics_out is None:
            metrics_out = args.trace_out + ".metrics.json"
        _TELEMETRY_EXIT_PATHS[0] = args.trace_out
        _TELEMETRY_EXIT_PATHS[1] = metrics_out
    elif metrics_out:
        # --metrics-out alone is an explicit opt-in to the metrics half;
        # without this the guard below would silently skip the requested
        # artifact.
        _metrics.enable()
        _TELEMETRY_EXIT_PATHS[1] = metrics_out

    from ray_shuffling_data_loader_tpu.telemetry import audit as _audit

    if args.audit:
        # Enable BEFORE runtime bring-up so pool workers inherit the
        # audit env and spool their map/reduce digest records where the
        # driver's reconciler can fold them.
        spool = (
            args.trace_out + ".auditspool"
            if args.trace_out
            else tempfile.mkdtemp(prefix="rsdl-audit-")
        )
        _audit.enable(spool_dir=spool)
        # Metrics carry the audit.* counters; keep them on so the
        # verdict counters land in the snapshot artifacts too.
        _metrics.enable()
        if "RSDL_BENCH_RESIDENT" not in os.environ:
            _log(
                "audit mode: forcing the map/reduce loader "
                "(RSDL_BENCH_RESIDENT=off) — the device-resident loader "
                "bypasses the audited host shuffle pipeline"
            )
            os.environ["RSDL_BENCH_RESIDENT"] = "off"

    platform, num_chips, tpu_error = init_backend()
    try:
        result = run_bench(platform, num_chips, tpu_error)
    except BaseException as exc:  # noqa: BLE001 — the one JSON line matters
        import traceback

        traceback.print_exc(file=sys.stderr)
        result = _error_result(platform, f"{type(exc).__name__}: {exc}")
        result["error_type"] = type(exc).__name__
        # Structured stage-failure fields (shuffle.StageFailedError, or
        # batch_queue.ProducerDiedError's epoch/rank): a poison task that
        # exhausted its retry budget names its stage and epoch in the
        # artifact instead of burying them in the message.
        for attr, key in (
            ("stage", "failed_stage"),
            ("epoch", "failed_epoch"),
            ("attempts", "failed_attempts"),
            ("rank", "failed_rank"),
        ):
            value = getattr(exc, attr, None)
            if value is not None:
                result[key] = value
        if tpu_error is not None:
            result["tpu_error"] = str(tpu_error)[:300]
    # Stop any sampler threads run_bench left running (it only reaches its
    # own teardown on the straight-line path) so the exports below cannot
    # race a live sampler appending to the metrics timeline.
    _stop_live_samplers()
    # Export the trace/metrics artifacts even for a failed run — the
    # trace of a failed run is the artifact that shows where it died.
    # Guarded: artifact export must never break the one-JSON-line
    # contract.
    if args.trace_out:
        try:
            result["trace_out"] = telemetry.trace_export(args.trace_out)
        except Exception as exc:
            result["trace_error"] = f"{type(exc).__name__}: {exc}"[:200]
    if args.audit and "audit" not in result:
        # Success path: the shuffle driver already reconciled at epoch
        # end; embed the per-epoch verdicts (the error path embeds them
        # via _error_result). Guarded like the other artifact exports.
        try:
            result["audit"] = _audit.summary()
        except Exception as exc:
            result["audit_error"] = f"{type(exc).__name__}: {exc}"[:200]
    if metrics_out and _metrics.enabled():
        try:
            # On a failed run the batch-queue source's actor may be wedged
            # rather than dead, and a source poll blocks with no timeout —
            # restrict the final snapshot to local instruments there.
            result["metrics_out"] = _metrics.dump_json(
                metrics_out, include_sources="error" not in result
            )
        except Exception as exc:
            result["metrics_error"] = f"{type(exc).__name__}: {exc}"[:200]
    if _metrics.enabled() and "telemetry_final" not in result:
        # Success path: embed the CLUSTER-aggregated final counters (the
        # error path embeds them via _error_result) — worker map/reduce
        # counters spooled at task-done fold in here; the driver-local
        # snapshot alone would silently drop everything worker-side.
        # Straggler/event summaries first, so their gauges fold in too.
        try:
            from ray_shuffling_data_loader_tpu.telemetry import (
                export as _metrics_export,
            )

            _attach_obs_summaries(result)
            result["telemetry_final"] = _metrics_export.aggregate()
        except Exception as exc:
            result["telemetry_error"] = f"{type(exc).__name__}: {exc}"[:200]
    if "profile" not in result:
        # Success path: the error path embeds via _error_result.
        _attach_profile(result)
    _ledger_append(result)
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
