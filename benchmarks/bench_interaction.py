"""Micro-benchmark: fused Pallas dot-interaction vs the XLA reference.

Times the op standalone (eager dispatch, realistic for a data-loader-bound
step) and embedded in the full DLRM train step (where XLA fusion decides
the real winner). Run on a TPU host:

    python benchmarks/bench_interaction.py [--batch 8192] [--reps 300]

Measured on v5e (1 chip, B=8192, N=27, D=16): standalone the two paths are
within noise of each other (~40 us, dispatch-bound); the kernel's value is
keeping the ``[B, N, N]`` Gram out of HBM inside larger fused steps.
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def _time(fn, x, reps: int) -> float:
    import jax

    f = jax.jit(fn)
    jax.block_until_ready(f(x))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(x)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch", type=int, default=8192)
    parser.add_argument("--num-features", type=int, default=27)
    parser.add_argument("--embed-dim", type=int, default=16)
    parser.add_argument("--block-batch", type=int, default=256)
    parser.add_argument("--reps", type=int, default=300)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from ray_shuffling_data_loader_tpu.ops import (
        dot_interaction,
        dot_interaction_reference,
    )

    print(f"backend={jax.default_backend()} devices={jax.device_count()}")
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((args.batch, args.num_features, args.embed_dim)),
        dtype=jnp.float32,
    )

    rows = []
    rows.append(
        (
            "pallas fwd",
            _time(
                lambda x: dot_interaction(
                    x, use_pallas=True, block_batch=args.block_batch
                ),
                x,
                args.reps,
            ),
        )
    )
    rows.append(("xla fwd", _time(dot_interaction_reference, x, args.reps)))
    rows.append(
        (
            "pallas fwd+bwd",
            _time(
                jax.grad(
                    lambda x: (
                        dot_interaction(
                            x, use_pallas=True, block_batch=args.block_batch
                        )
                        ** 2
                    ).sum()
                ),
                x,
                args.reps,
            ),
        )
    )
    rows.append(
        (
            "xla fwd+bwd",
            _time(
                jax.grad(
                    lambda x: (dot_interaction_reference(x) ** 2).sum()
                ),
                x,
                args.reps,
            ),
        )
    )
    for label, dt in rows:
        print(f"{label:>16}: {dt * 1e6:8.1f} us/iter")


if __name__ == "__main__":
    main()
