#!/usr/bin/env bash
# Benchmark sweep: grid over num_files × num_trainers × reducers_per_trainer
# (reference ``benchmarks/benchmark_batch.sh:9-42``, which drives the same
# grid through `ray exec cluster.yaml`; here the runtime is in-process on the
# TPU-VM host so the sweep is a plain loop).
#
# The reference's full-scale workload is 4e8 rows (~64 GB) with batch 250k;
# scale via NUM_ROWS for the hardware at hand.

set -euo pipefail
cd "$(dirname "$0")/.."

NUM_ROWS="${NUM_ROWS:-400000000}"
BATCH_SIZE="${BATCH_SIZE:-250000}"
NUM_EPOCHS="${NUM_EPOCHS:-10}"
NUM_TRIALS="${NUM_TRIALS:-2}"
MAX_CONCURRENT_EPOCHS="${MAX_CONCURRENT_EPOCHS:-2}"
STATS_DIR="${STATS_DIR:-benchmark_stats}"
DATA_DIR="${DATA_DIR:-benchmark_data}"

first=1
for num_files in 100 50 25; do
  for num_trainers in 16 8 4; do
    for reducers_per_trainer in 4 3 2; do
      num_reducers=$((num_trainers * reducers_per_trainer))
      echo "=== files=${num_files} trainers=${num_trainers}" \
           "reducers=${num_reducers} ==="
      # Reuse data only across same-file-count configs; when num_files
      # changes the old files must be cleared first or a later
      # --use-old-data run would pick up the leftovers.
      if [[ "${prev_files:-}" == "$num_files" ]]; then
        data_flags="--use-old-data"
      else
        data_flags="--num-files ${num_files} --clear-old-data"
      fi
      python benchmarks/benchmark.py \
        --num-rows "${NUM_ROWS}" \
        ${data_flags} \
        --num-row-groups-per-file 5 \
        --batch-size "${BATCH_SIZE}" \
        --num-epochs "${NUM_EPOCHS}" \
        --num-trials "${NUM_TRIALS}" \
        --max-concurrent-epochs "${MAX_CONCURRENT_EPOCHS}" \
        --num-trainers "${num_trainers}" \
        --num-reducers "${num_reducers}" \
        --data-dir "${DATA_DIR}" \
        --stats-dir "${STATS_DIR}" \
        $([[ "$first" -eq 1 ]] || echo --no-overwrite-stats)
      first=0
      prev_files="$num_files"
    done
  done
done
