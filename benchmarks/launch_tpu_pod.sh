#!/usr/bin/env bash
# Launch the shuffle benchmark across a TPU pod slice — the analog of the
# reference's Ray-autoscaler cluster.yaml + `ray exec` flow
# (reference benchmarks/cluster.yaml, benchmarks/benchmark_batch.sh).
#
# Topology: host 0 of the slice is the cluster head; every other TPU-VM
# host joins over the pod's internal network (the DCN control path).
# Input Parquet must be on storage all hosts can read (GCS via gcsfuse,
# or a shared NFS mount).
#
# Usage (from your workstation, gcloud configured):
#   TPU_NAME=my-v5e-16 ZONE=us-west4-a ./benchmarks/launch_tpu_pod.sh \
#       --num-rows 400000000 --num-files 100 --num-trainers 16 \
#       --num-reducers 48 --num-epochs 10
set -euo pipefail

TPU_NAME=${TPU_NAME:?set TPU_NAME}
ZONE=${ZONE:?set ZONE}
REPO_DIR=${REPO_DIR:-"\$HOME/ray_shuffling_data_loader_tpu"}
HEAD_PORT=${HEAD_PORT:-43211}

run_on() {  # run_on <worker-index|all> <command>
    gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" \
        --worker="$1" --command="$2"
}

# Head host (worker 0) starts the cluster and prints the join address
# (tcp://ip:port/token — the token gates the pickle RPC plane).
ADDRESS=$(run_on 0 "cd $REPO_DIR && python - <<'PY'
from ray_shuffling_data_loader_tpu import runtime
ctx = runtime.init_cluster(listen_port=$HEAD_PORT)
print(ctx.cluster.address, flush=True)
import time
time.sleep(86400)  # keep the head alive; benchmark attaches via env
PY" | tail -1)
echo "head up at $ADDRESS"

# Every other host joins as a worker.
NUM_WORKERS=$(gcloud compute tpus tpu-vm describe "$TPU_NAME" --zone "$ZONE" \
    --format="value(networkEndpoints.len())")
for w in $(seq 1 $((NUM_WORKERS - 1))); do
    run_on "$w" "cd $REPO_DIR && nohup python -m \
        ray_shuffling_data_loader_tpu.runtime.cluster join $ADDRESS \
        > join.log 2>&1 &" &
done
wait
echo "all $NUM_WORKERS hosts joined"

# Benchmark runs on the head, scattering shuffle stages across the pod.
run_on 0 "cd $REPO_DIR && python benchmarks/benchmark.py --address $ADDRESS $*"
