#!/usr/bin/env bash
# Launch the shuffle benchmark across a TPU pod slice — the analog of the
# reference's Ray-autoscaler cluster.yaml + `ray exec` flow
# (reference benchmarks/cluster.yaml, benchmarks/benchmark_batch.sh).
#
# Topology: host 0 of the slice is the cluster head; every other TPU-VM
# host joins over the pod's internal network (the DCN control path).
# Input Parquet must be on storage all hosts can read (GCS via gcsfuse,
# or a shared NFS mount).
#
# Usage (from your workstation, gcloud configured):
#   TPU_NAME=my-v5e-16 ZONE=us-west4-a ./benchmarks/launch_tpu_pod.sh \
#       --num-rows 400000000 --num-files 100 --num-trainers 16 \
#       --num-reducers 48 --num-epochs 10
#
# --print-only (first arg): emit the exact gcloud command sequence, one
# per line, without executing anything — the launcher's logic is testable
# without pod hardware (VERDICT r4 item 8). The worker count that gcloud
# would report comes from PRINT_ONLY_WORKERS (default 4); the head join
# address, unknowable without a live head, is the <HEAD_ADDRESS>
# placeholder.
set -euo pipefail

PRINT_ONLY=0
if [ "${1:-}" = "--print-only" ]; then
    PRINT_ONLY=1
    shift
fi

TPU_NAME=${TPU_NAME:?set TPU_NAME}
ZONE=${ZONE:?set ZONE}
REPO_DIR=${REPO_DIR:-"\$HOME/ray_shuffling_data_loader_tpu"}
HEAD_PORT=${HEAD_PORT:-43211}

ssh_cmd() {  # ssh_cmd <worker-index> <command> -> the argv, one line, quoted
    printf 'gcloud compute tpus tpu-vm ssh %q --zone %q --worker=%q --command=%q\n' \
        "$TPU_NAME" "$ZONE" "$1" "$2"
}

# run_on executes EXACTLY what ssh_cmd prints (eval of the %q-quoted
# line), so the --print-only output and the tests over it cannot drift
# from the live command sequence.
run_on() {  # run_on <worker-index|all> <command>
    eval "$(ssh_cmd "$1" "$2")"
}

# Head host (worker 0) starts the cluster and prints the join address
# (tcp://ip:port/token — the token gates the pickle RPC plane).
HEAD_CMD="cd $REPO_DIR && python - <<'PY'
from ray_shuffling_data_loader_tpu import runtime
ctx = runtime.init_cluster(listen_port=$HEAD_PORT)
print(ctx.cluster.address, flush=True)
import time
time.sleep(86400)  # keep the head alive; benchmark attaches via env
PY"
if [ "$PRINT_ONLY" = 1 ]; then
    ssh_cmd 0 "$HEAD_CMD"
    ADDRESS="<HEAD_ADDRESS>"
else
    # The head command never EOFs (the trailing sleep keeps the cluster
    # alive for the whole benchmark), so a plain $(...) capture would
    # block forever. Stream its output to a file in the background and
    # poll for the printed join address instead.
    HEAD_LOG=$(mktemp)
    run_on 0 "$HEAD_CMD" > "$HEAD_LOG" 2>&1 &
    HEAD_PID=$!
    ADDRESS=""
    for _ in $(seq 1 150); do
        ADDRESS=$(grep -oE 'tcp://[^[:space:]]+' "$HEAD_LOG" | head -1 || true)
        [ -n "$ADDRESS" ] && break
        # Fail fast if the head ssh already died (auth failure, bad
        # REPO_DIR) instead of sleeping out the full timeout.
        kill -0 "$HEAD_PID" 2>/dev/null || break
        sleep 2
    done
    if [ -z "$ADDRESS" ]; then
        echo "head never printed a join address; log:" >&2
        cat "$HEAD_LOG" >&2
        exit 1
    fi
    echo "head up at $ADDRESS"
fi

# Every other host joins as a worker.
DESCRIBE=(gcloud compute tpus tpu-vm describe "$TPU_NAME" --zone "$ZONE"
          --format="value(networkEndpoints.len())")
if [ "$PRINT_ONLY" = 1 ]; then
    printf '%q ' "${DESCRIBE[@]}"
    printf '\n'
    NUM_WORKERS=${PRINT_ONLY_WORKERS:-4}
else
    NUM_WORKERS=$("${DESCRIBE[@]}")
fi
JOIN_CMD_PREFIX="cd $REPO_DIR && nohup python -m \
    ray_shuffling_data_loader_tpu.runtime.cluster join"
JOIN_PIDS=()
for w in $(seq 1 $((NUM_WORKERS - 1))); do
    if [ "$PRINT_ONLY" = 1 ]; then
        ssh_cmd "$w" "$JOIN_CMD_PREFIX $ADDRESS > join.log 2>&1 &"
    else
        run_on "$w" "$JOIN_CMD_PREFIX $ADDRESS > join.log 2>&1 &" &
        JOIN_PIDS+=($!)
    fi
done
if [ "$PRINT_ONLY" != 1 ]; then
    # Wait on the join ssh jobs ONLY: a bare `wait` would also block on
    # the backgrounded head ssh, which stays alive for the whole run.
    [ "${#JOIN_PIDS[@]}" -gt 0 ] && wait "${JOIN_PIDS[@]}"
    echo "all $NUM_WORKERS hosts joined"
fi

# Benchmark runs on the head, scattering shuffle stages across the pod.
BENCH_CMD="cd $REPO_DIR && python benchmarks/benchmark.py --address $ADDRESS $*"
if [ "$PRINT_ONLY" = 1 ]; then
    ssh_cmd 0 "$BENCH_CMD"
else
    run_on 0 "$BENCH_CMD"
fi
