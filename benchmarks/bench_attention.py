"""Micro-benchmark: the attention stack across schedules and lowerings.

Times, at several sequence lengths, on whatever backend is up:

* dense XLA reference (``attention_reference``)
* blockwise XLA (``blockwise_attention`` — no [T, T] materialization)
* Pallas flash kernel (``flash_attention``; interpret mode off-TPU is
  meaningless for timing, so it only runs compiled on TPU)
* ring schedule over all local devices (``make_ring_attention``)
* Ulysses schedule over all local devices (``make_ulysses_attention``)

Prints one JSON line per (schedule, seq_len) so results can be diffed
across rounds. Run:

    python benchmarks/bench_attention.py [--seqs 1024,4096] [--reps 20]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _time(fn, args_, reps: int) -> float:
    import jax

    out = fn(*args_)
    jax.block_until_ready(out)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args_)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--seqs", type=str, default="1024,4096")
    parser.add_argument("--batch", type=int, default=2)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--head-dim", type=int, default=64)
    parser.add_argument("--reps", type=int, default=20)
    parser.add_argument("--causal", action="store_true")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from ray_shuffling_data_loader_tpu.ops import (
        attention_reference,
        blockwise_attention,
        flash_attention,
        make_ring_attention,
        make_ulysses_attention,
    )

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    rng = np.random.default_rng(0)

    for seq in [int(s) for s in args.seqs.split(",")]:
        shape = (args.batch, seq, args.heads, args.head_dim)
        q, k, v = (
            jnp.asarray(rng.standard_normal(shape), jnp.float32)
            for _ in range(3)
        )
        schedules = {
            "dense": jax.jit(
                lambda q, k, v: attention_reference(
                    q, k, v, causal=args.causal
                )
            ),
            "blockwise": jax.jit(
                lambda q, k, v: blockwise_attention(
                    q, k, v, causal=args.causal
                )
            ),
        }
        if platform == "tpu":
            schedules["flash"] = jax.jit(
                lambda q, k, v: flash_attention(
                    q,
                    k,
                    v,
                    causal=args.causal,
                    use_pallas=True,
                    interpret=False,
                )
            )
        # Pre-shard inputs for the sequence-parallel schedules: without
        # this, every timed rep would include a full scatter of q/k/v
        # from device 0, which the single-device schedules never pay.
        sharded_inputs = None
        if seq % n_dev == 0 and n_dev > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            sh = NamedSharding(mesh, P(None, "sp", None, None))
            sharded_inputs = tuple(jax.device_put(x, sh) for x in (q, k, v))
            schedules["ring"] = make_ring_attention(
                mesh, "sp", causal=args.causal
            )
            if args.heads % n_dev == 0:
                schedules["ulysses"] = make_ulysses_attention(
                    mesh, "sp", causal=args.causal
                )
        for name, fn in schedules.items():
            inputs = (
                sharded_inputs
                if name in ("ring", "ulysses")
                else (q, k, v)
            )
            try:
                dt = _time(fn, inputs, args.reps)
            except Exception as exc:  # e.g. OOM at long T for dense
                print(
                    json.dumps(
                        {
                            "schedule": name,
                            "seq": seq,
                            "error": f"{type(exc).__name__}: {exc}"[:200],
                        }
                    ),
                    flush=True,
                )
                continue
            print(
                json.dumps(
                    {
                        "schedule": name,
                        "seq": seq,
                        "batch": args.batch,
                        "heads": args.heads,
                        "head_dim": args.head_dim,
                        "causal": args.causal,
                        "ms": round(dt * 1e3, 3),
                        "backend": platform,
                        "devices": n_dev,
                    }
                ),
                flush=True,
            )


if __name__ == "__main__":
    sys.exit(main())
