"""Shuffle benchmark harness.

Capability parity with the reference benchmark driver
(``benchmarks/benchmark.py:28-337``): generate (or reuse) a synthetic
Parquet dataset, run N trials of the multi-epoch shuffle against per-trainer
consumer actors, collect per-stage stats plus object-store utilization, and
dump trial/epoch/consumer-timeline CSVs.

TPU-native differences: consumers are runtime actor processes on this host's
worker substrate (the reference spreads Ray actors over a placement group,
``benchmarks/benchmark.py:125-147``), and store utilization comes from the
session's shared-memory store instead of the raylet gRPC probe.

Run:
    python benchmarks/benchmark.py --num-rows 1000000 --num-files 10 \
        --num-trainers 4 --num-reducers 8 --num-epochs 5 --num-trials 2

Scope: this harness measures the HOST shuffle engine (map/reduce +
actor consumers). The device-resident loader bypasses that engine
entirely; its end-to-end measurement lives in the repo-root ``bench.py``
(which auto-selects between the loaders) and ``BENCHLOG.md``.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.data_generation import generate_data
from ray_shuffling_data_loader_tpu.runtime import ObjectRef
from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle
from ray_shuffling_data_loader_tpu.stats import (
    ObjectStoreStatsCollector,
    TrialStatsCollector,
    human_readable_big_num,
    process_stats,
)


class Consumer:
    """Per-trainer consumer actor: dereferences reducer outputs from the
    store, counts rows/bytes, frees segments (reference ``Consumer`` actor,
    ``benchmarks/benchmark.py:28-62``)."""

    def __init__(self, rank: int):
        self.rank = rank
        self.num_batches = 0
        self.num_rows = 0
        self.num_bytes = 0
        self.consume_times: List[float] = []
        self._epoch_starts: Dict[int, float] = {}

    def new_epoch(self, epoch: int) -> None:
        self._epoch_starts[epoch] = time.time()

    def consume(self, epoch: int, refs: List[ObjectRef]) -> int:
        ctx = runtime.ensure_initialized()
        rows = 0
        for ref in refs:
            cb = ctx.store.get_columns(ref)
            rows += cb.num_rows
            self.num_bytes += cb.nbytes
            del cb
            ctx.store.free(ref)
        self.num_batches += len(refs)
        self.num_rows += rows
        start = self._epoch_starts.get(epoch)
        if start is not None:
            self.consume_times.append(time.time() - start)
        return rows

    def producer_done(self, epoch: int) -> None:
        pass

    def get_stats(self) -> Dict:
        return {
            "rank": self.rank,
            "num_batches": self.num_batches,
            "num_rows": self.num_rows,
            "num_bytes": self.num_bytes,
            "consume_times": self.consume_times,
        }


class ActorBatchConsumer(BatchConsumer):
    """Driver-side adapter implementing the shuffle engine's consumer
    interface over per-rank consumer actors, with the epoch-window admission
    gate (reference ``BatchConsumer`` impl, ``benchmarks/benchmark.py:65-108``;
    window semantics per ``batch_queue.py:395-418``)."""

    def __init__(self, consumers, max_concurrent_epochs: int, num_trainers: int):
        self._consumers = consumers
        self._window = max_concurrent_epochs
        self._num_trainers = num_trainers
        self._cond = threading.Condition()
        self._in_flight: set = set()
        self._done_ranks = collections.defaultdict(set)

    def wait_until_ready(self, epoch: int) -> None:
        with self._cond:
            self._cond.wait_for(lambda: len(self._in_flight) < self._window)
            self._in_flight.add(epoch)
        for c in self._consumers:
            c.call_oneway("new_epoch", epoch)

    def consume(self, rank: int, epoch: int, batches: List[ObjectRef]) -> None:
        # Synchronous call: returning means the consumer has fully processed
        # (and freed) the batch, so window release implies consumption.
        self._consumers[rank].call("consume", epoch, batches)

    def producer_done(self, rank: int, epoch: int) -> None:
        self._consumers[rank].call_oneway("producer_done", epoch)
        with self._cond:
            self._done_ranks[epoch].add(rank)
            if len(self._done_ranks[epoch]) == self._num_trainers:
                self._in_flight.discard(epoch)
                self._cond.notify_all()

    def wait_until_all_epochs_done(self) -> None:
        with self._cond:
            self._cond.wait_for(lambda: not self._in_flight)


def run_trial(
    trial: int,
    filenames: List[str],
    args,
) -> "TrialStats":
    """One trial: fresh consumers + collector, timed shuffle, stats fetch
    (reference ``run_trials`` body, ``benchmarks/benchmark.py:111-184``)."""
    collector = None
    if not args.no_stats:
        collector = runtime.spawn_actor(
            TrialStatsCollector,
            args.num_epochs,
            len(filenames),
            args.num_reducers,
            args.num_rows,
            args.batch_size,
            args.num_trainers,
            trial,
            args.num_row_groups_per_file,
            args.max_concurrent_epochs,
            name=f"stats-trial-{trial}",
        )
        collector.wait_ready()
    # Cluster mode: spread consumers round-robin over the hosts — the
    # reference's SPREAD placement group for its Consumer actors
    # (``benchmarks/benchmark.py:125-130``). Single-host (empty list)
    # spawns locally as before; a host whose agent cannot import this
    # module (bare `runtime.cluster join` from another cwd) degrades to
    # a local spawn rather than sinking the trial.
    hosts = runtime.cluster_hosts()

    def _spawn_consumer(rank: int):
        name = f"consumer-{trial}-{rank}"
        target = hosts[rank % len(hosts)] if hosts else None
        try:
            return runtime.spawn_actor(
                Consumer, rank, name=name, host_id=target
            )
        except Exception:
            if target is None or target == hosts[0]:
                raise
            print(f"[bench] consumer {rank}: spawn on {target} failed; "
                  "falling back to a local spawn", flush=True)
            return runtime.spawn_actor(Consumer, rank, name=name)

    consumers = [_spawn_consumer(rank) for rank in range(args.num_trainers)]
    for c in consumers:
        c.wait_ready()
    batch_consumer = ActorBatchConsumer(
        consumers, args.max_concurrent_epochs, args.num_trainers
    )

    if collector is not None:
        with ObjectStoreStatsCollector(
            collector, sample_period_s=args.store_stats_sample_period
        ):
            duration = shuffle(
                filenames,
                batch_consumer,
                args.num_epochs,
                args.num_reducers,
                args.num_trainers,
                seed=args.seed + trial,
                stats_collector=collector,
                narrow_to_32=args.narrow_to_32,
                cache_decoded=args.cache_decoded,
            )
    else:
        duration = shuffle(
            filenames,
            batch_consumer,
            args.num_epochs,
            args.num_reducers,
            args.num_trainers,
            seed=args.seed + trial,
            narrow_to_32=args.narrow_to_32,
            cache_decoded=args.cache_decoded,
        )
    print(
        f"Trial {trial} done in {duration:.2f}s "
        f"({human_readable_big_num(args.num_rows * args.num_epochs / duration)}"
        f" rows/s)"
    )
    consumed_rows = sum(
        c.call("get_stats")["num_rows"] for c in consumers
    )
    expected = args.num_rows * args.num_epochs
    assert consumed_rows == expected, (consumed_rows, expected)

    stats = None
    if collector is not None:
        stats = collector.call("get_stats", 30)
        collector.terminate()
    for c in consumers:
        c.terminate()
    return stats if stats is not None else duration


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-rows", type=int, default=4 * 10 ** 6)
    p.add_argument("--num-files", type=int, default=100)
    p.add_argument("--num-row-groups-per-file", type=int, default=5)
    p.add_argument("--max-row-group-skew", type=float, default=0.0)
    p.add_argument("--num-reducers", type=int, default=5)
    p.add_argument("--num-trainers", type=int, default=5)
    p.add_argument("--num-epochs", type=int, default=10)
    p.add_argument("--num-trials", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--max-concurrent-epochs", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--data-dir", type=str, default="benchmark_data")
    p.add_argument("--stats-dir", type=str, default="benchmark_stats")
    p.add_argument(
        "--use-old-data",
        action="store_true",
        help="Reuse Parquet files already present in --data-dir.",
    )
    p.add_argument("--clear-old-data", action="store_true")
    p.add_argument("--no-stats", action="store_true")
    p.add_argument("--no-overwrite-stats", action="store_true")
    p.add_argument("--store-stats-sample-period", type=float, default=5.0)
    p.add_argument("--num-workers", type=int, default=None)
    p.add_argument(
        "--narrow-to-32",
        action="store_true",
        help="Cast 64-bit columns to 32-bit at decode (halves bytes "
        "through every shuffle pass; ids must fit int32).",
    )
    cache = p.add_mutually_exclusive_group()
    cache.add_argument(
        "--cache-decoded",
        dest="cache_decoded",
        action="store_true",
        default=None,
        help="Keep decoded columns in the store across epochs "
        "(default: auto by store budget).",
    )
    cache.add_argument(
        "--no-cache-decoded",
        dest="cache_decoded",
        action="store_false",
        help="Force per-epoch Parquet decode.",
    )
    p.add_argument(
        "--address",
        type=str,
        default=None,
        help="Join an existing runtime session instead of creating one.",
    )
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.use_old_data and args.clear_old_data:
        raise ValueError(
            "Only one of --use-old-data and --clear-old-data may be given."
        )
    runtime.init(address=args.address, num_workers=args.num_workers)

    if args.clear_old_data:
        print(f"Clearing old data from {args.data_dir}.")
        for f in glob.glob(os.path.join(args.data_dir, "*.parquet.snappy")):
            os.remove(f)

    if args.use_old_data:
        filenames = sorted(
            glob.glob(os.path.join(args.data_dir, "*.parquet.snappy"))
        )
        if not filenames:
            raise FileNotFoundError(
                f"--use-old-data given but no Parquet files in {args.data_dir}"
            )
        num_bytes = sum(os.path.getsize(f) for f in filenames)
        print(f"Reusing {len(filenames)} files ({num_bytes / 1e9:.2f} GB).")
    else:
        print(
            f"Generating {human_readable_big_num(args.num_rows)} rows over "
            f"{args.num_files} files."
        )
        t0 = time.time()
        filenames, num_bytes = generate_data(
            args.num_rows,
            args.num_files,
            args.num_row_groups_per_file,
            args.max_row_group_skew,
            args.data_dir,
            seed=args.seed,
        )
        print(
            f"Generated {num_bytes / 1e9:.2f} GB in {time.time() - t0:.1f}s."
        )

    print(
        f"Shuffling {human_readable_big_num(args.num_rows)} rows × "
        f"{args.num_epochs} epochs × {args.num_trials} trials: "
        f"{args.num_reducers} reducers → {args.num_trainers} trainers, "
        f"epoch window {args.max_concurrent_epochs}."
    )
    all_stats = []
    for trial in range(args.num_trials):
        all_stats.append(run_trial(trial, filenames, args))

    if not args.no_stats:
        summary = process_stats(
            all_stats,
            stats_dir=args.stats_dir,
            overwrite_stats=not args.no_overwrite_stats,
        )
        print(json.dumps(summary))
        print(f"Stats CSVs written to {args.stats_dir}/")
    else:
        # --no-stats: run_trial returned plain durations.
        print(
            f"Mean trial duration: {sum(all_stats) / len(all_stats):.2f}s"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
