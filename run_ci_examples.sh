#!/usr/bin/env bash
# Example smoke runs (reference run_ci_examples.sh runs the dataset and
# torch_dataset __main__ smoke tests; here the end-to-end DLRM trainer on a
# tiny workload, CPU backend, plus the multi-chip dry run).
set -euo pipefail
cd "$(dirname "$0")"
export JAX_PLATFORMS=cpu
python examples/train_dlrm.py --smoke
python __graft_entry__.py 8
