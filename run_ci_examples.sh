#!/usr/bin/env bash
# Example smoke runs (reference run_ci_examples.sh runs the dataset and
# torch_dataset __main__ smoke tests; here the end-to-end DLRM trainer on a
# tiny workload, CPU backend, plus the multi-chip dry run).
set -euo pipefail
cd "$(dirname "$0")"
export JAX_PLATFORMS=cpu
python examples/train_dlrm.py --smoke
python examples/train_dlrm.py --smoke --loader resident --model transformer
# 2 devices: one full butterfly round + the bf16 wire path at a fraction
# of the 8-device cost (8 virtual devices on shared cores is ~6 min).
XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python examples/train_dlrm.py --smoke --grad-reduce adasum --grad-bf16
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python examples/train_long_context.py --dp 2 --sp 4 --steps 8 \
    --seq-len 256
python examples/train_dlrm_multirank.py --num-trainers 2 \
    --num-rows 50000 --num-files 4 --batch-size 5000 --epochs 2
python -m ray_shuffling_data_loader_tpu.dataset
python -m ray_shuffling_data_loader_tpu.torch_dataset
python examples/train_dlrm_pod.py --simulate-pod 2 --platform cpu \
    --num-rows 30000 --num-files 8 --batch-size 3000 --epochs 1 \
    --rendezvous-dir "$(mktemp -d)"
python __graft_entry__.py 8
