#!/usr/bin/env python
"""Shuffle hot-path phase microbench: where a map/reduce epoch spends
its time, against this host's memory-bandwidth roofline.

Runs the real shuffle pipeline (pool workers, store, both schedules) at
a configurable shape with the per-op phase profiler on
(``telemetry/phases.py``), folds the worker-spooled metrics with
``telemetry.export.aggregate``, and prints:

* a **phase-cost table** — per ``(stage, phase)``: task count, total
  seconds, mean, bytes moved, and effective GB/s;
* a **roofline estimate** — this host's measured single-core memcpy
  bandwidth plus the gather/copy microprobe figures the schedule policy
  uses (``shuffle._probed_host_costs``), and each data-moving phase's
  bandwidth as a fraction of the copy roofline;
* the **schedule auto-policy verdict** for the shape (decode cache +
  index schedule), with its model terms — so a wrong decline at any
  shape is visible next to the measured phase costs that refute or
  confirm it.

Usage::

    python tools/shuffle_profile.py --gb 0.5 --files 8 --reducers 8 \
        --epochs 3 [--narrow] [--schedule auto|index|mapreduce] \
        [--out profile.json]

The VERDICT r5 evidence hole this exists for: "nobody has profiled
where the 7.7 s-average reduce task spends its time" — see BENCHLOG for
the committed tables.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

# Profiler + worker spools must be armed BEFORE the runtime (and its
# worker pool) come up, so every spawned process inherits the env.
os.environ.setdefault("RSDL_METRICS", "1")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_KEY_RE = re.compile(
    r"^shuffle\.phase_(seconds|bytes)\{phase=(?P<phase>[^,}]+),"
    r"stage=(?P<stage>[^,}]+)\}(?P<suffix>_count|_sum|_min|_max)?$"
)


class _DrainConsumer:
    """Counts + frees delivered reducer outputs (keeps the driver's store
    residency flat so the measured phases are the stage tasks, not an
    unbounded consumer backlog)."""

    def __init__(self):
        self.rows = 0
        self.nbytes = 0

    def consume(self, rank, epoch, batches):
        from ray_shuffling_data_loader_tpu import runtime

        store = runtime.get_context().store
        for ref in batches:
            cb = store.get_columns(ref)
            self.rows += cb.num_rows
            self.nbytes += cb.nbytes
            del cb
            store.free(ref)

    def producer_done(self, rank, epoch):
        pass

    def wait_until_ready(self, epoch):
        pass

    def wait_until_all_epochs_done(self):
        pass


def _memcpy_gbps(nbytes: int = 256 << 20, repeats: int = 3) -> float:
    """Measured single-core host memcpy bandwidth (the roofline a
    sequential data-moving phase cannot beat): best of ``repeats`` timed
    ``np.copyto`` passes over an ``nbytes`` buffer, counted as read+write
    traffic."""
    return _memcpy_gbps_mt(1, nbytes=nbytes, repeats=repeats)


def _memcpy_gbps_mt(
    threads: int, nbytes: int = 256 << 20, repeats: int = 3
) -> float:
    """N-core memcpy roofline: ``threads`` concurrent ``np.copyto``
    passes over disjoint buffers (numpy releases the GIL for large
    copies), counted as aggregate read+write traffic — the ceiling an
    N-thread data-moving kernel is measured against."""
    import threading as _threading

    per = max(1 << 20, nbytes // threads)
    srcs = [np.arange(per // 8, dtype=np.int64) for _ in range(threads)]
    dsts = [np.empty_like(s) for s in srcs]
    for s, d in zip(srcs, dsts):
        np.copyto(d, s)  # warm (defeat COW zero-pages)
    best = 0.0
    for _ in range(repeats):
        barrier = _threading.Barrier(threads + 1)

        def _run(s, d):
            barrier.wait()
            np.copyto(d, s)

        workers = [
            _threading.Thread(target=_run, args=(s, d))
            for s, d in zip(srcs, dsts)
        ]
        for w in workers:
            w.start()
        barrier.wait()
        t0 = time.perf_counter()
        for w in workers:
            w.join()
        dt = time.perf_counter() - t0
        best = max(best, 2 * sum(s.nbytes for s in srcs) / max(dt, 1e-9))
    return best / 1e9


def _best_s(fn, repeats: int = 5) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_threads_sweep(args, thread_counts) -> int:
    """Isolated kernel sweep (``--threads``): partition scatter, permute
    scatter, and gather at the bench task shape, per kernel thread
    count, against the matching N-core memcpy roofline. This is the
    multi-core evidence ROADMAP item 2 asks for — the kernels must scale
    with cores toward the roofline, isolated from pipeline effects
    (worker scheduling, store I/O, consumer pacing)."""
    from ray_shuffling_data_loader_tpu import native
    from ray_shuffling_data_loader_tpu.data_generation import DATA_SPEC

    bytes_per_row = 168  # DATA_SPEC (pre-narrowing)
    num_rows = max(1000, int(args.gb * 1e9) // bytes_per_row)
    task_rows = max(1, num_rows // args.files)
    rng = np.random.default_rng(0)
    # The narrowed map-task batch: every DATA_SPEC column 4 bytes wide
    # (the regime the typed kernels were built for, BENCHLOG r6).
    cols = {}
    for name, (low, high, dtype) in DATA_SPEC.items():
        if np.issubdtype(dtype, np.integer):
            cols[name] = rng.integers(
                low, high, task_rows, dtype=np.int64
            ).astype(np.int32)
        else:
            cols[name] = rng.random(task_rows).astype(np.float32)
    cols["key"] = np.arange(task_rows, dtype=np.int32)
    batch_bytes = sum(v.nbytes for v in cols.values())
    assignment = rng.integers(0, args.reducers, size=task_rows)
    out = {k: np.empty_like(v) for k, v in cols.items()}
    # Reduce-side shapes: one reducer's output (total epoch rows /
    # reducers) permuted; windows arrive per mapper file.
    red_rows = max(1, num_rows // args.reducers)
    red_col = rng.integers(0, 1 << 30, size=red_rows).astype(np.int32)
    perm = rng.permutation(red_rows)
    red_out = np.empty_like(red_col)
    print(
        f"[sweep] map task: {task_rows} rows x {len(cols)} cols "
        f"({batch_bytes / 1e6:.0f} MB narrowed), reducer output: "
        f"{red_rows} rows; native={native.native_available()}",
        file=sys.stderr,
    )
    if not native.native_available():
        print(
            "[sweep] WARNING: native kernels unavailable — numpy "
            "fallbacks ignore n_threads, the sweep will show no scaling",
            file=sys.stderr,
        )

    sweep = []
    base = {}
    print()
    print(
        f"{'threads':>7} {'op':<18} {'GB/s':>7} {'x vs 1':>7} "
        f"{'roofline GB/s':>13} {'%roof':>6}"
    )
    for t in thread_counts:
        roof = _memcpy_gbps_mt(t)
        ops = {
            "partition-scatter": (
                2 * batch_bytes,
                lambda t=t: native.group_rows_multi(
                    cols, assignment, args.reducers, out=out, n_threads=t
                ),
            ),
            "permute-scatter": (
                2 * red_col.nbytes,
                lambda t=t: native.scatter(
                    red_col, perm, red_out, n_threads=t
                ),
            ),
            "gather": (
                2 * red_col.nbytes,
                lambda t=t: native.take(
                    red_col, perm, out=red_out, n_threads=t
                ),
            ),
        }
        for op, (nbytes, fn) in ops.items():
            gbps = nbytes / _best_s(fn) / 1e9
            base.setdefault(op, gbps)
            speedup = gbps / base[op]
            print(
                f"{t:>7d} {op:<18} {gbps:>7.2f} {speedup:>6.2f}x "
                f"{roof:>13.2f} {100 * gbps / roof:>5.1f}%"
            )
            sweep.append(
                {
                    "threads": t,
                    "op": op,
                    "gbps": round(gbps, 3),
                    "speedup_vs_1": round(speedup, 3),
                    "memcpy_roofline_gbps": round(roof, 3),
                    "roofline_frac": round(gbps / roof, 4),
                }
            )
    result = {
        "mode": "threads-sweep",
        "shape": {
            "gb": args.gb,
            "files": args.files,
            "reducers": args.reducers,
            "task_rows": task_rows,
            "batch_mb": round(batch_bytes / 1e6, 1),
        },
        "host_cpus": os.cpu_count(),
        "native": native.native_available(),
        "sweep": sweep,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[sweep] wrote {args.out}", file=sys.stderr)
    return 0


def run_decode_sweep(args, thread_counts) -> int:
    """Isolated Parquet decode sweep (``--decode``, ISSUE 11): the
    row-group-parallel decode plan at each thread count, full decode vs
    a staging-style projection (features+label+key), against the
    N-core memcpy roofline. Decoded GB/s counts DECODED bytes (the
    projected set), so the projection rows additionally report the
    bytes pruned per file — the pushdown win is visible next to the
    thread win."""
    import importlib

    shuffle_mod = importlib.import_module(
        "ray_shuffling_data_loader_tpu.shuffle"
    )
    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.data_generation import (
        DATA_SPEC,
        cached_generate_data,
    )

    runtime.init(num_workers=2)
    bytes_per_row = 168  # DATA_SPEC
    num_rows = max(1000, int(args.gb * 1e9) // bytes_per_row)
    data_dir = args.data_dir or os.path.join(
        _REPO, ".bench_cache", f"decode_r{num_rows}_f{args.files}_g8"
    )
    os.makedirs(data_dir, exist_ok=True)
    filenames, dataset_bytes = cached_generate_data(
        num_rows, args.files, 8, data_dir, seed=0
    )
    # The sweep itself is single-process: drop the worker pool BEFORE
    # timing so idle runtime processes don't share the cores under
    # measurement (measured ~15% drag on the 2-core host).
    runtime.shutdown()
    proj = ["embeddings_name0", "one_hot0", "labels", "key"]
    projections = {"full": None, "projected": proj}
    print(
        f"[decode] dataset {dataset_bytes / 1e9:.2f} GB on disk, "
        f"{num_rows} rows x {args.files} files, "
        f"{len(shuffle_mod.file_row_group_sizes(filenames[0]))} row "
        f"groups/file",
        file=sys.stderr,
    )
    print()
    print(
        f"{'threads':>7} {'projection':<10} {'decoded GB':>10} "
        f"{'best s':>8} {'GB/s':>7} {'x vs 1':>7} {'pruned GB':>10}"
    )
    sweep = []
    base: dict = {}
    groups_of = {
        fname: list(
            range(len(shuffle_mod.file_row_group_sizes(fname)))
        )
        for fname in filenames
    }
    # Caveat the baseline honestly: pq.read_table's dataset scanner
    # uses Arrow's IO thread pool even with use_threads=False, so the
    # legacy "single-shot" read is NOT single-core. The sweep therefore
    # measures the row-group PLAN at 1..N threads (explicit row_groups
    # pins the plan path at every count) and reports the legacy read as
    # its own row for context.
    legacy = 0

    def _legacy():
        nonlocal legacy
        legacy = 0
        for fname in filenames:
            cb = shuffle_mod.read_parquet_columns(fname)
            legacy += cb.nbytes
            del cb

    lbest = _best_s(_legacy, repeats=5)
    print(
        f"{'-':>7} {'legacy':<10} {legacy / 1e9:>10.3f} {lbest:>8.3f} "
        f"{legacy / lbest / 1e9:>7.2f} {'-':>7} {0.0:>10.3f}"
    )
    sweep.append(
        {
            "threads": 0,
            "projection": "legacy-read-table",
            "decoded_gb": round(legacy / 1e9, 4),
            "best_s": round(lbest, 4),
            "gbps": round(legacy / lbest / 1e9, 3),
        }
    )
    for t in thread_counts:
        for label, cols in projections.items():
            decoded = 0

            def _run(cols=cols, t=t):
                nonlocal decoded
                decoded = 0
                for fname in filenames:
                    cb = shuffle_mod.read_parquet_columns(
                        fname,
                        columns=cols,
                        row_groups=groups_of[fname],
                        rowgroup_threads=t,
                    )
                    decoded += cb.nbytes
                    del cb

            best = _best_s(_run, repeats=5)
            gbps = decoded / best / 1e9
            base.setdefault(label, gbps)
            # Pruned = full decoded footprint minus the projected one
            # (what pushdown never decoded).
            pruned = 0
            if cols is not None and "full" in base:
                full_decoded = sweep[0]["decoded_gb"] * 1e9
                pruned = max(0, int(full_decoded - decoded))
            row = {
                "threads": t,
                "projection": label,
                "decoded_gb": round(decoded / 1e9, 4),
                "best_s": round(best, 4),
                "gbps": round(gbps, 3),
                "speedup_vs_1": round(gbps / base[label], 3),
                "pruned_gb": round(pruned / 1e9, 4),
            }
            sweep.append(row)
            print(
                f"{t:>7d} {label:<10} {row['decoded_gb']:>10.3f} "
                f"{best:>8.3f} {gbps:>7.2f} "
                f"{row['speedup_vs_1']:>6.2f}x {row['pruned_gb']:>10.3f}"
            )
    # Per-plan selective sweep (ISSUE 12): one epoch of the selective
    # schedule's decode work under each plan family, from the very
    # planning seam the reduce tasks run (selective_file_selection).
    # Rowwise shows the honest R-fold re-read (every reducer's
    # selection covers ~every group); block:1 shows disjoint
    # selections — each group decoded exactly once, amplification ~1x,
    # pruned GB > 0. This one command reproduces the BENCHLOG r12
    # amplification claim.
    phys_groups = sum(
        len(shuffle_mod.file_row_group_sizes(f)) for f in filenames
    )
    reducers = args.reducers
    full_bytes = sweep[0]["decoded_gb"] * 1e9
    selective_sweep = []
    print()
    print(
        f"{'plan':>9} {'decoded GB':>10} {'pruned GB':>10} "
        f"{'groups':>7} {'amp':>6} {'best s':>8}  groups/reducer"
    )
    for plan in (("rowwise", 0), ("block", 1)):
        label = plan[0] if plan[0] == "rowwise" else f"block:{plan[1]}"
        decoded = 0
        groups_per_reducer = [0] * reducers

        def _epoch(plan=plan):
            nonlocal decoded
            decoded = 0
            for r in range(reducers):
                groups_per_reducer[r] = 0
                for i, fname in enumerate(filenames):
                    gsel, _pos = shuffle_mod.selective_file_selection(
                        fname, i, r, reducers, 0, 0, plan
                    )
                    groups_per_reducer[r] += len(gsel)
                    cb = shuffle_mod.read_parquet_columns(
                        fname,
                        row_groups=[int(g) for g in gsel],
                        rowgroup_threads=1,
                    )
                    decoded += cb.nbytes
                    del cb

        best = _best_s(_epoch, repeats=3)
        groups_total = sum(groups_per_reducer)
        # Pruned vs the selective schedule's worst case: every
        # reducer decoding every file whole (what rowwise degrades
        # to).
        pruned = max(0, int(reducers * full_bytes - decoded))
        row = {
            "plan": label,
            "decoded_gb": round(decoded / 1e9, 4),
            "pruned_gb": round(pruned / 1e9, 4),
            "groups_touched": groups_total,
            "groups_per_reducer": groups_per_reducer[:],
            "physical_groups": phys_groups,
            "amplification": round(groups_total / phys_groups, 3),
            "best_s": round(best, 4),
        }
        selective_sweep.append(row)
        print(
            f"{label:>9} {row['decoded_gb']:>10.3f} "
            f"{row['pruned_gb']:>10.3f} {groups_total:>7d} "
            f"{row['amplification']:>5.2f}x {best:>8.3f}  "
            f"{groups_per_reducer}"
        )
    result = {
        "mode": "decode-sweep",
        "shape": {
            "gb": args.gb,
            "files": args.files,
            "rows": num_rows,
            "row_groups_per_file": len(
                shuffle_mod.file_row_group_sizes(filenames[0])
            ),
            "projection": proj,
        },
        "host_cpus": os.cpu_count(),
        "dataset_disk_gb": round(dataset_bytes / 1e9, 3),
        "sweep": sweep,
        "selective_sweep": {
            "reducers": reducers,
            "physical_groups": phys_groups,
            "rows": selective_sweep,
        },
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[decode] wrote {args.out}", file=sys.stderr)
    return 0


def _phase_table(flat: dict) -> dict:
    """``{(stage, phase): {count, total_s, bytes}}`` from an aggregated
    flat snapshot."""
    table: dict = {}
    for key, value in flat.items():
        m = _KEY_RE.match(key)
        if not m:
            continue
        entry = table.setdefault(
            (m.group("stage"), m.group("phase")),
            {"count": 0, "total_s": 0.0, "bytes": 0.0},
        )
        kind, suffix = m.group(1), m.group("suffix")
        if kind == "seconds" and suffix == "_count":
            entry["count"] = int(value)
        elif kind == "seconds" and suffix == "_sum":
            entry["total_s"] = float(value)
        elif kind == "bytes" and not suffix:
            entry["bytes"] = float(value)
    return table


def main() -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--gb", type=float, default=0.5)
    parser.add_argument("--files", type=int, default=8)
    parser.add_argument("--reducers", type=int, default=8)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--trainers", type=int, default=1)
    parser.add_argument("--narrow", action="store_true")
    parser.add_argument(
        "--schedule",
        choices=("auto", "index", "mapreduce"),
        default="auto",
        help="force the steady-state schedule (sets RSDL_INDEX_SHUFFLE)",
    )
    parser.add_argument(
        "--data-dir",
        default=None,
        help="dataset cache dir (default: .bench_cache/profile_* shape key)",
    )
    parser.add_argument(
        "--threads",
        default=None,
        help="comma list of kernel thread counts (e.g. 1,2,4): run the "
        "ISOLATED kernel sweep (partition scatter / permute scatter / "
        "gather at the bench task shape vs the N-core memcpy roofline) "
        "instead of the pipeline profile",
    )
    parser.add_argument(
        "--decode",
        default=None,
        help="comma list of decode thread counts (e.g. 1,2): run the "
        "ISOLATED Parquet decode sweep (row-group-parallel plan, full "
        "vs projected decode, pruned bytes) instead of the pipeline "
        "profile",
    )
    parser.add_argument("--out", default=None, help="also dump JSON here")
    args = parser.parse_args()

    if args.threads:
        thread_counts = [int(x) for x in args.threads.split(",") if x]
        return run_threads_sweep(args, thread_counts)
    if args.decode:
        thread_counts = [int(x) for x in args.decode.split(",") if x]
        return run_decode_sweep(args, thread_counts)

    if args.schedule != "auto":
        os.environ["RSDL_INDEX_SHUFFLE"] = (
            "on" if args.schedule == "index" else "off"
        )

    import importlib

    # The package re-exports shuffle() the FUNCTION under the same name as
    # the module; resolve the module explicitly.
    shuffle_mod = importlib.import_module(
        "ray_shuffling_data_loader_tpu.shuffle"
    )
    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.data_generation import (
        cached_generate_data,
    )
    from ray_shuffling_data_loader_tpu.telemetry import export as _export

    bytes_per_row = 168  # DATA_SPEC
    num_rows = max(1000, int(args.gb * 1e9) // bytes_per_row)
    data_dir = args.data_dir or os.path.join(
        _REPO, ".bench_cache", f"profile_r{num_rows}_f{args.files}"
    )
    os.makedirs(data_dir, exist_ok=True)
    filenames, dataset_bytes = cached_generate_data(
        num_rows, args.files, 2, data_dir, seed=0
    )
    print(
        f"[profile] dataset {dataset_bytes / 1e9:.2f} GB on disk, "
        f"{num_rows} rows x {args.files} files",
        file=sys.stderr,
    )

    runtime.init(num_workers=max(2, os.cpu_count() or 1))
    consumer = _DrainConsumer()
    schedule_log: list = []
    t0 = time.perf_counter()
    shuffle_mod.shuffle(
        list(filenames),
        consumer,
        num_epochs=args.epochs,
        num_reducers=args.reducers,
        num_trainers=args.trainers,
        seed=0,
        narrow_to_32=args.narrow,
        schedule_log=schedule_log,
    )
    wall_s = time.perf_counter() - t0

    flat = _export.aggregate()
    table = _phase_table(flat)
    copy_gbps = _memcpy_gbps()
    probed = shuffle_mod._probed_host_costs()

    # Schedule-policy verdicts for this shape, with the model's terms.
    est_cache = None
    try:
        est_cache = shuffle_mod._est_decoded_bytes(
            list(filenames), args.narrow
        )
    except OSError:
        pass
    cache_auto = shuffle_mod._decode_cache_auto(
        list(filenames), args.epochs, args.narrow
    )
    index_auto = shuffle_mod._index_schedule_allowed(
        list(filenames), args.reducers, args.narrow
    )
    policy = {
        "est_decoded_bytes": est_cache,
        "decode_cache_auto": bool(cache_auto),
        "index_schedule_auto": bool(index_auto),
        "probed_costs": {k: float(v) for k, v in probed.items()},
        "gather_bw_at_cache": (
            shuffle_mod._gather_bw_for(est_cache) if est_cache else None
        ),
        "schedules_run": [s for _, s in schedule_log],
    }

    rows = []
    order = sorted(table, key=lambda sp: -table[sp]["total_s"])
    print()
    print(
        f"{'stage':<14} {'phase':<18} {'n':>5} {'total s':>9} "
        f"{'mean s':>8} {'GB':>8} {'GB/s':>7} {'%roofline':>9}"
    )
    for stage, phase in order:
        e = table[(stage, phase)]
        gb = e["bytes"] / 1e9
        gbps = gb / e["total_s"] if e["total_s"] > 0 else 0.0
        frac = 100.0 * gbps / copy_gbps if copy_gbps else 0.0
        mean = e["total_s"] / e["count"] if e["count"] else 0.0
        print(
            f"{stage:<14} {phase:<18} {e['count']:>5d} "
            f"{e['total_s']:>9.2f} {mean:>8.3f} {gb:>8.2f} "
            f"{gbps:>7.2f} {frac:>8.1f}%"
        )
        rows.append(
            {
                "stage": stage,
                "phase": phase,
                "count": e["count"],
                "total_s": round(e["total_s"], 3),
                "mean_s": round(mean, 4),
                "gb": round(gb, 3),
                "gbps": round(gbps, 3),
                "roofline_frac": round(gbps / copy_gbps, 4)
                if copy_gbps
                else None,
            }
        )
    phase_total = sum(e["total_s"] for e in table.values())
    print(
        f"\n[profile] wall {wall_s:.1f}s; phase-accounted task time "
        f"{phase_total:.1f}s across all workers; delivered "
        f"{consumer.nbytes / 1e9:.2f} GB ({consumer.rows} rows); "
        f"pipeline {consumer.nbytes / 1e9 / wall_s:.3f} GB/s"
    )
    print(
        f"[profile] roofline: single-core memcpy {copy_gbps:.2f} GB/s "
        f"(r+w); probe copy {probed['copy'] / 1e9:.2f}, gather "
        f"{probed['gather_small'] / 1e9:.2f} (cache-res) / "
        f"{probed['gather_large'] / 1e9:.2f} (DRAM) GB/s, store "
        f"round-trip {probed['roundtrip'] * 1e3:.2f} ms"
    )
    print(f"[profile] schedule policy: {json.dumps(policy)}")

    result = {
        "shape": {
            "gb": args.gb,
            "files": args.files,
            "reducers": args.reducers,
            "epochs": args.epochs,
            "narrow": bool(args.narrow),
            "schedule_arg": args.schedule,
        },
        "wall_s": round(wall_s, 2),
        "pipeline_gbps": round(consumer.nbytes / 1e9 / wall_s, 4),
        "delivered_gb": round(consumer.nbytes / 1e9, 3),
        "memcpy_roofline_gbps": round(copy_gbps, 3),
        "phases": rows,
        "policy": policy,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[profile] wrote {args.out}", file=sys.stderr)
    runtime.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
