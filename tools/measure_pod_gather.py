"""Measure the resident pod path's delivery costs (VERDICT r3 item 5):
materialized one-gather epochs vs the per-batch-gather schedule, on the
same 2-process / 8-virtual-device harness the pod test drives.

Reuses ``tests/test_resident_pod.py``'s worker verbatim (``RSDL_T_ROWS``
/ ``RSDL_T_BATCH`` scale it up) so the measured path is exactly the
tested path. Prints one JSON line; append the numbers to BENCHLOG.md.

Run:  python tools/measure_pod_gather.py [num_rows] [batch]
"""

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tests.test_resident_pod import _WORKER, _free_port  # noqa: E402


def main() -> None:
    num_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000
    rdv = tempfile.mkdtemp(prefix="rsdl-podmeasure-")
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for rank in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            RSDL_T_REPO=_REPO,
            RSDL_T_COORD=coord,
            RSDL_T_RANK=str(rank),
            RSDL_T_RDV=rdv,
            RSDL_T_ROWS=str(num_rows),
            RSDL_T_BATCH=str(batch),
        )
        log = open(os.path.join(rdv, f"rank{rank}.log"), "w")
        procs.append(
            (
                subprocess.Popen(
                    [sys.executable, "-u", "-c", _WORKER],
                    stdout=log,
                    stderr=subprocess.STDOUT,
                    env=env,
                ),
                log,
            )
        )
    try:
        for proc, _ in procs:
            proc.wait(timeout=1800)
    finally:
        for proc, log in procs:
            proc.kill()
            proc.wait()
            log.close()
    for rank in range(2):
        with open(os.path.join(rdv, f"rank{rank}.log")) as f:
            tail = f.read()
        if f"RESPOD_RANK_DONE {rank}" not in tail:
            print(json.dumps({"error": f"rank {rank} failed",
                              "log_tail": tail[-2000:]}))
            return
    r0 = json.load(open(os.path.join(rdv, "keys_0")))
    row_bytes = 4 * 3  # 2 feature cols + label, packed int32
    epoch_gb = num_rows * row_bytes / 1e9
    mat_steady = r0["mat_epoch_s"][1]
    result = {
        "num_rows": num_rows,
        "batch": batch,
        "epoch_gb": round(epoch_gb, 4),
        "staging_s": round(r0["stats"]["first_batch_s"], 3),
        "mat_epoch_s": [round(s, 3) for s in r0["mat_epoch_s"]],
        "gather_epoch_s": round(r0["gather_epoch_s"], 3),
        "gather_vs_mat_steady": round(
            r0["gather_epoch_s"] / max(1e-9, mat_steady), 2
        ),
        "mat_stats": r0["stats"],
        "gather_stats": r0["gather_stats"],
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
