#!/usr/bin/env python
"""Epoch critical-path report: trace + stats CSVs -> per-epoch breakdown.

Answers the operator question the raw artifacts only imply: **which
stage was the bottleneck this epoch?** Ingests the merged Chrome-trace
JSON (``telemetry.trace_export`` / ``bench.py --trace-out``) and
optionally the ``stats.process_stats`` CSVs and the bench result JSON,
then computes per epoch:

* the wall-clock **busy time per pipeline stage** — ``map``, ``reduce``,
  ``deliver`` (reducer-output handoff incl. queue backpressure), and
  ``consume`` (trainer-side ``stage:h2d`` staging) — as merged interval
  unions, so N overlapping map tasks count once;
* the **overlap** structure: how much of the epoch window had >= 2
  stages active (pipelining working) vs exactly one (that stage IS the
  critical path there) vs none (idle: admission throttle, scheduling
  gaps);
* the **critical-path stage**: the stage carrying the largest
  sole-active share of the epoch window (the time nothing else could
  hide), tie-broken toward the later pipeline stage;
* **stall attribution** from the trainer's ``stall`` spans
  (``cause=upstream|staging``) and the epoch CSV's admission-throttle
  column.

With ``--baseline BENCH_rXX.json`` (either a raw ``bench.py`` JSON line
or the round-capture wrapper with a ``"parsed"`` field) the current
run's headline numbers (``--bench``, same shapes) gate a regression
check: exit **1** when throughput drops more than ``--threshold-pct``
(default 10) or stall% rises more than ``--stall-threshold-pts``
(default 10) — so a CI lane can fail on a real slowdown. Exit 2 on
usage errors, 3 when the inputs contain no per-epoch data (an empty
report must not read as a pass).

Pure stdlib, no server. Example::

    python bench.py --trace-out=/tmp/run.json > /tmp/bench.json
    python tools/epoch_report.py --trace /tmp/run.json \
        --epoch-csv epoch_stats.csv --bench /tmp/bench.json \
        --baseline BENCH_r05.json
"""

from __future__ import annotations

import argparse
import csv
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# Span-name -> pipeline-stage mapping (docs/observability.md vocabulary).
# map:read is a sub-interval of map and deliver:wait-maps is bookkeeping,
# so neither contributes its own stage.
_SPAN_STAGE = {
    "map": "map",
    "reduce": "reduce",
    "deliver": "deliver",
    "stage:h2d": "consume",
}
STAGE_ORDER = ["map", "reduce", "deliver", "consume"]


def _load_json(path: Optional[str]) -> Optional[dict]:
    if not path:
        return None
    with open(path) as f:
        text = f.read().strip()
    # bench stdout may carry log lines around the one JSON line; take the
    # last line that parses as a JSON object.
    try:
        return json.loads(text)
    except ValueError:
        for line in reversed(text.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    continue
    raise ValueError(f"{path}: no JSON object found")


def _load_csv(path: Optional[str]) -> List[Dict[str, str]]:
    if not path:
        return []
    with open(path, newline="") as f:
        return list(csv.DictReader(f))


def _bench_fields(obj: Optional[dict]) -> Dict[str, Any]:
    """Headline fields from a bench result JSON — accepts both the raw
    one-line shape and the round-capture wrapper (``{"parsed": {...}}``,
    the BENCH_rXX.json format)."""
    if not obj:
        return {}
    if isinstance(obj.get("parsed"), dict):
        obj = obj["parsed"]
    return {
        k: obj[k]
        for k in (
            "value", "stall_pct", "stall_upstream_pct", "stall_staging_pct",
            "total_s", "map_stage_s", "reduce_stage_s", "throttle_s",
            "backend", "error",
        )
        if k in obj
    }


# ---------------------------------------------------------------------------
# Interval math (microsecond Chrome-trace timestamps)
# ---------------------------------------------------------------------------


def _merge(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    out: List[Tuple[float, float]] = []
    for start, end in sorted(intervals):
        if out and start <= out[-1][1]:
            if end > out[-1][1]:
                out[-1] = (out[-1][0], end)
        else:
            out.append((start, end))
    return out


def _total(merged: List[Tuple[float, float]]) -> float:
    return sum(end - start for start, end in merged)


def _active_profile(
    by_stage: Dict[str, List[Tuple[float, float]]]
) -> Dict[str, float]:
    """Sweep the union of all stage boundaries and integrate: per-stage
    sole-active time, total >= 2-stages-overlap time, and any-active
    time — the decomposition the critical-path call keys on."""
    points = sorted(
        {t for ivs in by_stage.values() for iv in ivs for t in iv}
    )
    sole = {stage: 0.0 for stage in by_stage}
    overlap = 0.0
    any_active = 0.0
    for lo, hi in zip(points, points[1:]):
        if hi <= lo:
            continue
        active = [
            stage
            for stage, ivs in by_stage.items()
            if any(s <= lo and hi <= e for s, e in ivs)
        ]
        span = hi - lo
        if len(active) == 1:
            sole[active[0]] += span
        elif len(active) >= 2:
            overlap += span
        if active:
            any_active += span
    return {"sole": sole, "overlap": overlap, "any": any_active}


def collect_epochs(events: List[dict]) -> Dict[int, Dict[str, Any]]:
    """Per-epoch stage intervals + stall attribution from trace events."""
    intervals: Dict[int, Dict[str, List[Tuple[float, float]]]] = {}
    stalls: Dict[int, Dict[str, float]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        epoch = args.get("epoch")
        if epoch is None:
            continue
        try:
            epoch = int(epoch)
        except (TypeError, ValueError):
            continue
        name = e.get("name")
        start = float(e.get("ts", 0.0))
        end = start + max(0.0, float(e.get("dur", 0.0)))
        stage = _SPAN_STAGE.get(name)
        if stage is not None:
            intervals.setdefault(epoch, {}).setdefault(stage, []).append(
                (start, end)
            )
        elif name == "stall":
            cause = str(args.get("cause", "unknown"))
            per = stalls.setdefault(epoch, {})
            per[cause] = per.get(cause, 0.0) + (end - start) / 1e6
    out: Dict[int, Dict[str, Any]] = {}
    for epoch, by_stage in intervals.items():
        merged = {stage: _merge(ivs) for stage, ivs in by_stage.items()}
        lo = min(s for ivs in merged.values() for s, _ in ivs)
        hi = max(e for ivs in merged.values() for _, e in ivs)
        profile = _active_profile(merged)
        row: Dict[str, Any] = {
            "epoch": epoch,
            "wall_s": (hi - lo) / 1e6,
            "idle_s": (hi - lo - profile["any"]) / 1e6,
            "overlap_s": profile["overlap"] / 1e6,
        }
        for stage in STAGE_ORDER:
            if stage in merged:
                row[f"{stage}_s"] = _total(merged[stage]) / 1e6
                row[f"{stage}_sole_s"] = profile["sole"][stage] / 1e6
        # Critical path: the stage with the largest SOLE-active time —
        # the part of the epoch it alone kept the clock running; a
        # stage fully hidden under another's overlap cannot be the
        # bottleneck no matter how busy it was. Ties (fully-pipelined
        # epochs) break toward the later pipeline stage, which is the
        # one backpressure propagates from.
        present = [s for s in STAGE_ORDER if s in merged]
        row["critical_path"] = max(
            present,
            key=lambda s: (profile["sole"][s], STAGE_ORDER.index(s)),
        )
        for cause, secs in (stalls.get(epoch) or {}).items():
            row[f"stall_{cause}_s"] = secs
        out[epoch] = row
    return out


def build_report(
    events: List[dict],
    epoch_rows: List[Dict[str, str]],
    trial_rows: List[Dict[str, str]],
    bench: Optional[dict],
    baseline: Optional[dict],
    threshold_pct: float,
    stall_threshold_pts: float,
) -> Dict[str, Any]:
    epochs = collect_epochs(events)

    # Join the stats-CSV timings by epoch id — first trial only (the CSV
    # carries one row per (trial, epoch); later trials would overwrite).
    first_trial = next(
        (r.get("trial") for r in epoch_rows if r.get("epoch")), None
    )
    for r in epoch_rows:
        if r.get("trial") != first_trial or not r.get("epoch"):
            continue
        try:
            epoch = int(r["epoch"])
        except ValueError:
            continue
        row = epochs.setdefault(epoch, {"epoch": epoch})
        for src, dst in (
            ("duration", "epoch_s"),
            ("throttle_duration", "throttle_s"),
            ("map_stage_duration", "csv_map_s"),
            ("reduce_stage_duration", "csv_reduce_s"),
        ):
            try:
                row[dst] = float(r[src])
            except (KeyError, ValueError, TypeError):
                pass

    header: Dict[str, Any] = {}
    cur = _bench_fields(bench)
    base = _bench_fields(baseline)
    if cur:
        header.update(cur)
    if trial_rows:
        t = trial_rows[0]
        for k in ("duration", "num_rows", "num_epochs", "row_throughput"):
            if t.get(k):
                header.setdefault(k, t[k])
    rows = [epochs[e] for e in sorted(epochs)]
    if rows:
        totals = {
            s: sum(r.get(f"{s}_s", 0.0) for r in rows) for s in STAGE_ORDER
        }
        header["stage_totals_s"] = {
            s: round(v, 3) for s, v in totals.items() if v
        }
        crit = [r["critical_path"] for r in rows if "critical_path" in r]
        if crit:
            # The run-level call: the stage most often on the critical
            # path across epochs (ties toward the later stage).
            header["critical_path"] = max(
                set(crit),
                key=lambda s: (crit.count(s), STAGE_ORDER.index(s)),
            )

    regressions: List[str] = []
    if base:
        bval, cval = base.get("value"), cur.get("value")
        if bval and cval is not None:
            drop_pct = 100.0 * (float(bval) - float(cval)) / float(bval)
            header["value_vs_baseline_pct"] = round(-drop_pct, 2)
            if drop_pct > threshold_pct:
                regressions.append(
                    f"value {cval} is {drop_pct:.1f}% below baseline "
                    f"{bval} (threshold {threshold_pct}%)"
                )
        bstall, cstall = base.get("stall_pct"), cur.get("stall_pct")
        if bstall is not None and cstall is not None:
            rise = float(cstall) - float(bstall)
            header["stall_vs_baseline_pts"] = round(rise, 2)
            if rise > stall_threshold_pts:
                regressions.append(
                    f"stall_pct {cstall} is {rise:.1f} pts above baseline "
                    f"{bstall} (threshold {stall_threshold_pts} pts)"
                )
    header["regressions"] = regressions
    return {"header": header, "epochs": rows}


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def _fmt(value: Any, width: int = 0) -> str:
    if value is None or value == "":
        out = "-"
    elif isinstance(value, float):
        out = f"{value:.4g}"
    else:
        out = str(value)
    return out.rjust(width) if width else out


_COLUMNS = [
    "epoch", "wall_s", "map_s", "reduce_s", "deliver_s", "consume_s",
    "overlap_s", "idle_s", "critical_path", "stall_upstream_s",
    "stall_staging_s", "throttle_s", "epoch_s",
]


def render(report: Dict[str, Any]) -> str:
    lines = ["epoch critical-path report"]
    for k, v in report["header"].items():
        if k == "regressions":
            continue
        lines.append(f"  {k}: {_fmt(v) if not isinstance(v, dict) else v}")
    rows = report["epochs"]
    if not rows:
        lines.append("  (no per-epoch data in the given inputs)")
    else:
        columns = [
            c
            for c in _COLUMNS
            if any(r.get(c) is not None for r in rows)
            or c in ("epoch", "critical_path")
        ]
        widths = {
            c: max(len(c), *(len(_fmt(r.get(c))) for r in rows))
            for c in columns
        }
        lines.append("")
        lines.append("  ".join(c.rjust(widths[c]) for c in columns))
        lines.append("  ".join("-" * widths[c] for c in columns))
        for r in rows:
            lines.append(
                "  ".join(_fmt(r.get(c), widths[c]) for c in columns)
            )
    for msg in report["header"].get("regressions", []):
        lines.append(f"REGRESSION: {msg}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--trace", help="merged Chrome-trace JSON (telemetry.trace_export)"
    )
    parser.add_argument("--epoch-csv", help="stats.py epoch_stats.csv")
    parser.add_argument("--trial-csv", help="stats.py trial_stats.csv")
    parser.add_argument(
        "--bench", help="current run's bench result JSON (bench.py stdout)"
    )
    parser.add_argument(
        "--baseline",
        help="baseline bench JSON (raw line or BENCH_rXX.json wrapper) "
        "to gate regressions against",
    )
    parser.add_argument(
        "--threshold-pct", type=float, default=10.0,
        help="max tolerated throughput drop vs baseline (%%, default 10)",
    )
    parser.add_argument(
        "--stall-threshold-pts", type=float, default=10.0,
        help="max tolerated stall%% rise vs baseline (points, default 10)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the report as JSON instead of a table",
    )
    args = parser.parse_args(argv)
    if not any((args.trace, args.epoch_csv, args.bench)):
        parser.print_usage(sys.stderr)
        print(
            "epoch_report: need at least one of --trace/--epoch-csv/--bench",
            file=sys.stderr,
        )
        return 2
    try:
        events: List[dict] = []
        if args.trace:
            payload = _load_json(args.trace) or {}
            events = payload.get("traceEvents") or []
        bench = _load_json(args.bench)
        report = build_report(
            events,
            _load_csv(args.epoch_csv),
            _load_csv(args.trial_csv),
            bench,
            _load_json(args.baseline),
            args.threshold_pct,
            args.stall_threshold_pts,
        )
    except (OSError, ValueError) as exc:
        print(f"epoch_report: {exc}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render(report))
    if report["header"].get("regressions"):
        return 1
    if not report["epochs"] and not _bench_fields(bench):
        # Nothing per-epoch AND no headline numbers: the inputs carried
        # zero signal — a gate must not go green on that.
        print(
            "epoch_report: no per-epoch data found in the given inputs",
            file=sys.stderr,
        )
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
