#!/usr/bin/env python
"""Epoch critical-path report: trace + stats CSVs -> per-epoch breakdown.

Answers the operator question the raw artifacts only imply: **which
stage was the bottleneck this epoch?** Ingests the merged Chrome-trace
JSON (``telemetry.trace_export`` / ``bench.py --trace-out``) and
optionally the ``stats.process_stats`` CSVs and the bench result JSON,
then computes per epoch:

* the wall-clock **busy time per pipeline stage** — ``map``, ``reduce``,
  ``deliver`` (reducer-output handoff incl. queue backpressure), and
  ``consume`` (trainer-side ``stage:h2d`` staging) — as merged interval
  unions, so N overlapping map tasks count once;
* the **overlap** structure: how much of the epoch window had >= 2
  stages active (pipelining working) vs exactly one (that stage IS the
  critical path there) vs none (idle: admission throttle, scheduling
  gaps);
* the **critical-path stage**: the stage carrying the largest
  sole-active share of the epoch window (the time nothing else could
  hide), tie-broken toward the later pipeline stage;
* **stall attribution** from the trainer's ``stall`` spans
  (``cause=upstream|staging``) and the epoch CSV's admission-throttle
  column.

The temporal plane (ISSUE 7) joins in when its artifacts are given:

* ``--events <file|dir>`` — the structured NDJSON event log
  (``$RSDL_RUNTIME_DIR/events`` / ``RSDL_EVENTS_DIR``): per-epoch
  retry/recovery event counts land on the epoch rows and the notable
  events (retries, failovers, spills, producer deaths) are listed
  with timestamps — "what happened when throughput dipped";
* ``--task-records <file|dir>`` — the straggler task-duration spool
  (``<metrics spool>/tasks``): a per-epoch **straggler table** (per
  stage: count, median, p99, skew ratio, slowest host, tasks flagged
  over ``k×`` median — ``--straggler-k``, default 4);
* ``--timeseries <file|dir>`` — the sampler's append-only NDJSON
  (``<metrics spool>/ts/timeseries.ndjson``): sample count/span and
  the map-rows rate envelope in the header;
* ``--capacity <file|dir>`` — the capacity-ledger spool
  (``<metrics spool>/capacity``, ISSUE 9): the per-(epoch, tier)
  residency/high-watermark table — which epochs held how many bytes
  where, folded by the same ``telemetry/capacity.py`` ledger the live
  ``/capacity`` endpoint serves;
* ``--profile <dir>`` — the sampling-profiler spool (ISSUE 17,
  ``$RSDL_RUNTIME_DIR/profiles`` of per-process ``profile-*.json``
  aggregates): the merged hot-frames table (self seconds / share,
  per-stage attribution) joins the report, so "which stage stalled"
  and "which frame burned the time" land on the same page.

The interval-union / critical-path math itself is shared with the live
``/critical`` analyzer (``telemetry/critical.py``): the online verdict
and this report agree by construction.

With ``--baseline BENCH_rXX.json`` (either a raw ``bench.py`` JSON line
or the round-capture wrapper with a ``"parsed"`` field) the current
run's headline numbers (``--bench``, same shapes) gate a regression
check: exit **1** when throughput drops more than ``--threshold-pct``
(default 10) or stall% rises more than ``--stall-threshold-pts``
(default 10) — so a CI lane can fail on a real slowdown. Exit 2 on
usage errors, 3 when the inputs contain no per-epoch data (an empty
report must not read as a pass). The temporal artifacts follow the
zero-coverage audit rule: an artifact that was **never produced**
(path absent) is informational — noted, exit unaffected — but one
that is **present yet empty** exits 3, because "the plane was on and
recorded nothing" must not gate green.

Pure stdlib, no server. Example::

    python bench.py --trace-out=/tmp/run.json > /tmp/bench.json
    python tools/epoch_report.py --trace /tmp/run.json \
        --epoch-csv epoch_stats.csv --bench /tmp/bench.json \
        --baseline BENCH_r05.json --events /tmp/spool/events \
        --task-records /tmp/spool/metrics/tasks
"""

from __future__ import annotations

import argparse
import csv
import json
import os as _os
import sys
from typing import Any, Dict, List, Optional, Tuple

# The interval-union / critical-path math is SHARED with the live
# analyzer (telemetry/critical.py serves the same decomposition at
# /critical mid-run) — one implementation, so the online verdict and
# this post-hoc report agree by construction (ISSUE 9). The modules
# are loaded straight from their source files, NOT via the package:
# the package __init__ pulls numpy-dependent modules, and this tool's
# contract is pure stdlib (runs on an analysis box with no deps).
# Both files keep their own telemetry imports function-local for
# exactly this reason; the already-imported package module is reused
# when present (same file either way).


def _load_telemetry_module(name: str):
    import importlib.util

    full = f"ray_shuffling_data_loader_tpu.telemetry.{name}"
    if full in sys.modules:
        return sys.modules[full]
    path = _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "ray_shuffling_data_loader_tpu", "telemetry", f"{name}.py",
    )
    spec = importlib.util.spec_from_file_location(f"_rsdl_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_capacity = _load_telemetry_module("capacity")
_critical = _load_telemetry_module("critical")

# Span-name -> pipeline-stage mapping (docs/observability.md vocabulary).
# map:read is a sub-interval of map and deliver:wait-maps is bookkeeping,
# so neither contributes its own stage.
_SPAN_STAGE = {
    "map": "map",
    "reduce": "reduce",
    "deliver": "deliver",
    "stage:h2d": "consume",
}
STAGE_ORDER = ["map", "reduce", "deliver", "consume"]


def _load_json(path: Optional[str]) -> Optional[dict]:
    if not path:
        return None
    with open(path) as f:
        text = f.read().strip()
    # bench stdout may carry log lines around the one JSON line; take the
    # last line that parses as a JSON object.
    try:
        return json.loads(text)
    except ValueError:
        for line in reversed(text.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    continue
    raise ValueError(f"{path}: no JSON object found")


def _load_csv(path: Optional[str]) -> List[Dict[str, str]]:
    if not path:
        return []
    with open(path, newline="") as f:
        return list(csv.DictReader(f))


def _load_ndjson(
    path: Optional[str], prefix: str, required_key: str
) -> Tuple[Optional[List[dict]], bool]:
    """Records from one NDJSON file or a spool directory of
    ``<prefix>*.ndjson`` files. Returns ``(records, present)`` —
    ``present=False`` means the artifact was never produced (path or
    matching files absent), which the exit-code policy treats as
    informational rather than a failure; an empty-but-present artifact
    returns ``([], True)``."""
    import os

    if not path:
        return None, False
    files: List[str] = []
    if os.path.isdir(path):
        files = [
            os.path.join(path, f)
            for f in sorted(os.listdir(path))
            if f.startswith(prefix) and f.endswith(".ndjson")
        ]
        if not files:
            return None, False
    elif os.path.isfile(path):
        files = [path]
    else:
        return None, False
    out: List[dict] = []
    for fpath in files:
        try:
            with open(fpath) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn append; skip
                    if isinstance(rec, dict) and required_key in rec:
                        out.append(rec)
        except OSError:
            continue
    out.sort(key=lambda r: float(r.get("ts", 0.0)))
    return out, True


def _bench_fields(obj: Optional[dict]) -> Dict[str, Any]:
    """Headline fields from a bench result JSON — accepts both the raw
    one-line shape and the round-capture wrapper (``{"parsed": {...}}``,
    the BENCH_rXX.json format)."""
    if not obj:
        return {}
    if isinstance(obj.get("parsed"), dict):
        obj = obj["parsed"]
    return {
        k: obj[k]
        for k in (
            "value", "stall_pct", "stall_upstream_pct", "stall_staging_pct",
            "total_s", "map_stage_s", "reduce_stage_s", "throttle_s",
            "backend", "error",
        )
        if k in obj
    }


# ---------------------------------------------------------------------------
# Interval math — delegated to telemetry/critical.py (the live /critical
# analyzer); these thin aliases keep the tool's public surface stable.
# Trace timestamps are microseconds; profile_epoch scales them out.
# ---------------------------------------------------------------------------

_merge = _critical.merge_intervals
_total = _critical.intervals_total
_active_profile = _critical.active_profile


def collect_epochs(events: List[dict]) -> Dict[int, Dict[str, Any]]:
    """Per-epoch stage intervals + stall attribution from trace events."""
    intervals: Dict[int, Dict[str, List[Tuple[float, float]]]] = {}
    stalls: Dict[int, Dict[str, float]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        epoch = args.get("epoch")
        if epoch is None:
            continue
        try:
            epoch = int(epoch)
        except (TypeError, ValueError):
            continue
        name = e.get("name")
        start = float(e.get("ts", 0.0))
        end = start + max(0.0, float(e.get("dur", 0.0)))
        stage = _SPAN_STAGE.get(name)
        if stage is not None:
            intervals.setdefault(epoch, {}).setdefault(stage, []).append(
                (start, end)
            )
        elif name == "stall":
            cause = str(args.get("cause", "unknown"))
            per = stalls.setdefault(epoch, {})
            per[cause] = per.get(cause, 0.0) + (end - start) / 1e6
    out: Dict[int, Dict[str, Any]] = {}
    for epoch, by_stage in intervals.items():
        row = _critical.profile_epoch(by_stage, scale=1e6)
        if not row:
            continue
        row["epoch"] = epoch
        for cause, secs in (stalls.get(epoch) or {}).items():
            row[f"stall_{cause}_s"] = secs
        out[epoch] = row
    return out


# Event kinds worth listing with timestamps in the report (the routine
# epoch/trial lifecycle markers only feed the per-epoch counts).
_NOTABLE_EVENT_KINDS = (
    "stage.retry", "recovery", "task.failover", "agent.evicted",
    "store.spill", "producer.died", "epoch.failed", "trial.failed",
    "straggler.wedged",
)


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


def straggler_rows(
    task_records: List[dict], k: float
) -> List[Dict[str, Any]]:
    """The per-(epoch, stage) straggler table: count, median, p99, skew
    ratio, slowest host by mean duration, and how many tasks blew the
    ``k×median`` budget — the post-hoc twin of the live ``/stragglers``
    analysis (telemetry/stragglers.py)."""
    groups: Dict[Tuple[Any, str], List[dict]] = {}
    for rec in task_records:
        key = (rec.get("epoch", "-"), str(rec.get("stage", "?")))
        groups.setdefault(key, []).append(rec)
    rows: List[Dict[str, Any]] = []
    for (epoch, stage), recs in sorted(
        groups.items(), key=lambda kv: (str(kv[0][0]), kv[0][1])
    ):
        durs = sorted(float(r.get("dur_s", 0.0)) for r in recs)
        median = _quantile(durs, 0.5)
        p99 = _quantile(durs, 0.99)
        budget = k * median
        hosts: Dict[str, List[float]] = {}
        for r in recs:
            hosts.setdefault(str(r.get("host", "?")), []).append(
                float(r.get("dur_s", 0.0))
            )
        host_means = {h: sum(v) / len(v) for h, v in hosts.items()}
        flagged = [
            r for r in recs if float(r.get("dur_s", 0.0)) > budget
        ] if median > 0 else []
        rows.append(
            {
                "epoch": epoch,
                "stage": stage,
                "tasks": len(recs),
                "median_s": round(median, 4),
                "p99_s": round(p99, 4),
                "skew": round(p99 / median, 2) if median > 0 else None,
                "flagged": len(flagged),
                "slowest_host": (
                    max(host_means, key=host_means.get)
                    if host_means else None
                ),
                "flagged_tasks": sorted(
                    flagged, key=lambda r: -float(r.get("dur_s", 0.0))
                )[:8],
            }
        )
    return rows


def _join_events(
    epochs: Dict[int, Dict[str, Any]], event_records: List[dict]
) -> Dict[str, Any]:
    """Fold the event log into the per-epoch rows (retry/recovery
    counts) and return the run-level summary (counts by kind + the
    notable events, timestamped)."""
    by_kind: Dict[str, int] = {}
    notable: List[dict] = []
    for rec in event_records:
        kind = str(rec.get("kind", "unknown"))
        by_kind[kind] = by_kind.get(kind, 0) + 1
        epoch = rec.get("epoch")
        if epoch is not None:
            try:
                row = epochs.setdefault(
                    int(epoch), {"epoch": int(epoch)}
                )
            except (TypeError, ValueError):
                row = None
            if row is not None:
                if kind == "stage.retry":
                    row["retries"] = row.get("retries", 0) + 1
                elif kind in ("recovery", "task.failover"):
                    row["recoveries"] = row.get("recoveries", 0) + 1
        if kind in _NOTABLE_EVENT_KINDS:
            notable.append(rec)
    return {"by_kind": by_kind, "notable": notable[-40:]}


def _timeseries_summary(samples: List[dict]) -> Dict[str, Any]:
    """Header-level envelope of the sampler history: sample count,
    span, and the map-rows rate min/mean/max (the dip the events
    explain)."""
    out: Dict[str, Any] = {"samples": len(samples)}
    if not samples:
        return out
    ts0 = float(samples[0].get("ts", 0.0))
    ts1 = float(samples[-1].get("ts", 0.0))
    out["span_s"] = round(ts1 - ts0, 1)
    rates = []
    for s in samples:
        entry = (s.get("metrics") or {}).get("shuffle.map_rows")
        if entry and "rate" in entry:
            rates.append(float(entry["rate"]))
    if rates:
        out["map_rows_rate"] = {
            "min": round(min(rates), 2),
            "mean": round(sum(rates) / len(rates), 2),
            "max": round(max(rates), 2),
        }
    return out


def build_report(
    events: List[dict],
    epoch_rows: List[Dict[str, str]],
    trial_rows: List[Dict[str, str]],
    bench: Optional[dict],
    baseline: Optional[dict],
    threshold_pct: float,
    stall_threshold_pts: float,
    event_records: Optional[List[dict]] = None,
    task_records: Optional[List[dict]] = None,
    ts_samples: Optional[List[dict]] = None,
    capacity_records: Optional[List[dict]] = None,
    straggler_k: float = 4.0,
) -> Dict[str, Any]:
    epochs = collect_epochs(events)

    # Join the stats-CSV timings by epoch id — first trial only (the CSV
    # carries one row per (trial, epoch); later trials would overwrite).
    first_trial = next(
        (r.get("trial") for r in epoch_rows if r.get("epoch")), None
    )
    for r in epoch_rows:
        if r.get("trial") != first_trial or not r.get("epoch"):
            continue
        try:
            epoch = int(r["epoch"])
        except ValueError:
            continue
        row = epochs.setdefault(epoch, {"epoch": epoch})
        for src, dst in (
            ("duration", "epoch_s"),
            ("throttle_duration", "throttle_s"),
            ("map_stage_duration", "csv_map_s"),
            ("reduce_stage_duration", "csv_reduce_s"),
        ):
            try:
                row[dst] = float(r[src])
            except (KeyError, ValueError, TypeError):
                pass

    header: Dict[str, Any] = {}
    cur = _bench_fields(bench)
    base = _bench_fields(baseline)
    if cur:
        header.update(cur)
    events_summary = None
    if event_records is not None:
        events_summary = _join_events(epochs, event_records)
        header["events_by_kind"] = events_summary["by_kind"]
    if ts_samples is not None:
        header["timeseries"] = _timeseries_summary(ts_samples)
    if trial_rows:
        t = trial_rows[0]
        for k in ("duration", "num_rows", "num_epochs", "row_throughput"):
            if t.get(k):
                header.setdefault(k, t[k])
    rows = [epochs[e] for e in sorted(epochs)]
    if rows:
        totals = {
            s: sum(r.get(f"{s}_s", 0.0) for r in rows) for s in STAGE_ORDER
        }
        header["stage_totals_s"] = {
            s: round(v, 3) for s, v in totals.items() if v
        }
        # The run-level call: the stage most often on the critical
        # path across epochs (ties toward the later stage) — the same
        # fold the live /critical endpoint serves.
        run_crit = _critical.run_critical_path(rows)
        if run_crit is not None:
            header["critical_path"] = run_crit

    regressions: List[str] = []
    if base:
        bval, cval = base.get("value"), cur.get("value")
        if bval and cval is not None:
            drop_pct = 100.0 * (float(bval) - float(cval)) / float(bval)
            header["value_vs_baseline_pct"] = round(-drop_pct, 2)
            if drop_pct > threshold_pct:
                regressions.append(
                    f"value {cval} is {drop_pct:.1f}% below baseline "
                    f"{bval} (threshold {threshold_pct}%)"
                )
        bstall, cstall = base.get("stall_pct"), cur.get("stall_pct")
        if bstall is not None and cstall is not None:
            rise = float(cstall) - float(bstall)
            header["stall_vs_baseline_pts"] = round(rise, 2)
            if rise > stall_threshold_pts:
                regressions.append(
                    f"stall_pct {cstall} is {rise:.1f} pts above baseline "
                    f"{bstall} (threshold {stall_threshold_pts} pts)"
                )
    header["regressions"] = regressions
    report: Dict[str, Any] = {"header": header, "epochs": rows}
    if events_summary is not None:
        report["events"] = events_summary["notable"]
    if task_records is not None:
        report["stragglers"] = straggler_rows(task_records, straggler_k)
    if capacity_records is not None:
        report["capacity"] = capacity_rows(capacity_records)
    return report


def capacity_rows(capacity_records: List[dict]) -> List[Dict[str, Any]]:
    """The per-(epoch, tier) residency/watermark table from the
    capacity-ledger spool — the post-hoc twin of the live ``/capacity``
    view (the fold is telemetry/capacity.py's, shared)."""
    folded = _capacity.ledger(capacity_records)
    rows: List[Dict[str, Any]] = []
    for epoch in sorted(
        folded.get("epochs", {}), key=_capacity.epoch_sort_key
    ):
        for tier, cell in sorted(folded["epochs"][epoch].items()):
            rows.append(
                {
                    "epoch": epoch,
                    "tier": tier,
                    "resident_mb": round(
                        cell.get("resident_bytes", 0) / 1e6, 3
                    ),
                    "hwm_mb": round(cell.get("hwm_bytes", 0) / 1e6, 3),
                    "created_mb": round(
                        cell.get("created_bytes", 0) / 1e6, 3
                    ),
                    "fetched_mb": round(
                        cell.get("fetched_bytes", 0) / 1e6, 3
                    ),
                    "freed_mb": round(cell.get("freed_bytes", 0) / 1e6, 3),
                    "segments": cell.get("segments", 0),
                    "oldest_age_s": cell.get("oldest_age_s"),
                }
            )
    return rows


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def _fmt(value: Any, width: int = 0) -> str:
    if value is None or value == "":
        out = "-"
    elif isinstance(value, float):
        out = f"{value:.4g}"
    else:
        out = str(value)
    return out.rjust(width) if width else out


_COLUMNS = [
    "epoch", "wall_s", "map_s", "reduce_s", "deliver_s", "consume_s",
    "overlap_s", "idle_s", "critical_path", "stall_upstream_s",
    "stall_staging_s", "throttle_s", "epoch_s", "retries", "recoveries",
]

_STRAGGLER_COLUMNS = [
    "epoch", "stage", "tasks", "median_s", "p99_s", "skew", "flagged",
    "slowest_host",
]

_CAPACITY_COLUMNS = [
    "epoch", "tier", "resident_mb", "hwm_mb", "created_mb", "fetched_mb",
    "freed_mb", "segments", "oldest_age_s",
]


def render(report: Dict[str, Any]) -> str:
    lines = ["epoch critical-path report"]
    for k, v in report["header"].items():
        if k == "regressions":
            continue
        lines.append(f"  {k}: {_fmt(v) if not isinstance(v, dict) else v}")
    rows = report["epochs"]
    if not rows:
        lines.append("  (no per-epoch data in the given inputs)")
    else:
        columns = [
            c
            for c in _COLUMNS
            if any(r.get(c) is not None for r in rows)
            or c in ("epoch", "critical_path")
        ]
        widths = {
            c: max(len(c), *(len(_fmt(r.get(c))) for r in rows))
            for c in columns
        }
        lines.append("")
        lines.append("  ".join(c.rjust(widths[c]) for c in columns))
        lines.append("  ".join("-" * widths[c] for c in columns))
        for r in rows:
            lines.append(
                "  ".join(_fmt(r.get(c), widths[c]) for c in columns)
            )
    straggler_table = report.get("stragglers")
    if straggler_table is not None:
        lines.append("")
        lines.append("straggler table (per epoch/stage)")
        if not straggler_table:
            lines.append("  (no task records)")
        else:
            widths = {
                c: max(
                    len(c),
                    *(len(_fmt(r.get(c))) for r in straggler_table),
                )
                for c in _STRAGGLER_COLUMNS
            }
            lines.append(
                "  ".join(c.rjust(widths[c]) for c in _STRAGGLER_COLUMNS)
            )
            lines.append(
                "  ".join("-" * widths[c] for c in _STRAGGLER_COLUMNS)
            )
            for r in straggler_table:
                lines.append(
                    "  ".join(
                        _fmt(r.get(c), widths[c])
                        for c in _STRAGGLER_COLUMNS
                    )
                )
                for t in r.get("flagged_tasks", []):
                    lines.append(
                        f"    STRAGGLER: host={t.get('host')} "
                        f"pid={t.get('pid')} dur={_fmt(t.get('dur_s'))}s "
                        f"(median {_fmt(r.get('median_s'))}s)"
                    )
    capacity_table = report.get("capacity")
    if capacity_table is not None:
        lines.append("")
        lines.append("capacity ledger (per epoch/tier)")
        if not capacity_table:
            lines.append("  (no ledger records)")
        else:
            widths = {
                c: max(
                    len(c),
                    *(len(_fmt(r.get(c))) for r in capacity_table),
                )
                for c in _CAPACITY_COLUMNS
            }
            lines.append(
                "  ".join(c.rjust(widths[c]) for c in _CAPACITY_COLUMNS)
            )
            lines.append(
                "  ".join("-" * widths[c] for c in _CAPACITY_COLUMNS)
            )
            for r in capacity_table:
                lines.append(
                    "  ".join(
                        _fmt(r.get(c), widths[c])
                        for c in _CAPACITY_COLUMNS
                    )
                )
    profile = report.get("profile")
    if profile is not None:
        lines.append("")
        lines.append(
            "hot frames (profile)  samples=%d sampled=%.1fs sources=%d"
            % (
                profile.get("samples", 0),
                profile.get("seconds", 0.0),
                profile.get("sources", 0),
            )
        )
        for row in profile.get("top", []):
            stages = ",".join(
                f"{k}={v:.1f}s"
                for k, v in (row.get("stages") or {}).items()
            )
            lines.append(
                f"  {row['self_s']:>7.1f}s {row['self_frac']:>6.1%}  "
                f"{row['frame']}" + (f"  [{stages}]" if stages else "")
            )
    notable = report.get("events")
    if notable:
        lines.append("")
        lines.append("notable events")
        import time as _time

        for rec in notable:
            stamp = _time.strftime(
                "%H:%M:%S", _time.localtime(float(rec.get("ts", 0.0)))
            )
            detail = " ".join(
                f"{k}={rec[k]}"
                for k in ("epoch", "stage", "attempt", "counter",
                          "error", "rank", "agent", "nbytes", "pid",
                          "age_s")
                if k in rec
            )
            lines.append(
                f"  {stamp}  {rec.get('kind', '?'):<18} {detail}"[:118]
            )
    for msg in report["header"].get("regressions", []):
        lines.append(f"REGRESSION: {msg}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--trace", help="merged Chrome-trace JSON (telemetry.trace_export)"
    )
    parser.add_argument("--epoch-csv", help="stats.py epoch_stats.csv")
    parser.add_argument("--trial-csv", help="stats.py trial_stats.csv")
    parser.add_argument(
        "--bench", help="current run's bench result JSON (bench.py stdout)"
    )
    parser.add_argument(
        "--baseline",
        help="baseline bench JSON (raw line or BENCH_rXX.json wrapper) "
        "to gate regressions against",
    )
    parser.add_argument(
        "--events",
        help="structured event-log NDJSON (file, or the events spool "
        "dir of events-*.ndjson) to join per epoch",
    )
    parser.add_argument(
        "--task-records",
        help="straggler task-duration NDJSON (file, or the "
        "<metrics spool>/tasks dir of tasks-*.ndjson) for the "
        "per-epoch straggler table",
    )
    parser.add_argument(
        "--timeseries",
        help="timeseries sampler NDJSON (file, or the dir holding "
        "ts/timeseries.ndjson) for the header rate envelope",
    )
    parser.add_argument(
        "--capacity",
        help="capacity-ledger NDJSON (file, or the <metrics spool>/"
        "capacity dir of ledger-*.ndjson) for the per-epoch "
        "residency/watermark table",
    )
    parser.add_argument(
        "--profile",
        help="sampling-profiler spool dir of profile-*.json "
        "per-process aggregates ($RSDL_RUNTIME_DIR/profiles) for "
        "the hot-frames table",
    )
    parser.add_argument(
        "--straggler-k", type=float, default=4.0,
        help="straggler budget: flag tasks slower than K x the "
        "(epoch, stage) median (default 4)",
    )
    parser.add_argument(
        "--job", default=None,
        help="multi-job service (ISSUE 15): restrict the events / "
        "task-records / capacity-ledger joins to ONE job (exact job "
        "id, or a substring matching it) so per-job views don't "
        "interleave concurrent tenants' same-numbered epochs",
    )
    parser.add_argument(
        "--threshold-pct", type=float, default=10.0,
        help="max tolerated throughput drop vs baseline (%%, default 10)",
    )
    parser.add_argument(
        "--stall-threshold-pts", type=float, default=10.0,
        help="max tolerated stall%% rise vs baseline (points, default 10)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the report as JSON instead of a table",
    )
    args = parser.parse_args(argv)
    if not any((args.trace, args.epoch_csv, args.bench, args.events,
                args.task_records, args.timeseries, args.capacity,
                args.profile)):
        parser.print_usage(sys.stderr)
        print(
            "epoch_report: need at least one of --trace/--epoch-csv/"
            "--bench/--events/--task-records/--timeseries/--capacity/"
            "--profile",
            file=sys.stderr,
        )
        return 2
    # The temporal artifacts distinguish "never produced" (absent path:
    # the plane was off — informational) from "present but empty" (the
    # plane was on and recorded nothing: exit 3, the zero-coverage
    # rule). Resolve a --timeseries DIR to its ts/timeseries.ndjson.
    ts_path = args.timeseries
    if ts_path and not ts_path.endswith(".ndjson"):
        for candidate in (
            _os.path.join(ts_path, "ts", "timeseries.ndjson"),
            _os.path.join(ts_path, "timeseries.ndjson"),
        ):
            if _os.path.exists(candidate):
                ts_path = candidate
                break
    absent_notes: List[str] = []
    empty_present: List[str] = []

    def _temporal(path, prefix, required_key, label):
        records, present = _load_ndjson(path, prefix, required_key)
        if path and not present:
            absent_notes.append(
                f"note: no {label} present at {path} (plane off?) — "
                "informational"
            )
            return None
        if present and not records:
            empty_present.append(
                f"{label} at {path} is present but empty — the plane "
                "was on and recorded nothing"
            )
        return records

    def _job_filter(records):
        """Keep one tenant's records. Job-stamped records must match;
        unstamped ones (session-level ops — store samples, cleanup)
        are kept: dropping them would hide session-wide capacity."""
        if records is None or not args.job:
            return records
        return [
            r
            for r in records
            if "job" not in r or args.job in str(r.get("job"))
        ]

    event_records = _job_filter(
        _temporal(args.events, "events-", "kind", "events")
    )
    task_records = _job_filter(
        _temporal(args.task_records, "tasks-", "dur_s", "task records")
    )
    ts_samples = _temporal(
        ts_path, "timeseries", "metrics", "timeseries"
    )
    # A --capacity DIR may be the metrics spool itself; resolve to its
    # capacity/ subdir of ledger-*.ndjson when present.
    cap_path = args.capacity
    if cap_path and _os.path.isdir(cap_path):
        sub = _os.path.join(cap_path, "capacity")
        if _os.path.isdir(sub):
            cap_path = sub
    capacity_records = _job_filter(
        _temporal(cap_path, "ledger-", "op", "capacity ledger")
    )

    def _profile_join(path):
        """The profiler spool is per-process JSON aggregates
        (``profile-*.json``), not NDJSON, so it gets its own loader —
        same zero-coverage policy as ``_temporal``: spool never
        produced = note + informational, spool present with zero
        samples = the plane was armed and recorded nothing (exit 3)."""
        if not path:
            return None
        present = _os.path.isdir(path) and any(
            f.startswith("profile-") and f.endswith(".json")
            for f in _os.listdir(path)
        )
        if not present:
            absent_notes.append(
                f"note: no profile spool present at {path} "
                "(plane off?) — informational"
            )
            return None
        profiler = _load_telemetry_module("profiler")
        agg = profiler.aggregate_profiles(
            records=profiler.load_records(path)
        )
        if not agg["stacks"]:
            empty_present.append(
                f"profile spool at {path} is present but empty — the "
                "plane was on and recorded nothing"
            )
            return None
        return {
            "samples": agg["samples"],
            "seconds": round(agg["seconds"], 3),
            "sources": len(agg["sources"]),
            "top": profiler.top_table(agg, n=5),
        }

    profile_view = _profile_join(args.profile)
    try:
        events: List[dict] = []
        if args.trace:
            payload = _load_json(args.trace) or {}
            events = payload.get("traceEvents") or []
        bench = _load_json(args.bench)
        report = build_report(
            events,
            _load_csv(args.epoch_csv),
            _load_csv(args.trial_csv),
            bench,
            _load_json(args.baseline),
            args.threshold_pct,
            args.stall_threshold_pts,
            event_records=event_records,
            task_records=task_records,
            ts_samples=ts_samples,
            capacity_records=capacity_records,
            straggler_k=args.straggler_k,
        )
    except (OSError, ValueError) as exc:
        print(f"epoch_report: {exc}", file=sys.stderr)
        return 2
    if profile_view is not None:
        report["profile"] = profile_view
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render(report))
    for note in absent_notes:
        print(f"epoch_report: {note}", file=sys.stderr)
    if report["header"].get("regressions"):
        return 1
    if empty_present:
        for msg in empty_present:
            print(f"epoch_report: {msg}", file=sys.stderr)
        return 3
    has_temporal = bool(
        event_records or task_records or ts_samples or capacity_records
        or profile_view
    )
    if (
        not report["epochs"]
        and not _bench_fields(bench)
        and not has_temporal
    ):
        # Nothing per-epoch AND no headline numbers: the inputs carried
        # zero signal — a gate must not go green on that.
        print(
            "epoch_report: no per-epoch data found in the given inputs",
            file=sys.stderr,
        )
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
