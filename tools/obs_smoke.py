#!/usr/bin/env python
"""Exit-code-gated smoke for the temporal + decision obs planes (CI).

Starts a small multi-epoch shuffle with the obs endpoint up and, while
it is MID-FLIGHT, asserts the acceptance surface end to end:

1. ``/timeseries?name=rsdl_shuffle_map_rows`` serves a non-empty rate
   series (the sampler is running, counter deltas became rates);
2. ``tools/rsdl_top.py --once --json`` renders a frame from the live
   endpoint (exit 0, parseable);
3. (ISSUE 9) ``/capacity`` shows live per-epoch residency from the
   store's ledger hooks, ``/critical`` names a critical-path stage
   from the task records, and ``/alerts`` lists the rule pack;
4. (ISSUE 9) a deliberately-tripped custom SLO rule (threshold on a
   gauge this script flips) FIRES mid-flight and RESOLVES after the
   gauge clears — fire/resolve both visible on ``/alerts`` and as
   ``alert.fired``/``alert.resolved`` events on ``/events``;
5. after completion, ``/events`` carries the full epoch lifecycle
   (``epoch.start``/``epoch.done`` per epoch, one ``trial.done``);
6. (ISSUE 16) with the service plane armed, ``/jobs`` lists the
   running tenant mid-flight, and after completion
   ``/events?job=<id>`` returns that tenant's stamped events while a
   bogus job id returns none (the fleet filter actually filters);
7. (ISSUE 17) with ``RSDL_PROFILE`` armed, ``/profile`` merges the
   spools of at least two distinct processes mid-flight (driver +
   task workers) and attributes nonzero self time to a shuffle stage
   frame — the cluster-wide sampler actually samples the cluster.

Run from the repo root (``run_ci_tests.sh`` obs lane)::

    RSDL_METRICS=1 python tools/obs_smoke.py

``--federation`` (ISSUE 19) runs the cross-host gate instead: a second
host process joins over TCP with NO shared spool tree (its
``RSDL_RUNTIME_DIR`` is its own), ``RSDL_RELAY=auto`` ships its spools
to the driver, and MID-FLIGHT the driver's ``/metrics`` must show
metric series from >= 2 distinct ``host=`` label values while
``/healthz`` shows the relay sink with a fresh (non-stale) source —
the relay lane in ``run_ci_tests.sh``::

    RSDL_METRICS=1 python tools/obs_smoke.py --federation

Exits non-zero on any miss — the exit code IS the gate.
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request


def main() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    os.environ.setdefault("RSDL_METRICS", "1")
    # Continuous profiling plane on (ISSUE 17): every process samples.
    os.environ.setdefault("RSDL_PROFILE", "1")
    os.environ["RSDL_OBS_PORT"] = str(port)
    # Sample fast so a short CI shuffle yields several ring entries.
    os.environ.setdefault("RSDL_TS_PERIOD_S", "0.2")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # Service plane on (ISSUE 16): the shuffle auto-registers a tenant,
    # so /jobs and the job= event filter have a real job id to show.
    os.environ.setdefault("RSDL_SERVICE", "auto")
    # The deliberately-tripped rule (ISSUE 9): a threshold on a gauge
    # this script flips mid-flight — rides alongside the default pack.
    os.environ["RSDL_SLO_RULES"] = json.dumps([
        {"name": "smoke_trip", "kind": "threshold",
         "metric": "obs.smoke_trip", "op": ">", "value": 0},
    ])

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.data_generation import generate_file
    from ray_shuffling_data_loader_tpu.shuffle import (
        BatchConsumer,
        shuffle,
    )

    data_dir = tempfile.mkdtemp(prefix="rsdl-obs-smoke-")
    files = [
        generate_file(i, i * 2048, 2048, 1, data_dir)[0] for i in range(2)
    ]
    runtime.init(num_workers=2)

    class _Consumer(BatchConsumer):
        def __init__(self):
            self.done = threading.Event()

        def consume(self, rank, epoch, batches):
            time.sleep(0.2)  # keep the run observably mid-flight

        def producer_done(self, rank, epoch):
            if epoch == 2:
                self.done.set()

        def wait_until_ready(self, epoch):
            pass

        def wait_until_all_epochs_done(self):
            assert self.done.wait(timeout=180)
            assert release.wait(timeout=180)

    errors = []
    # The mid-flight assertions below race a ~seconds-long run; the
    # consumer holds shuffle() open (so the tenant stays *running* on
    # /jobs) until the main thread releases it.
    release = threading.Event()

    def _run():
        try:
            shuffle(
                files, _Consumer(), num_epochs=3, num_reducers=2,
                num_trainers=1, seed=7,
            )
        except BaseException as exc:
            errors.append(exc)

    thread = threading.Thread(target=_run, daemon=True)
    thread.start()

    base = f"http://127.0.0.1:{port}"

    def get(path):
        with urllib.request.urlopen(base + path, timeout=10) as resp:
            return json.loads(resp.read().decode())

    deadline = time.time() + 120
    rate_seen = top_out = None
    while time.time() < deadline:
        ts = get("/timeseries?name=rsdl_shuffle_map_rows")
        series = ts.get("series") or {}
        rates = [
            p for pts in series.values() for p in pts if p.get("rate")
        ]
        if rates and top_out is None:
            rate_seen = rates[-1]
            top_out = subprocess.run(
                [
                    sys.executable,
                    os.path.join(repo, "tools", "rsdl_top.py"),
                    "--url", base, "--once", "--json",
                ],
                capture_output=True,
                text=True,
            )
            break
        time.sleep(0.2)
    assert rate_seen, (
        "no non-empty rsdl_shuffle_map_rows rate series mid-flight"
    )
    assert top_out is not None and top_out.returncode == 0, (
        top_out and top_out.stderr
    )
    frame = json.loads(top_out.stdout)
    assert frame["status"] is not None

    # Decision plane, mid-flight (ISSUE 9): the capacity ledger shows
    # live residency from the store hooks, the critical analyzer names
    # a stage, and the alert engine serves its rule pack.
    cap = get("/capacity")
    assert cap.get("ops", 0) > 0, "capacity ledger saw no store ops"
    assert cap.get("epochs"), "no per-epoch residency in /capacity"
    crit_deadline = time.time() + 60
    crit_path = None
    while time.time() < crit_deadline and crit_path is None:
        crit = get("/critical")
        crit_path = (crit.get("current") or {}).get("critical_path")
        if crit_path is None:
            time.sleep(0.2)
    assert crit_path, "no critical-path verdict mid-flight"
    alerts = get("/alerts")
    rule_names = {r["name"] for r in alerts.get("rules", [])}
    assert "wedged_worker" in rule_names, rule_names
    assert "smoke_trip" in rule_names, rule_names

    # Fleet view, mid-flight (ISSUE 16): the auto-registered service
    # tenant shows on /jobs as running, with a real job id.
    jobs_deadline = time.time() + 60
    smoke_jid = None
    while time.time() < jobs_deadline and smoke_jid is None:
        rows = get("/jobs").get("jobs") or []
        running_rows = [r for r in rows if r.get("running")]
        if running_rows:
            smoke_jid = running_rows[0]["job_id"]
        else:
            time.sleep(0.2)
    assert smoke_jid, "no running tenant on /jobs mid-flight"

    # Profiling plane, mid-flight (ISSUE 17): the merged /profile view
    # must cover >= 2 distinct processes (driver + at least one task
    # worker spool) and pin nonzero self time on a shuffle-stage frame.
    prof_deadline = time.time() + 60
    prof_procs = prof_staged = None
    while time.time() < prof_deadline:
        prof = get("/profile")
        procs = {
            (s.get("host"), s.get("pid"))
            for s in (prof.get("sources") or [])
            if s.get("pid")
        }
        staged = [
            r for r in (prof.get("top") or [])
            if any(v > 0 for v in (r.get("stages") or {}).values())
        ]
        if len(procs) >= 2 and staged:
            prof_procs, prof_staged = len(procs), staged[0]
            break
        time.sleep(0.2)
    assert prof_procs, (
        "/profile never showed >=2 process sources plus a stage-"
        "attributed frame mid-flight"
    )

    # Trip the custom rule, wait for it to FIRE on /alerts, clear the
    # gauge, wait for it to RESOLVE — both transitions event-logged.
    from ray_shuffling_data_loader_tpu.telemetry import metrics

    metrics.registry.gauge("obs.smoke_trip").set(1)
    fired = _wait_alert_state(get, "smoke_trip", active=True)
    assert fired, "smoke_trip never fired"
    metrics.registry.gauge("obs.smoke_trip").set(0)
    resolved = _wait_alert_state(get, "smoke_trip", active=False)
    assert resolved, "smoke_trip never resolved"

    release.set()
    thread.join(timeout=180)
    assert not thread.is_alive() and not errors, errors
    kinds = get("/events")["by_kind"]
    assert kinds.get("epoch.start", 0) >= 3, kinds
    assert kinds.get("epoch.done", 0) >= 3, kinds
    assert kinds.get("trial.done") == 1, kinds
    assert kinds.get("alert.fired", 0) >= 1, kinds
    assert kinds.get("alert.resolved", 0) >= 1, kinds
    # The job= filter filters (ISSUE 16): the real tenant's stamped
    # events come back, a bogus id returns nothing.
    job_events = get(f"/events?job={smoke_jid}")
    assert job_events["count"] > 0, "no events for the tenant's job id"
    assert all(
        e.get("job") == smoke_jid for e in job_events["events"]
    ), "job filter leaked other tenants' events"
    assert get("/events?job=no-such-job")["count"] == 0
    print(
        "obs smoke ok: rate=%.1f rows/s, critical=%s, profile=%d procs"
        " (hot %s), events=%s"
        % (rate_seen["rate"], crit_path, prof_procs,
           prof_staged["frame"], kinds)
    )
    runtime.shutdown()
    return 0


_FED_WORKER_SCRIPT = r"""
import os, sys, time
sys.path.insert(0, sys.argv[1])
addr_file = sys.argv[2]
from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.runtime import cluster

deadline = time.time() + 60
while not os.path.exists(addr_file):
    if time.time() > deadline:
        sys.exit(2)
    time.sleep(0.1)
with open(addr_file) as f:
    address = f.read().strip()
runtime.init(address=address, num_workers=2)
cluster.serve_forever()
runtime.shutdown()
"""


def federation_main() -> int:
    """The ISSUE 19 gate: with a remote host on a DISJOINT spool tree,
    the driver's /metrics shows >= 2 distinct host= labels mid-flight
    (its own records plus the worker's relayed ones) and /healthz shows
    the relay sink feeding from a fresh source."""
    import re

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    os.environ.setdefault("RSDL_METRICS", "1")
    os.environ["RSDL_RELAY"] = "auto"
    os.environ["RSDL_OBS_PORT"] = str(port)
    os.environ.setdefault("RSDL_TS_PERIOD_S", "0.2")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.data_generation import generate_file
    from ray_shuffling_data_loader_tpu.shuffle import (
        BatchConsumer,
        shuffle,
    )

    ctx = runtime.init_cluster(advertise_host="127.0.0.1", num_workers=2)
    tmp = tempfile.mkdtemp(prefix="rsdl-fed-smoke-")
    addr_file = os.path.join(tmp, "head_address")
    with open(addr_file + ".tmp", "w") as f:
        f.write(ctx.cluster.address)
    os.rename(addr_file + ".tmp", addr_file)

    # The worker host must NOT inherit this session's spool tree — a
    # shared RSDL_RUNTIME_DIR would let the files federate by
    # filesystem and the relay would (correctly) skip them all.
    worker_env = {
        k: v for k, v in os.environ.items()
        if k not in ("RSDL_RUNTIME_DIR", "RSDL_OBS_PORT")
    }
    worker = subprocess.Popen(
        [sys.executable, "-c", _FED_WORKER_SCRIPT, repo, addr_file],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=worker_env,
    )
    try:
        deadline = time.time() + 60
        while len(ctx.cluster.registry.call("hosts")) < 2:
            assert time.time() < deadline, "worker host never joined"
            assert worker.poll() is None, "worker died while joining"
            time.sleep(0.2)

        data_dir = tempfile.mkdtemp(prefix="rsdl-fed-data-")
        files = [
            generate_file(i, i * 2048, 2048, 1, data_dir)[0]
            for i in range(2)
        ]

        class _Consumer(BatchConsumer):
            def consume(self, rank, epoch, batches):
                time.sleep(0.2)  # keep the run observably mid-flight

            def producer_done(self, rank, epoch):
                pass

            def wait_until_ready(self, epoch):
                pass

            def wait_until_all_epochs_done(self):
                pass

        errors = []

        def _run():
            try:
                shuffle(
                    files, _Consumer(), num_epochs=3, num_reducers=2,
                    num_trainers=1, seed=7,
                )
            except BaseException as exc:
                errors.append(exc)

        thread = threading.Thread(target=_run, daemon=True)
        thread.start()

        base = f"http://127.0.0.1:{port}"

        def get_text(path):
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return r.read().decode()

        # MID-FLIGHT: >= 2 distinct host= label values on /metrics.
        deadline = time.time() + 120
        hosts_seen = set()
        while time.time() < deadline and thread.is_alive():
            hosts_seen = set(
                re.findall(r'host="([^"]+)"', get_text("/metrics"))
            )
            if len(hosts_seen) >= 2:
                break
            time.sleep(0.3)
        assert len(hosts_seen) >= 2, (
            f"/metrics never federated >=2 hosts mid-flight: "
            f"{sorted(hosts_seen)}"
        )

        # The sink is live and its source is fresh.
        hz = json.loads(get_text("/healthz"))
        rl = hz.get("relay") or {}
        assert rl.get("role") == "sink", rl
        assert rl.get("hosts"), "relay sink has no sources"
        assert not any(
            rec.get("stale") for rec in rl["hosts"].values()
        ), rl

        thread.join(timeout=180)
        assert not thread.is_alive() and not errors, errors
        print(
            "federation smoke ok: hosts=%s, relay=%s"
            % (sorted(hosts_seen), rl["hosts"])
        )
        runtime.shutdown()
        return 0
    finally:
        worker.kill()
        worker.wait()


def _wait_alert_state(get, rule, active, timeout_s=60.0):
    """Poll /alerts until ``rule`` reaches the wanted active state
    (the sampler tick drives evaluation); True on success."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        for row in get("/alerts").get("rules", []):
            if row["name"] == rule and bool(row["active"]) == active:
                return True
        time.sleep(0.2)
    return False


if __name__ == "__main__":
    if "--federation" in sys.argv[1:]:
        sys.exit(federation_main())
    sys.exit(main())
