#!/usr/bin/env python
"""Time-travel debugging: re-run one journaled epoch and prove (or
refute) that it reproduces.

A run recorded under ``RSDL_JOURNAL`` (runtime/journal.py) carries
everything that determined its delivered stream — seed, plan spec,
topology, column set, fault schedule — plus the per-epoch audit
verdicts journaled at the reconcile barrier, including the
order-sensitive per-rank ``delivered_seq`` digest. This tool replays
epoch N of such a run on a fresh runtime under the *recorded* identity
(same seed, same ``RSDL_SHUFFLE_PLAN``, same ``RSDL_FAULTS`` schedule
and ``RSDL_FAULTS_SEED``), reconciles the replay's digests, and
compares them field-for-field against the journal:

* match → exit 0 (the epoch reproduces — determinism held through
  whatever faults the schedule injected);
* divergence → exit 1, with the differing fields named in the JSON
  report (a reproducibility bug, or a replay environment that differs
  from the recorded one in a stream-determining knob);
* usage / journal errors → exit 2.

One ``epoch.replayed`` event is emitted per compared epoch (when the
events plane is armed), so replays are visible in the run's timeline.

Usage::

    python tools/replay.py <journal-file-or-dir> [--epoch N]
        [--workers W] [--json OUT]

``--epoch`` defaults to every epoch the journal holds a verdict for.
The journal must be a COMPLETED run (verdicts are journaled at the
end-of-run reconcile); replaying a suspended run's journal exits 2 —
resume it first (``RSDL_RESUME=auto``), then replay the resumed run's
journal. The replay itself never journals and never resumes: it is a
read-only re-execution of recorded history.

See docs/robustness.md ("Preemption, suspend/resume, and replay").
"""

import argparse
import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# The digest fields a replay must reproduce, in report order.
# ``delivered_seq`` is THE acceptance digest: order-sensitive per-rank
# fold of every delivered row window. The coverage digests and row
# counts pin the map/reduce sides too.
_COMPARED = (
    "delivered_seq",
    "delivered_digest",
    "map_digest",
    "reduce_digest",
    "rows_mapped",
    "rows_reduced",
    "rows_delivered",
)


def _die(msg: str) -> "NoReturn":  # noqa: F821 — py38-friendly
    print(f"replay: {msg}", file=sys.stderr)
    raise SystemExit(2)


def _load_state(path: str):
    from ray_shuffling_data_loader_tpu.runtime import journal

    if os.path.isdir(path):
        files = journal._run_files(path)
        if not files:
            _die(f"no run journals under {path!r}")
        path = files[0]
    try:
        return journal.load_run(path)
    except (OSError, ValueError) as exc:
        _die(f"cannot load journal {path!r}: {exc}")


def _arm_recorded_env(identity: dict) -> None:
    """Point every stream-determining env knob at the RECORDED value —
    including clearing knobs the recorded run did not have set. The
    replay must not inherit this shell's divergent schedule."""
    plan = identity.get("plan") or "rowwise"
    os.environ["RSDL_SHUFFLE_PLAN"] = plan
    for key, val in (
        ("RSDL_FAULTS", identity.get("faults")),
        ("RSDL_FAULTS_SEED", identity.get("faults_seed")),
    ):
        if val:
            os.environ[key] = str(val)
        else:
            os.environ.pop(key, None)
    # Read-only re-execution: never journal the replay, never resume.
    os.environ.pop("RSDL_JOURNAL", None)
    os.environ.pop("RSDL_RESUME", None)
    # Fresh audit spool: the replay's digests must fold alone.
    os.environ["RSDL_AUDIT"] = "1"
    os.environ.pop("RSDL_AUDIT_STRICT", None)  # we diff, not raise
    os.environ["RSDL_AUDIT_DIR"] = tempfile.mkdtemp(prefix="rsdl-replay-")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("journal", help="journal file, or a journal dir "
                        "(newest run file is picked)")
    parser.add_argument("--epoch", type=int, default=None,
                        help="epoch to replay (default: every epoch the "
                        "journal holds a verdict for)")
    parser.add_argument("--workers", type=int, default=2,
                        help="pool workers for the replay runtime")
    parser.add_argument("--json", dest="json_out", default=None,
                        help="also write the report JSON here")
    args = parser.parse_args(argv)

    state = _load_state(args.journal)
    if not state.verdicts:
        _die(
            f"journal {state.path!r} holds no reconciled verdicts "
            "(suspended or failed run?) — resume it to completion "
            "first, then replay the resumed run's journal"
        )
    if args.epoch is not None:
        if args.epoch not in state.verdicts:
            _die(
                f"no journaled verdict for epoch {args.epoch} "
                f"(have: {sorted(state.verdicts)})"
            )
        epochs = [args.epoch]
    else:
        epochs = sorted(state.verdicts)

    identity = state.identity
    missing = [f for f in identity.get("filenames", []) if
               "://" not in f and not os.path.exists(f)]
    if missing:
        _die(f"recorded input files are gone: {missing[:3]}")
    _arm_recorded_env(identity)

    from ray_shuffling_data_loader_tpu import runtime, telemetry
    from ray_shuffling_data_loader_tpu.runtime import faults
    from ray_shuffling_data_loader_tpu.shuffle import (
        BatchConsumer,
        shuffle,
    )
    from ray_shuffling_data_loader_tpu.telemetry import audit as _audit

    _audit.refresh_from_env()
    faults.refresh_from_env()

    device_layout = None
    if identity.get("device_batch"):
        device_layout = {
            "batch": int(identity["device_batch"]),
            "columns": list(identity.get("device_columns") or []),
        }

    class _Drain(BatchConsumer):
        def consume(self, rank, epoch, batches):
            store = runtime.get_context().store
            for ref in batches:
                store.free(ref)

        def producer_done(self, rank, epoch):
            pass

        def wait_until_ready(self, epoch):
            pass

        def wait_until_all_epochs_done(self):
            pass

    report = {
        "journal": state.path,
        "run_id": state.run_id,
        "epochs": {},
        "ok": True,
    }
    runtime.init(num_workers=args.workers)
    try:
        for epoch in epochs:
            _audit.begin_run()
            shuffle(
                list(identity["filenames"]),
                _Drain(),
                num_epochs=epoch + 1,
                num_reducers=int(identity["num_reducers"]),
                num_trainers=int(identity["num_trainers"]),
                seed=int(identity["seed"]),
                start_epoch=epoch,
                narrow_to_32=bool(identity.get("narrow_to_32")),
                columns=identity.get("columns"),
                device_layout=device_layout,
            )
            verdicts = _audit.reconcile([epoch])
            replayed = verdicts[0] if verdicts else {}
            recorded = state.verdicts[epoch]
            diverged = {
                f: {"recorded": recorded.get(f), "replayed": replayed.get(f)}
                for f in _COMPARED
                if recorded.get(f) != replayed.get(f)
            }
            ok = not diverged and replayed.get("ok") is True
            report["epochs"][str(epoch)] = {
                "ok": ok,
                "diverged": diverged,
                "delivered_seq": replayed.get("delivered_seq"),
                "audit_ok": replayed.get("ok"),
            }
            report["ok"] = report["ok"] and ok
            telemetry.emit_event(
                "epoch.replayed", _flush=True, epoch=epoch,
                run_id=state.run_id, ok=ok,
                diverged=sorted(diverged) or None,
            )
    finally:
        try:
            runtime.shutdown()
        except Exception:
            pass

    out = json.dumps(report, indent=2)
    print(out)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(out + "\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
