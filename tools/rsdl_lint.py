#!/usr/bin/env python
"""rsdl-lint — the repo's invariant-enforcing static analyzer (ISSUE 14).

Runs the AST checkers in ``ray_shuffling_data_loader_tpu/analysis``
over the repo (or ``--root DIR`` for a fixture tree) and exit-codes on
the findings, so ``run_ci_tests.sh`` and ``format.sh --check`` can gate
on invariants that used to live only in review memory:

    $ python tools/rsdl_lint.py                    # human output
    $ python tools/rsdl_lint.py --json             # machine output
    $ python tools/rsdl_lint.py --explain gate-integrity
    $ python tools/rsdl_lint.py --select knob-registry,vocabulary-drift

Exit codes: 0 clean, 1 findings, 3 internal crash (argparse usage
errors keep their conventional 2).

Suppressions are per-line with a REQUIRED reason::

    FOO.update(x)  # rsdl-lint: disable=lock-discipline -- written once
                   # at import time, readers start after init()

Policy, checker catalog, and how to register a new knob or metric:
``docs/static-analysis.md``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from ray_shuffling_data_loader_tpu.analysis import (  # noqa: E402
    Project,
    all_checkers,
    get_checker,
    run_checks,
)
from ray_shuffling_data_loader_tpu.analysis.core import LintCrash  # noqa: E402

JSON_VERSION = 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="rsdl_lint", description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "--root",
        default=_REPO_ROOT,
        help="repo root to lint (default: this repo)",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="machine-readable output",
    )
    parser.add_argument(
        "--explain",
        metavar="CHECK",
        help="print what a checker enforces and how to fix/register, "
        "then exit 0",
    )
    parser.add_argument(
        "--select",
        metavar="CHECKS",
        help="comma-separated subset of checkers to run",
    )
    parser.add_argument(
        "--list-checks",
        action="store_true",
        help="list checker names and exit",
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print suppressed findings (never affect the exit code)",
    )
    args = parser.parse_args(argv)

    if args.list_checks:
        for name in all_checkers():
            print(name)
        return 0

    if args.explain:
        entry = get_checker(args.explain)
        if entry is None:
            print(
                f"unknown checker {args.explain!r}; known: "
                f"{', '.join(all_checkers() + ['bad-suppression'])}",
                file=sys.stderr,
            )
            return 2
        print(entry[1])
        return 0

    select = (
        [s.strip() for s in args.select.split(",") if s.strip()]
        if args.select
        else None
    )
    project = Project(root=os.path.abspath(args.root))
    findings = run_checks(project, select=select)
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    if args.as_json:
        payload = {
            "version": JSON_VERSION,
            "root": project.root,
            "checks": select or all_checkers(),
            "counts": {
                "active": len(active),
                "suppressed": len(suppressed),
            },
            "findings": [f.to_json() for f in findings],
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        for f in active:
            print(f"{f.location()}: [{f.check}] {f.message}")
        if args.show_suppressed:
            for f in suppressed:
                print(
                    f"{f.location()}: [{f.check}] suppressed "
                    f"({f.suppress_reason}): {f.message}"
                )
        print(
            f"rsdl-lint: {len(active)} finding(s), "
            f"{len(suppressed)} suppressed"
        )
    return 1 if active else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except LintCrash as exc:
        print(f"rsdl-lint: internal error: {exc}", file=sys.stderr)
        sys.exit(3)
    except Exception:
        import traceback

        traceback.print_exc()
        print("rsdl-lint: internal error (crash)", file=sys.stderr)
        sys.exit(3)
