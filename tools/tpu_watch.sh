#!/usr/bin/env bash
# Watch for the axon TPU tunnel to come up; the moment it does, capture the
# round's TPU proof artifacts automatically:
#   1. python bench.py                       -> tools/tpu_bench.out (JSON line at tail)
#   2. RSDL_TPU_TESTS=1 pytest TPU-gated     -> tools/tpu_tests.out
# Probe runs jax.devices() in a subprocess with a hard timeout because a down
# tunnel HANGS rather than erroring (see BENCHLOG.md).
set -u
cd /root/repo
OUT=tools
mkdir -p "$OUT"
LOG="$OUT/tpu_watch.log"
echo "[watch] started $(date -u +%FT%TZ)" >> "$LOG"
while true; do
  if python - <<'EOF' 2>>"$LOG"
import subprocess, sys
code = "import jax; ds=jax.devices(); print('PLATFORM='+ds[0].platform)"
try:
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120)
except subprocess.TimeoutExpired:
    sys.exit(1)
ok = p.returncode == 0 and "PLATFORM=tpu" in p.stdout
sys.exit(0 if ok else 1)
EOF
  then
    echo "[watch] TUNNEL UP $(date -u +%FT%TZ) — capturing" >> "$LOG"
    # Bench first (the scarce artifact), then the gated tests.
    timeout 3600 python bench.py > "$OUT/tpu_bench.out" 2>&1
    echo "[watch] bench rc=$? $(date -u +%FT%TZ)" >> "$LOG"
    RSDL_TPU_TESTS=1 timeout 2400 python -m pytest -q \
      tests/test_ops_tpu.py tests/test_resident_tpu.py \
      > "$OUT/tpu_tests.out" 2>&1
    echo "[watch] tests rc=$? $(date -u +%FT%TZ)" >> "$LOG"
    touch "$OUT/TPU_CAPTURED"
    echo "[watch] capture complete — exiting" >> "$LOG"
    exit 0
  fi
  echo "[watch] down $(date -u +%FT%TZ)" >> "$LOG"
  sleep 180
done
