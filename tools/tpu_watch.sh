#!/usr/bin/env bash
# Watch for the axon TPU tunnel to come up; the moment it does, capture the
# round's TPU proof artifacts automatically — QUICK FIRST, so even a ~5-min
# window yields an on-chip number (VERDICT r3 item 1):
#   1. RSDL_BENCH_QUICK=1 python bench.py    -> tools/tpu_bench_quick.out
#   2. python bench.py (full, >=10 GB)       -> tools/tpu_bench.out
#   3. RSDL_TPU_TESTS=1 pytest TPU-gated     -> tools/tpu_tests.out
# Each stage re-probes the tunnel before starting: if the window closed,
# keep the artifacts already captured and go back to watching for a wider
# one (a captured quick artifact is kept; later windows only ADD stages).
# Probe runs jax.devices() in a subprocess with a hard timeout because a
# down tunnel HANGS rather than erroring (see BENCHLOG.md).
set -u
cd /root/repo
OUT=tools
mkdir -p "$OUT"
LOG="$OUT/tpu_watch.log"
echo "[watch] started $(date -u +%FT%TZ)" >> "$LOG"

probe() {
  # Control-plane AND data-plane: jax.devices() can succeed over a tunnel
  # whose bulk-transfer path is dead (observed 2026-07-31: devices() OK at
  # 03:48, a 256 MB device_put wedged forever at 03:49 with ~0 B/s on the
  # wire). Round-trip 64 MB — big enough to exercise the bulk path, small
  # enough to clear the 120 s budget on any usable link.
  python - <<'EOF' 2>>"$LOG"
import subprocess, sys
code = (
    "import jax, numpy as np; ds = jax.devices(); "
    "a = np.ones((64, 1024, 1024), np.uint8); "
    "d = jax.block_until_ready(jax.device_put(a)); "
    "assert int(jax.numpy.max(d)) == 1; "
    "print('PLATFORM='+ds[0].platform)"
)
try:
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120)
except subprocess.TimeoutExpired:
    sys.exit(1)
ok = p.returncode == 0 and "PLATFORM=tpu" in p.stdout
sys.exit(0 if ok else 1)
EOF
}

# A stage is done when its marker file exists AND records success: the
# JSON line must say backend tpu (a CPU-failover line means the window
# closed mid-stage) and carry no "error" key (the stall-watchdog and
# last-resort error JSONs also say backend tpu but report value 0.0 —
# treating those as captured would permanently skip the retry).
bench_ok() {
  grep -q '"backend": "tpu"' "$1" 2>/dev/null \
    && ! grep -q '"error"' "$1" 2>/dev/null
}
tests_ok() {
  grep -q 'passed' "$1" 2>/dev/null \
    && ! grep -qE 'failed|error' "$1" 2>/dev/null
}

while true; do
  # Never capture concurrently with ANOTHER bench.py (the driver's
  # round-end run): two benches sharing the core would distort the
  # artifact that actually counts. Wait for it to finish instead.
  while pgrep -f "python bench.py" >/dev/null 2>&1; do
    echo "[watch] foreign bench.py running; standing by $(date -u +%FT%TZ)" >> "$LOG"
    sleep 60
  done
  if probe; then
    echo "[watch] TUNNEL UP $(date -u +%FT%TZ) — capturing" >> "$LOG"
    # Capture lock: CPU-heavy side work (the trainer sweep) polls this and
    # pauses while a TPU capture is running — on a 1-core host a
    # concurrent sweep would inflate the bench's stall% measurement. The
    # lock carries this watcher's PID so a SIGKILL-orphaned lock can be
    # detected as stale (EXIT trap covers TERM/INT, not KILL).
    echo $$ > "$OUT/CAPTURE_IN_PROGRESS"
    trap 'rm -f "$OUT/CAPTURE_IN_PROGRESS"' EXIT
    # Preempt an IN-FLIGHT sweep trial (the between-trial check can't see
    # a window that opens mid-trial): the TPU number outranks one sweep
    # config, and the killed config is left unrecorded so a later sweep
    # run retries it. Pool workers self-destruct when their parent dies.
    if pkill -f "benchmarks/benchmark.py" 2>/dev/null; then
      echo "[watch] preempted in-flight sweep trial" >> "$LOG"
      sleep 5
    fi
    if ! bench_ok "$OUT/tpu_bench_quick.out"; then
      RSDL_BENCH_QUICK=1 RSDL_BENCH_INIT_ATTEMPTS=1 \
        timeout 1200 python bench.py > "$OUT/tpu_bench_quick.out" 2> "$OUT/tpu_bench_quick.err"
      echo "[watch] quick bench rc=$? $(date -u +%FT%TZ)" >> "$LOG"
      bench_ok "$OUT/tpu_bench_quick.out" && touch "$OUT/TPU_CAPTURED"
    fi
    if probe && ! bench_ok "$OUT/tpu_bench.out"; then
      timeout 3600 python bench.py > "$OUT/tpu_bench.out" 2> "$OUT/tpu_bench.err"
      echo "[watch] full bench rc=$? $(date -u +%FT%TZ)" >> "$LOG"
      bench_ok "$OUT/tpu_bench.out" && touch "$OUT/TPU_CAPTURED"
    fi
    if probe && ! tests_ok "$OUT/tpu_tests.out"; then
      RSDL_TPU_TESTS=1 timeout 2400 python -m pytest -q \
        tests/test_ops_tpu.py tests/test_resident_tpu.py \
        > "$OUT/tpu_tests.out" 2>&1
      echo "[watch] tests rc=$? $(date -u +%FT%TZ)" >> "$LOG"
    fi
    if bench_ok "$OUT/tpu_bench_quick.out" && bench_ok "$OUT/tpu_bench.out" \
        && tests_ok "$OUT/tpu_tests.out"; then
      echo "[watch] all captures complete — exiting" >> "$LOG"
      exit 0
    fi
    echo "[watch] window closed with stages pending — rewatching" >> "$LOG"
    rm -f "$OUT/CAPTURE_IN_PROGRESS"
  else
    echo "[watch] down $(date -u +%FT%TZ)" >> "$LOG"
  fi
  sleep 180
done
