#!/usr/bin/env bash
# Multi-trainer benchmark sweep (VERDICT r3 item 2): the reference's
# official workload shape at {4,8,16} trainers x {2,4} reducers/trainer
# (reference benchmarks/benchmark_batch.sh:9-24), on a >=5 GB DATA_SPEC
# dataset. One trial x 10 epochs per config, results + CSVs under
# tools/sweep_results/; the JSON summary line of each config is saved as
# <tag>.json for the BENCHLOG table.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT=tools/sweep_results
mkdir -p "$OUT"
ROWS=${RSDL_SWEEP_ROWS:-29761904}     # ~5 GB at 168 B/row
FILES=${RSDL_SWEEP_FILES:-25}         # reference's smallest official file count
EPOCHS=${RSDL_SWEEP_EPOCHS:-10}
DATA_DIR=${RSDL_SWEEP_DATA:-.bench_cache/sweep5g}
# Reuse only a COMPLETE dataset: a capture-preempted trial can die
# mid-generation, and benchmarking a fragment while recording it as the
# full workload would silently corrupt the rows/s comparison. Re-counted
# before every trial so the first successful generation flips later
# trials to reuse (and a fragment left by a preempted trial is wiped).
count_files() {
  find "$DATA_DIR" -name '*.parquet.snappy' 2>/dev/null | wc -l
}
gen_args() {
  if [ "$(count_files)" -ge "$FILES" ]; then echo "--use-old-data"; fi
}
nfiles=$(count_files)
if [ "$nfiles" -gt 0 ] && [ "$nfiles" -lt "$FILES" ]; then
  echo "[sweep] partial dataset ($nfiles of >=$FILES files); regenerating"
  rm -rf "$DATA_DIR"
fi
for T in 4 8 16; do
  for RPT in 2 4; do
    R=$((T * RPT))
    TAG="t${T}_r${R}"
    if [ -s "$OUT/$TAG.json" ]; then
      echo "[sweep] $TAG already recorded; skipping"
      continue
    fi
    # Yield the single core to any in-flight TPU capture: a concurrent
    # sweep would distort the on-chip stall% artifact. (The reverse
    # direction — a window opening mid-trial — is handled by the watch
    # loop preempting the trial; see tpu_watch.sh.) A lock whose watcher
    # PID is gone is stale (SIGKILL skips the EXIT trap) and is removed.
    while [ -e tools/CAPTURE_IN_PROGRESS ]; do
      wpid=$(cat tools/CAPTURE_IN_PROGRESS 2>/dev/null || echo "")
      # Stale only if the watcher is gone AND no capture child survived
      # it (a SIGKILLed watcher orphans its bench.py or TPU pytest
      # stage, which keeps the core busy; clearing the lock then would
      # defeat the exclusion).
      if [ -n "$wpid" ] && ! kill -0 "$wpid" 2>/dev/null \
          && ! pgrep -f "python bench.py" >/dev/null 2>&1 \
          && ! pgrep -f "test_ops_tpu" >/dev/null 2>&1; then
        echo "[sweep] stale capture lock (pid $wpid gone); clearing"
        rm -f tools/CAPTURE_IN_PROGRESS
        break
      fi
      echo "[sweep] TPU capture in progress; waiting ($(date -u +%FT%TZ))"
      sleep 60
    done
    echo "[sweep] trainers=$T reducers=$R ($(date -u +%FT%TZ))"
    python benchmarks/benchmark.py \
      --num-rows "$ROWS" --num-files "$FILES" \
      --num-row-groups-per-file 5 --batch-size 250000 \
      --num-epochs "$EPOCHS" --num-trials 1 \
      --num-trainers "$T" --num-reducers "$R" \
      --max-concurrent-epochs 2 \
      --data-dir "$DATA_DIR" $(gen_args) \
      --stats-dir "$OUT/stats_$TAG" \
      > "$OUT/$TAG.log" 2>&1 || {
        echo "[sweep] $TAG FAILED (see $OUT/$TAG.log)"; continue; }
    grep -E '^\{' "$OUT/$TAG.log" | tail -1 > "$OUT/$TAG.json"
    echo "[sweep] $TAG done: $(cat "$OUT/$TAG.json")"
  done
done
echo "[sweep] complete"
