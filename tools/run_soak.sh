#!/usr/bin/env bash
# Idle-host race hunt: widened-seed stress soaks + deep hypothesis runs,
# yielding to any in-flight TPU capture between iterations (the capture
# owns the core; see tpu_watch.sh). Usage: tools/run_soak.sh [iterations]
set -u
cd "$(dirname "$0")/.."
ITER=${1:-5}
for i in $(seq 1 "$ITER"); do
  while [ -e tools/CAPTURE_IN_PROGRESS ]; do
    echo "[soak] TPU capture in progress; standing by"
    sleep 60
  done
  echo "[soak] iteration $i/$ITER ($(date -u +%FT%TZ))"
  RSDL_STRESS_SEEDS=$((3 + i * 3)) python -m pytest tests/test_stress.py -q \
    2>&1 | tail -1
  HYPOTHESIS_PROFILE=deep python -m pytest tests/test_rebatch_property.py \
    -q -p no:cacheprovider 2>&1 | tail -1
done
echo "[soak] complete"
