"""Aggregate tools/sweep_results/*.json + stats CSVs into the BENCHLOG
markdown table for the multi-trainer sweep (VERDICT r3 item 2).

Run after tools/run_trainer_sweep.sh: python tools/summarize_sweep.py
"""

import csv
import glob
import json
import os
import re

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "sweep_results")


def main() -> None:
    rows = []
    for path in sorted(glob.glob(os.path.join(OUT, "t*_r*.json"))):
        tag = os.path.basename(path)[:-5]
        m = re.match(r"t(\d+)_r(\d+)", tag)
        if not m:
            continue
        trainers, reducers = int(m.group(1)), int(m.group(2))
        with open(path) as f:
            summary = json.loads(f.read().strip() or "{}")
        trial_csv = os.path.join(OUT, f"stats_{tag}", "trial_stats.csv")
        extra = {}
        if os.path.exists(trial_csv):
            with open(trial_csv) as f:
                recs = list(csv.DictReader(f))
            if recs:
                r0 = recs[0]
                extra = {
                    "per_trainer": float(r0["batch_throughput_per_trainer"]),
                    "map_avg": float(r0["avg_map_stage_duration"]),
                    "reduce_avg": float(r0["avg_reduce_stage_duration"]),
                    "consume_avg": float(r0["avg_consume_stage_duration"]),
                    "store_peak_gb": float(r0["max_object_store_utilization"])
                    / 1e9,
                }
        rows.append((trainers, reducers, summary, extra))
    rows.sort(key=lambda r: (r[0], r[1]))
    print(
        "| trainers | reducers | trial s | rows/s | batches/s/trainer "
        "| map avg s | reduce avg s | consume avg s | peak store GB |"
    )
    print("|---|---|---|---|---|---|---|---|---|")
    for trainers, reducers, s, e in rows:
        print(
            f"| {trainers} | {reducers} "
            f"| {s.get('duration_mean', float('nan')):.0f} "
            f"| {s.get('row_throughput_mean', float('nan')):,.0f} "
            f"| {e.get('per_trainer', float('nan')):.3f} "
            f"| {e.get('map_avg', float('nan')):.1f} "
            f"| {e.get('reduce_avg', float('nan')):.1f} "
            f"| {e.get('consume_avg', float('nan')):.1f} "
            f"| {e.get('store_peak_gb', float('nan')):.1f} |"
        )


if __name__ == "__main__":
    main()
