#!/usr/bin/env python
"""rsdl_top: live terminal dashboard for a running shuffle.

``top`` for the shuffle plane — polls the obs endpoint
(``RSDL_OBS_PORT``, :mod:`telemetry.obs_server`) and renders one
refreshing screen: epoch-window state, per-stage throughput sparklines
(from ``/timeseries`` rate series), queue depths, store residency,
the capacity ledger (per-tier/per-epoch residency + host headroom,
``/capacity``), the online critical-path verdict (``/critical``),
active SLO alerts with their recent transitions (``/alerts``),
recovery counters, stall attribution, the straggler/skew table, the
continuous profiler's hot-frames panel (top-5 self-time frames with
per-stage attribution, ``/profile`` — shown when ``RSDL_PROFILE`` is
armed), and the latest structured events. Pure stdlib, no curses —
ANSI clear + redraw, so it works over any ssh session.

Usage::

    RSDL_METRICS=1 RSDL_OBS_PORT=9100 python bench.py ... &
    python tools/rsdl_top.py                    # live, 2 s refresh
    python tools/rsdl_top.py --once             # one frame (CI smoke)
    python tools/rsdl_top.py --once --json      # machine-readable frame
    python tools/rsdl_top.py --fleet            # per-tenant panel (/jobs)
    python tools/rsdl_top.py --url http://host:9100 --interval 5

``--fleet`` (ISSUE 16) swaps the single-trial dashboard for the
service-wide per-tenant table: one row per job with its epoch window,
delivered bytes + current rate, resident store bytes, decode-cache
claims, admission waits, fair-share vtime lag, and any SLO alerts
firing against the tenant.

Exit codes: 0 on a rendered frame, 1 when the endpoint is unreachable
(so ``--once`` doubles as an is-the-obs-plane-up gate).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

SPARK_CHARS = "▁▂▃▄▅▆▇█"

# The rate series the throughput panel shows, in display order.
THROUGHPUT_SERIES = (
    ("map rows/s", "rsdl_shuffle_map_rows"),
    ("reduce rows/s", "rsdl_shuffle_reduce_rows"),
    ("h2d B/s", "rsdl_h2d_bytes"),
)


def _get_json(base: str, path: str, timeout: float = 5.0):
    with urllib.request.urlopen(base + path, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def sparkline(values: List[float], width: int = 40) -> str:
    """Unicode block sparkline of the trailing ``width`` values,
    normalized to the window's own max (an all-zero window renders
    flat)."""
    if not values:
        return ""
    values = values[-width:]
    peak = max(values)
    if peak <= 0:
        return SPARK_CHARS[0] * len(values)
    out = []
    for v in values:
        idx = int(round((len(SPARK_CHARS) - 1) * max(0.0, v) / peak))
        out.append(SPARK_CHARS[idx])
    return "".join(out)


def _fmt_bytes(num: Optional[float]) -> str:
    if num is None:
        return "-"
    num = float(num)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(num) < 1024.0:
            return f"{num:.1f}{unit}"
        num /= 1024.0
    return f"{num:.1f}PiB"


def _fmt(value: Any) -> str:
    if value is None:
        return "-"
    if isinstance(value, float):
        return f"{value:.4g}"
    return str(value)


# ---------------------------------------------------------------------------
# Frame collection
# ---------------------------------------------------------------------------


def collect(base: str, window_s: float) -> Dict[str, Any]:
    """One dashboard frame's worth of endpoint data. Individual pages
    degrade to an ``error`` entry (the dashboard renders what it got)
    — only a fully unreachable endpoint raises."""
    frame: Dict[str, Any] = {"ts": time.time(), "url": base}
    # /status is the must-have page: let its failure propagate (the
    # caller maps it to exit 1).
    frame["status"] = _get_json(base, "/status")
    for key, path in (
        ("healthz", "/healthz"),
        ("timeseries", f"/timeseries?window={window_s:g}"),
        ("events", "/events?limit=12"),
        ("stragglers", "/stragglers"),
        ("capacity", "/capacity"),
        ("critical", "/critical"),
        ("alerts", "/alerts"),
        ("jobs", "/jobs"),
        ("profile", "/profile?top=5"),
    ):
        try:
            frame[key] = _get_json(base, path)
        except Exception as exc:
            frame[key] = {"error": f"{type(exc).__name__}: {exc}"[:200]}
    return frame


def _series_points(frame: dict, name: str) -> List[dict]:
    series = (frame.get("timeseries") or {}).get("series") or {}
    for key, points in series.items():
        base = key.split("{", 1)[0]
        if name in (key, base) or name == _prom_alias(base):
            return points
    return []


def _prom_alias(base: str) -> str:
    import re

    out = re.sub(r"[^a-zA-Z0-9_:]", "_", base)
    return out if out.startswith("rsdl_") else "rsdl_" + out


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def _match_job(jobs: Dict[str, Any], wanted: str) -> Optional[str]:
    """Resolve a ``--job`` filter against the per-job map: exact id
    first, then unique substring (job names prefix the ids)."""
    if wanted in jobs:
        return wanted
    hits = [k for k in jobs if wanted in k]
    return hits[0] if len(hits) == 1 else None


def render(frame: Dict[str, Any]) -> str:
    status = frame.get("status") or {}
    healthz = frame.get("healthz") or {}
    lines: List[str] = []
    shuffle = (status.get("providers") or {}).get("shuffle") or {}
    job_filter = frame.get("job_filter")
    job_note = ""
    if job_filter:
        # Multi-job service (ISSUE 15): focus the trial panel on ONE
        # tenant's view instead of interleaving every job's epochs.
        jobs = shuffle.get("jobs") or {}
        key = _match_job(jobs, job_filter)
        if key is not None:
            shuffle = jobs[key]
            job_note = f"  job={key}"
        else:
            job_note = f"  job={job_filter}(no match)"
    epoch_window = (
        shuffle.get("in_flight_epochs")
        if job_filter
        else status.get("in_flight_epochs")
    ) or []
    lines.append(
        f"rsdl_top  {time.strftime('%H:%M:%S', time.localtime(frame['ts']))}"
        f"  {frame['url']}"
        f"  up={healthz.get('ok', '?')}"
        f"  uptime={_fmt(healthz.get('uptime_s'))}s"
        f"  trial_running={shuffle.get('running', '-')}"
        + job_note
    )
    service = (status.get("providers") or {}).get("service") or {}
    if service.get("jobs"):
        parts = []
        for rec in service["jobs"][-6:]:
            parts.append(
                f"{rec.get('job_id')}"
                f"[w={rec.get('weight')}"
                f",{'run' if rec.get('running') else 'done'}]"
            )
        lines.append("jobs     " + "  ".join(parts)[:115])
    epochs = shuffle.get("epochs") or {}
    parts = []
    for e in sorted(epochs, key=lambda x: int(x)):
        st = epochs[e]
        parts.append(
            f"e{e}:{st.get('state', '?')}"
            f"({st.get('delivered_reducers', 0)}"
            f"/{shuffle.get('num_reducers', '?')})"
        )
    lines.append(
        "epochs   in-flight=" + (str(epoch_window) if epoch_window else "[]")
        + ("  " + " ".join(parts) if parts else "")
    )

    # Federation freshness (ISSUE 19): per-source-host last-shipped age
    # from the relay sink — a dead remote relay reads STALE here live.
    relay = healthz.get("relay") or {}
    relay_hosts = relay.get("hosts") or {}
    if relay.get("role") or relay_hosts:
        parts = []
        for host_id in sorted(relay_hosts):
            rec = relay_hosts[host_id] or {}
            mark = (
                "STALE"
                if rec.get("stale")
                else f"{_fmt(rec.get('age_s'))}s"
            )
            parts.append(
                f"{host_id}:{mark}"
                f"/{_fmt_bytes(rec.get('bytes', 0))}"
            )
        lines.append(
            (
                f"relay    role={relay.get('role') or '-'}  "
                + ("  ".join(parts) if parts else "(no remote hosts)")
            )[:115]
        )

    # Throughput sparklines from /timeseries rate series.
    lines.append("")
    lines.append("throughput (rate over the window)")
    for label, name in THROUGHPUT_SERIES:
        points = _series_points(frame, name)
        rates = [float(p.get("rate", 0.0)) for p in points if "rate" in p]
        cur = rates[-1] if rates else None
        lines.append(
            f"  {label:>14}  {sparkline(rates):40s}  "
            f"{_fmt(cur) if cur is not None else '-'}"
        )

    # Queue depths + store residency.
    depths = status.get("queue_depths") or {}
    total = depths.get("queue.depth.total")
    lines.append("")
    lines.append(
        f"queue    total={_fmt(total)}  "
        + "  ".join(
            f"{k.split('{', 1)[1].rstrip('}')}: {int(v)}"
            for k, v in sorted(depths.items())
            if k != "queue.depth.total"
        )[:100]
    )
    store = status.get("store") or {}
    store_bytes = store.get("total_bytes") or store.get("shm_bytes")
    lines.append(
        "store    "
        f"objects={_fmt(store.get('objects'))}  "
        f"bytes={_fmt_bytes(store_bytes)}  "
        f"spill={_fmt_bytes(store.get('spill_bytes'))}"
    )

    # Recovery + stall attribution.
    recovery = status.get("recovery") or {}
    lines.append(
        "recovery "
        + (
            "  ".join(
                f"{k.replace('recovery.', '')}={int(v)}"
                for k, v in sorted(recovery.items())
            )
            if recovery
            else "(none)"
        )
    )

    # Capacity ledger: per-tier residency + host headroom (ISSUE 9).
    cap = frame.get("capacity") or {}
    totals = cap.get("totals") or {}
    host = cap.get("host") or {}
    shm_tot = (totals.get("shm") or {})
    spill_tot = (totals.get("spill") or {})
    frac = cap.get("shm_used_frac")
    lines.append(
        "capacity "
        f"shm={_fmt_bytes(shm_tot.get('resident_bytes'))}"
        f"({shm_tot.get('segments', 0)} seg)  "
        f"spill={_fmt_bytes(spill_tot.get('resident_bytes'))}"
        f"({spill_tot.get('segments', 0)} seg)  "
        f"used={'-' if frac is None else f'{100 * frac:.1f}%'}  "
        f"rss={_fmt_bytes(host.get('rss_bytes'))}  "
        f"shm_free={_fmt_bytes(host.get('shm_free_bytes'))}"
    )
    epochs_cap = cap.get("epochs") or {}
    if epochs_cap:
        parts = []
        # Numeric order, unknown-epoch bucket last — matches
        # telemetry/capacity.py's epoch_sort_key (this tool stays
        # stdlib-only, so the 2-line key is mirrored, not imported).
        for e in sorted(
            epochs_cap,
            key=lambda x: (0, int(x)) if x.lstrip("-").isdigit()
            else (1, 0),
        )[-6:]:
            tiers = epochs_cap[e]
            res = sum(
                c.get("resident_bytes", 0) for c in tiers.values()
            )
            parts.append(f"e{e}={_fmt_bytes(res)}")
        lines.append("  resident by epoch: " + "  ".join(parts))

    # Online critical path (shares of the current epoch's active time).
    crit = frame.get("critical") or {}
    current = crit.get("current") or {}
    shares = current.get("sole_share") or {}
    share_txt = "  ".join(
        f"{stage}={100 * share:.0f}%"
        for stage, share in sorted(
            shares.items(), key=lambda kv: -kv[1]
        )
    )
    lines.append(
        "critical "
        f"epoch={_fmt(current.get('epoch'))}  "
        f"path={current.get('critical_path') or '-'}  "
        f"run={crit.get('run_critical_path') or '-'}"
        + (f"  [{share_txt}]" if share_txt else "")
    )

    # Alerts: active first, then the recent transitions.
    alerts = frame.get("alerts") or {}
    active = alerts.get("active") or []
    lines.append(
        "alerts   "
        + (
            "ACTIVE: " + ", ".join(active)
            if active
            else f"(none active, {len(alerts.get('rules') or [])} rules)"
        )
    )
    for rec in (alerts.get("history") or [])[-4:]:
        stamp = time.strftime(
            "%H:%M:%S", time.localtime(float(rec.get("ts", 0.0)))
        )
        lines.append(
            f"  {stamp}  {rec.get('event', '?'):<9} {rec.get('rule')}"
            f"  value={_fmt(rec.get('value'))}"
        )

    # Stragglers.
    stragglers = frame.get("stragglers") or {}
    stages = stragglers.get("stages") or {}
    lines.append("")
    flagged_total = stragglers.get(
        "flagged_total", len(stragglers.get("flagged") or [])
    )
    lines.append(
        "stragglers  "
        f"tasks={_fmt(stragglers.get('tasks_total'))}  "
        f"wedged={len(stragglers.get('wedged') or [])}  "
        f"flagged={flagged_total}"
    )
    if stages:
        lines.append(
            "  stage          n    median_s      p99_s   skew  slowest_host"
        )
        for stage in sorted(stages):
            st = stages[stage]
            lines.append(
                f"  {stage:<12}{st.get('count', 0):>4}"
                f"{_fmt(st.get('median_s')):>12}"
                f"{_fmt(st.get('p99_s')):>11}"
                f"{_fmt(st.get('skew_ratio')):>7}"
                f"  {st.get('slowest_host') or '-'}"
            )
    for task in (stragglers.get("wedged") or [])[:4]:
        lines.append(
            f"  WEDGED: {task.get('stage')} pid={task.get('pid')} "
            f"age={_fmt(task.get('age_s'))}s "
            f"(budget {_fmt(task.get('budget_s'))}s)"
        )
    for task in (stragglers.get("flagged") or [])[:4]:
        lines.append(
            f"  slow: {task.get('stage')} host={task.get('host')} "
            f"pid={task.get('pid')} dur={_fmt(task.get('dur_s'))}s"
            + (f" epoch={task['epoch']}" if "epoch" in task else "")
        )

    # Hot frames (ISSUE 17): the continuous profiler's top self-time
    # frames with per-stage attribution — where the run's wall time
    # ACTUALLY goes, declared-instrumentation or not. Absent (not an
    # error) when the profiling plane is off.
    profile = frame.get("profile") or {}
    top_frames = profile.get("top") or []
    if top_frames:
        lines.append("")
        lines.append(
            "hot frames  "
            f"samples={_fmt(profile.get('samples'))}  "
            f"sampled={_fmt(profile.get('seconds'))}s  "
            f"hz={_fmt(profile.get('hz'))}  "
            f"sampler={'on' if profile.get('sampler_running') else 'off'}"
        )
        for row in top_frames[:5]:
            stages = ",".join(
                f"{k}={v:.1f}s" for k, v in (row.get("stages") or {}).items()
            )
            lines.append(
                f"  {row.get('self_s', 0.0):>6.1f}s "
                f"{row.get('self_frac', 0.0):>6.1%}  {row.get('frame')}"
                + (f"  [{stages}]" if stages else "")
            )

    # Events tail (job-filtered when --job is set: job-stamped records
    # must match; UNstamped ones are session-level — store/evictor/obs
    # — and stay visible, the same policy as epoch_report --job). The
    # by_kind header is recomputed from the filtered set so the counts
    # and the tail below them can never disagree.
    events = frame.get("events") or {}
    if job_filter:
        recs = [
            r
            for r in (events.get("events") or [])
            if "job" not in r or job_filter in str(r.get("job"))
        ]
        by_kind_f: Dict[str, int] = {}
        for r in recs:
            kind = str(r.get("kind", "?"))
            by_kind_f[kind] = by_kind_f.get(kind, 0) + 1
        events = dict(events, events=recs, by_kind=by_kind_f)
    lines.append("")
    by_kind = events.get("by_kind") or {}
    lines.append(
        "events   "
        + (
            "  ".join(f"{k}={v}" for k, v in sorted(by_kind.items()))[:110]
            if by_kind
            else "(none)"
        )
    )
    for rec in (events.get("events") or [])[-8:]:
        ts = time.strftime(
            "%H:%M:%S", time.localtime(float(rec.get("ts", 0.0)))
        )
        detail = " ".join(
            f"{k}={rec[k]}"
            for k in ("epoch", "stage", "schedule", "attempt", "error",
                      "counter", "rank", "duration_s")
            if k in rec
        )
        lines.append(f"  {ts}  {rec.get('kind', '?'):<18} {detail}"[:118])
    return "\n".join(lines)


def render_fleet(frame: Dict[str, Any]) -> str:
    """The ``--fleet`` panel: one row per tenant from ``/jobs``."""
    page = frame.get("jobs") or {}
    rows = page.get("jobs") or []
    healthz = frame.get("healthz") or {}
    lines: List[str] = []
    running = sum(1 for r in rows if r.get("running"))
    lines.append(
        "rsdl_top --fleet  "
        f"{time.strftime('%H:%M:%S', time.localtime(frame['ts']))}"
        f"  {frame['url']}"
        f"  up={healthz.get('ok', '?')}"
        f"  mode={page.get('service_mode') or '-'}"
        f"  jobs={len(rows)} ({running} running)"
    )
    if page.get("error"):
        lines.append(f"  /jobs error: {page['error']}")
        return "\n".join(lines)
    if not rows:
        lines.append("  (no tenants known to this session)")
        return "\n".join(lines)
    lines.append(
        "  job                    w  run  epochs   in-flight"
        "    delivered      rate  resident   cache  adm(n/s)"
        "   vlag  alerts"
    )
    for row in rows:
        jid = str(row.get("job_id", "?"))
        done = row.get("epochs_done")
        total = row.get("num_epochs")
        epochs = (
            f"{done}/{total}" if done is not None and total is not None
            else (str(done) if done is not None else "-")
        )
        window = row.get("in_flight_epochs")
        resident = row.get("resident_bytes") or {}
        resident_total = (
            sum(resident.values()) if isinstance(resident, dict) else None
        )
        adm = row.get("admission") or {}
        adm_txt = (
            f"{adm.get('waits', 0)}/{adm.get('wait_s', 0.0):.1f}s"
            if adm else "-"
        )
        alerts = row.get("active_alerts") or []
        lines.append(
            f"  {jid:<22}"
            f"{_fmt(row.get('weight')):>3}"
            f"{('yes' if row.get('running') else 'no'):>5}"
            f"{epochs:>8}"
            f"  {str(window if window else []):<10}"
            f"{_fmt_bytes(row.get('delivered_bytes')):>11}"
            f"{_fmt_bytes(row.get('delivered_rate_bps')) + '/s' if row.get('delivered_rate_bps') is not None else '-':>10}"
            f"{_fmt_bytes(resident_total):>10}"
            f"{_fmt(row.get('cache_claims')):>8}"
            f"{adm_txt:>10}"
            f"{_fmt(row.get('dispatch_vtime_lag')):>7}"
            f"  {'ALERT: ' + ','.join(alerts) if alerts else '-'}"
        )
        if row.get("error"):
            lines.append(f"      error: {str(row['error'])[:100]}")
    # The engine-wide view below the table: firing instances + history.
    alerts_page = frame.get("alerts") or {}
    active = alerts_page.get("active") or []
    if active:
        lines.append("  active alerts: " + ", ".join(active))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Main
# ---------------------------------------------------------------------------


def default_url() -> str:
    port = os.environ.get("RSDL_OBS_PORT", "").strip() or "9100"
    host = os.environ.get("RSDL_OBS_HOST", "").strip() or "127.0.0.1"
    if host == "0.0.0.0":
        host = "127.0.0.1"
    return f"http://{host}:{port}"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--url",
        default=None,
        help="obs endpoint base URL (default: http://$RSDL_OBS_HOST"
        ":$RSDL_OBS_PORT, falling back to 127.0.0.1:9100)",
    )
    parser.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh period in seconds (live mode; default 2)",
    )
    parser.add_argument(
        "--window", type=float, default=120.0,
        help="sparkline window in seconds (default 120)",
    )
    parser.add_argument(
        "--once", action="store_true",
        help="render one frame and exit (CI smoke / scripting)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the raw frame as JSON instead of the dashboard",
    )
    parser.add_argument(
        "--fleet", action="store_true",
        help="render the service-wide per-tenant table (/jobs) instead "
        "of the single-trial dashboard (ISSUE 16)",
    )
    parser.add_argument(
        "--job", default=None,
        help="focus on ONE service job (exact job id or unique "
        "substring): the trial panel shows that job's epochs and the "
        "events tail is filtered to it (multi-job service, ISSUE 15)",
    )
    args = parser.parse_args(argv)
    base = (args.url or default_url()).rstrip("/")

    while True:
        try:
            frame = collect(base, args.window)
            if args.job:
                frame["job_filter"] = args.job
        except (urllib.error.URLError, OSError, ValueError) as exc:
            print(f"rsdl_top: {base} unreachable: {exc}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(frame, default=str))
        else:
            if not args.once:
                # ANSI clear + home; keeps the frame flicker-free enough
                # without curses.
                sys.stdout.write("\x1b[2J\x1b[H")
            print(render_fleet(frame) if args.fleet else render(frame))
        if args.once:
            return 0
        try:
            time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
