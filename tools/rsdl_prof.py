#!/usr/bin/env python
"""CLI for the continuous profiling plane (telemetry/profiler.py).

Subcommands::

    rsdl_prof.py top   [--dir DIR | --url URL] [--stage S] [--job J]
                       [--epoch E] [-n N] [--json]
    rsdl_prof.py flame --out PAGE.html [--dir DIR | --url URL]
                       [--stage S] [--job J] [--epoch E]
    rsdl_prof.py diff  BASE HEAD [--ledger PATH] [-n N] [--json]

``top`` prints the merged self/total frame table (per-stage
attribution included); ``flame`` writes the self-contained flamegraph
HTML page; ``diff`` is the differential profile — BASE and HEAD are
either two profile **spool directories** or, with ``--ledger``, two
run-ledger record refs (index, id, or unique id prefix) whose embedded
profile digests are compared. Diffs compare self-time *shares*, not
seconds, so runs of different lengths diff meaningfully.

Source resolution for top/flame: ``--url`` scrapes a live obs
endpoint's ``/profile``; ``--dir`` reads a spool directory; default is
this environment's spool (``RSDL_PROFILE_DIR`` /
``$RSDL_RUNTIME_DIR/profiles``). Exit 3 when no profile data exists at
the chosen source — "the plane was never on" is distinguishable from
an empty-but-armed run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from ray_shuffling_data_loader_tpu.telemetry import profiler  # noqa: E402


def _fetch_url(url: str, args) -> dict:
    import urllib.parse
    import urllib.request

    params = {}
    for name in ("stage", "job", "epoch"):
        value = getattr(args, name, None)
        if value:
            params[name] = value
    query = ("?" + urllib.parse.urlencode(params)) if params else ""
    with urllib.request.urlopen(
        url.rstrip("/") + "/profile" + query, timeout=10
    ) as resp:
        return json.loads(resp.read().decode())


def _local_agg(args) -> dict:
    return profiler.aggregate_profiles(
        directory=getattr(args, "dir", None),
        stage=getattr(args, "stage", None),
        job=getattr(args, "job", None),
        epoch=getattr(args, "epoch", None),
    )


def _agg_of(args) -> dict:
    """An aggregate-shaped view from --url, --dir, or the ambient
    spool. A /profile body converts via its collapsed text? No — it
    already carries the top table; reuse it as-is for rendering by
    rebuilding stacks from the collapsed text."""
    url = getattr(args, "url", None)
    if url:
        body = _fetch_url(url, args)
        stacks = []
        for line in (body.get("collapsed") or "").splitlines():
            stack, _, count = line.rpartition(" ")
            if not stack:
                continue
            tags = {}
            if stack.startswith("stage:"):
                head, _, rest = stack.partition(";")
                tags["stage"] = head[len("stage:"):]
                stack = rest or head
            try:
                n = int(count)
            except ValueError:
                continue
            hz = float(body.get("hz") or 67.0)
            stacks.append({
                "stack": stack, "count": n, "seconds": n / hz,
                "tags": tags,
            })
        return {
            "sources": body.get("sources") or [],
            "samples": int(body.get("samples") or 0),
            "seconds": float(body.get("seconds") or 0.0),
            "stacks": stacks,
        }
    return _local_agg(args)


def cmd_top(args) -> int:
    agg = _agg_of(args)
    if not agg["stacks"]:
        print("no profile data (is RSDL_PROFILE set?)", file=sys.stderr)
        return 3
    rows = profiler.top_table(agg, n=args.n)
    if args.json:
        print(json.dumps({
            "samples": agg["samples"],
            "seconds": round(agg["seconds"], 3),
            "sources": len(agg["sources"]),
            "top": rows,
        }, indent=2))
        return 0
    print(
        f"{agg['samples']} samples, {agg['seconds']:.1f} sampled-seconds,"
        f" {len(agg['sources'])} sources"
    )
    print(f"{'SELF':>8} {'FRAC':>6} {'TOTAL':>8}  FRAME / stages")
    for row in rows:
        stages = ",".join(
            f"{k}={v:.1f}s" for k, v in row["stages"].items()
        )
        print(
            f"{row['self_s']:>7.1f}s {row['self_frac']:>6.1%} "
            f"{row['total_s']:>7.1f}s  {row['frame']}"
            + (f"  [{stages}]" if stages else "")
        )
    return 0


def cmd_flame(args) -> int:
    agg = _agg_of(args)
    if not agg["stacks"]:
        print("no profile data (is RSDL_PROFILE set?)", file=sys.stderr)
        return 3
    title = "rsdl profile"
    if args.stage:
        title += f" · stage={args.stage}"
    html = profiler.render_flame_html(agg, title=title)
    with open(args.out, "w") as f:
        f.write(html)
    print(f"wrote {args.out} ({len(html)} bytes, "
          f"{agg['samples']} samples)")
    return 0


def _digest_of_ref(path: str, ref: str) -> Optional[dict]:
    from ray_shuffling_data_loader_tpu.telemetry import runledger

    records = runledger.read(path)
    try:
        rec = records[int(ref)]
    except (ValueError, IndexError):
        matches = [
            r for r in records
            if str(r.get("id", "")).startswith(ref)
        ]
        rec = matches[0] if len(matches) == 1 else None
    if rec is None:
        return None
    return rec.get("profile")


def _digest_of_dir(directory: str) -> Optional[dict]:
    records = profiler.load_records(directory)
    if not records:
        return None
    return profiler.digest(records=records, n=50)


def cmd_diff(args) -> int:
    if args.ledger:
        base = _digest_of_ref(args.ledger, args.base)
        head = _digest_of_ref(args.ledger, args.head)
    else:
        base = _digest_of_dir(args.base)
        head = _digest_of_dir(args.head)
    if base is None or head is None:
        which = args.base if base is None else args.head
        print(f"no profile data for {which!r}", file=sys.stderr)
        return 3
    shift = profiler.diff_digests(base, head, n=args.n)
    if args.json:
        print(json.dumps(shift, indent=2))
        return 0
    if not shift["regressed"] and not shift["improved"]:
        print("no self-time share movement between BASE and HEAD")
        return 0
    for row in shift["regressed"]:
        print(
            f"+{100 * row['delta_frac']:5.1f}pp  {row['frame']}  "
            f"({100 * row['base_frac']:.1f}% -> "
            f"{100 * row['head_frac']:.1f}%)"
        )
    for row in shift["improved"]:
        print(
            f"{100 * row['delta_frac']:6.1f}pp  {row['frame']}  "
            f"({100 * row['base_frac']:.1f}% -> "
            f"{100 * row['head_frac']:.1f}%)"
        )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0]
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    def _source_args(p):
        p.add_argument("--dir", default=None,
                       help="profile spool directory")
        p.add_argument("--url", default=None,
                       help="live obs endpoint base URL")
        p.add_argument("--stage", default=None)
        p.add_argument("--job", default=None)
        p.add_argument("--epoch", default=None)

    p_top = sub.add_parser("top", help="self/total frame table")
    _source_args(p_top)
    p_top.add_argument("-n", type=int, default=None)
    p_top.add_argument("--json", action="store_true")
    p_flame = sub.add_parser("flame", help="write flamegraph HTML")
    _source_args(p_flame)
    p_flame.add_argument("--out", required=True)
    p_diff = sub.add_parser("diff", help="differential profile")
    p_diff.add_argument("base")
    p_diff.add_argument("head")
    p_diff.add_argument("--ledger", default=None,
                        help="treat BASE/HEAD as run-ledger refs")
    p_diff.add_argument("-n", type=int, default=10)
    p_diff.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)
    if args.cmd == "top":
        return cmd_top(args)
    if args.cmd == "flame":
        return cmd_flame(args)
    return cmd_diff(args)


if __name__ == "__main__":
    sys.exit(main())
