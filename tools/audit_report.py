#!/usr/bin/env python
"""Audit report CLI: join audit verdicts with trial/epoch stats + bench JSON.

Renders one human-readable per-epoch table from the artifacts a run
leaves behind (any subset works; more inputs = more columns):

* ``--bench bench.json`` — the bench's one-line JSON result; its
  embedded ``"audit"`` summary (``bench.py --audit``) is the primary
  verdict source, and headline fields (GB/s, stall%, backend) become the
  report header.
* ``--metrics run.metrics.json`` — ``telemetry.metrics.dump_json``
  artifact; the ``audit.*`` gauges/counters in its final snapshot are
  the fallback verdict source, and totals are cross-checked.
* ``--trial-csv trial_stats.csv`` / ``--epoch-csv epoch_stats.csv`` —
  ``stats.process_stats`` artifacts; epoch durations and stage timings
  join the table by epoch id, trial totals join the header.
* ``--audit-json audit.json`` — a bare ``telemetry.audit.summary()``
  dump, for drivers that write it directly.

Pure stdlib, no server. Exit codes (so CI lanes can gate on it): 0 when
every reconciled epoch passed, 1 on any digest mismatch, 2 on usage
errors, 3 when verdicts are present but NONE actually reconciled (wrong
audit key / unshared spool — zero coverage must not read as a pass).

Example::

    python bench.py --audit --trace-out=/tmp/run.json > /tmp/bench.json
    python tools/audit_report.py --bench /tmp/bench.json \
        --metrics /tmp/run.json.metrics.json
"""

from __future__ import annotations

import argparse
import csv
import json
import re
import sys
from typing import Any, Dict, List, Optional


def _load_json(path: Optional[str]) -> Optional[dict]:
    if not path:
        return None
    with open(path) as f:
        text = f.read().strip()
    # bench stdout may carry log lines around the one JSON line; take the
    # last line that parses as a JSON object.
    try:
        return json.loads(text)
    except ValueError:
        for line in reversed(text.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    continue
    raise ValueError(f"{path}: no JSON object found")


def _load_csv(path: Optional[str]) -> List[Dict[str, str]]:
    if not path:
        return []
    with open(path, newline="") as f:
        return list(csv.DictReader(f))


_AUDIT_GAUGE = re.compile(r"^audit\.([a-z_]+)\{epoch=(\d+)\}$")


def verdicts_from_metrics(snapshot: Dict[str, float]) -> List[Dict[str, Any]]:
    """Reconstruct per-epoch rows from the ``audit.*`` registry keys in a
    metrics snapshot (the fallback when no bench/audit JSON embeds full
    verdicts — counters are totals, gauges are per-epoch)."""
    by_epoch: Dict[int, Dict[str, Any]] = {}
    for key, value in snapshot.items():
        m = _AUDIT_GAUGE.match(key)
        if not m:
            continue
        name, epoch = m.group(1), int(m.group(2))
        row = by_epoch.setdefault(epoch, {"epoch": epoch})
        if name == "epoch_ok":
            row["ok"] = bool(value)
        else:
            row[name] = value
    return [by_epoch[e] for e in sorted(by_epoch)]


def _fmt(value: Any, width: int = 0) -> str:
    if value is None or value == "":
        out = "-"
    elif isinstance(value, bool):
        out = "OK" if value else "MISMATCH"
    elif isinstance(value, float):
        out = f"{value:.4g}"
    else:
        out = str(value)
    return out.rjust(width) if width else out


def _table(rows: List[Dict[str, Any]], columns: List[str]) -> str:
    widths = {
        c: max(len(c), *(len(_fmt(r.get(c))) for r in rows)) for c in columns
    }
    header = "  ".join(c.rjust(widths[c]) for c in columns)
    rule = "  ".join("-" * widths[c] for c in columns)
    body = [
        "  ".join(_fmt(r.get(c), widths[c]) for c in columns) for r in rows
    ]
    return "\n".join([header, rule, *body])


def build_report(
    bench: Optional[dict],
    metrics: Optional[dict],
    audit_json: Optional[dict],
    trial_rows: List[Dict[str, str]],
    epoch_rows: List[Dict[str, str]],
) -> Dict[str, Any]:
    """Merge every input into ``{"header": {...}, "epochs": [...]}``."""
    audit = None
    for candidate in (
        audit_json,
        (bench or {}).get("audit"),
    ):
        if candidate and candidate.get("epochs"):
            audit = candidate
            break
    final_snapshot = (metrics or {}).get("final", {}) if metrics else {}
    epochs: List[Dict[str, Any]] = []
    if audit:
        epochs = [dict(v) for v in audit["epochs"]]
    elif final_snapshot:
        epochs = verdicts_from_metrics(final_snapshot)

    # Join per-epoch stats-CSV timings by epoch id — restricted to the
    # FIRST trial's rows (the CSV carries one row per (trial, epoch);
    # letting later trials overwrite would join another trial's timings
    # onto this run's verdicts).
    first_trial = next(
        (r.get("trial") for r in epoch_rows if r.get("epoch")), None
    )
    by_epoch = {
        int(r["epoch"]): r
        for r in epoch_rows
        if r.get("epoch") and r.get("trial") == first_trial
    }
    for row in epochs:
        stats_row = by_epoch.get(int(row["epoch"]))
        if stats_row:
            for src, dst in (
                ("duration", "epoch_s"),
                ("map_stage_duration", "map_s"),
                ("reduce_stage_duration", "reduce_s"),
                ("throttle_duration", "throttle_s"),
            ):
                try:
                    row[dst] = float(stats_row[src])
                except (KeyError, ValueError, TypeError):
                    pass

    header: Dict[str, Any] = {}
    if bench:
        for k in (
            "value", "unit", "vs_baseline", "stall_pct", "backend",
            "loader", "dataset_gb", "total_s", "error",
        ):
            if k in bench:
                header[k] = bench[k]
    if trial_rows:
        t = trial_rows[0]
        for k in (
            "duration", "num_rows", "num_epochs", "row_throughput",
            "audit_epochs_ok", "audit_mismatch_epochs",
        ):
            if t.get(k):
                header[k] = t[k]
    for k in (
        "audit.rows_mapped", "audit.rows_reduced", "audit.rows_delivered",
        "audit.digest_mismatch",
    ):
        if k in final_snapshot:
            header[k] = final_snapshot[k]
    mismatched = [r["epoch"] for r in epochs if r.get("ok") is False]
    # audit_ok stays None when no epoch actually reconciled (all-null
    # verdicts = zero audit coverage, which must not read as a pass).
    audited = [r for r in epochs if r.get("ok") is not None]
    header["audit_ok"] = (not mismatched) if audited else None
    if mismatched:
        header["mismatch_epochs"] = mismatched
    return {"header": header, "epochs": epochs}


_COLUMNS = [
    "epoch", "ok", "mismatch", "rows_mapped", "rows_reduced",
    "rows_delivered", "rows_consumed", "delivered_digest", "delivered_seq",
    "adjacent_pair_retention", "mean_normalized_displacement",
    "source_entropy_mean", "epoch_s", "map_s", "reduce_s", "throttle_s",
]


def render(report: Dict[str, Any]) -> str:
    lines = ["audit report"]
    for k, v in report["header"].items():
        lines.append(f"  {k}: {_fmt(v)}")
    epochs = report["epochs"]
    if not epochs:
        lines.append("  (no per-epoch audit verdicts in the given inputs)")
        return "\n".join(lines)
    columns = [
        c
        for c in _COLUMNS
        if any(r.get(c) not in (None, "", []) or c in ("epoch", "ok")
               for r in epochs)
    ]
    rows = [
        {
            **r,
            "mismatch": ",".join(r["mismatch"]) if r.get("mismatch") else "",
        }
        for r in epochs
    ]
    lines.append("")
    lines.append(_table(rows, columns))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--bench", help="bench result JSON (bench.py stdout)")
    parser.add_argument(
        "--metrics", help="metrics timeline/snapshot JSON (dump_json)"
    )
    parser.add_argument(
        "--audit-json", help="bare telemetry.audit.summary() JSON dump"
    )
    parser.add_argument("--trial-csv", help="stats.py trial_stats.csv")
    parser.add_argument("--epoch-csv", help="stats.py epoch_stats.csv")
    parser.add_argument(
        "--json", action="store_true",
        help="emit the merged report as JSON instead of a table",
    )
    args = parser.parse_args(argv)
    if not any(
        (args.bench, args.metrics, args.audit_json, args.trial_csv)
    ):
        parser.print_usage(sys.stderr)
        print(
            "audit_report: need at least one of --bench/--metrics/"
            "--audit-json/--trial-csv",
            file=sys.stderr,
        )
        return 2
    try:
        report = build_report(
            _load_json(args.bench),
            _load_json(args.metrics),
            _load_json(args.audit_json),
            _load_csv(args.trial_csv),
            _load_csv(args.epoch_csv),
        )
    except (OSError, ValueError) as exc:
        print(f"audit_report: {exc}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render(report))
    if report["header"].get("audit_ok") is False:
        return 1
    if report["epochs"] and report["header"].get("audit_ok") is None:
        # Verdicts exist but none reconciled: the audit ran with zero
        # coverage (typo'd RSDL_AUDIT_KEY, unshared spool). A gate must
        # not go green on that.
        print(
            "audit_report: no epoch was actually audited (every verdict "
            "is null) — zero coverage is not a pass",
            file=sys.stderr,
        )
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
