#!/usr/bin/env python
"""Query the durable run ledger (telemetry/runledger.py).

Subcommands::

    run_ledger.py [--ledger PATH] list [--limit N] [--json]
    run_ledger.py [--ledger PATH] show <id-or-index>
    run_ledger.py [--ledger PATH] diff <base> <head>
    run_ledger.py [--ledger PATH] --regress BASE..HEAD \
        [--drop-frac 0.2] [--stall-rise-frac 0.5]

Records are addressed by full id (``run-<hex>-<pid>``), unique id
prefix, or append-order index (``0`` oldest, ``-1`` newest). The
``--regress`` gate compares HEAD against BASE and exits **1** when
HEAD's throughput dropped by more than ``--drop-frac`` or its total
stall seconds rose by more than ``--stall-rise-frac`` (relative);
exit **3** when either record (or the ledger itself) is missing, so
CI can tell "regressed" from "nothing to compare". When both records
carry a ``profile`` digest (ISSUE 17), the verdict also NAMES the
frames whose self-time share moved most — where the regression went,
not just that it happened. The ledger path
comes from ``--ledger`` or ``RSDL_RUN_LEDGER`` (same resolution as
the writer: docs/observability.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from ray_shuffling_data_loader_tpu.telemetry import runledger  # noqa: E402


def _resolve(records: List[dict], ref: str) -> Optional[dict]:
    """Index (incl. negative), exact id, or unique id prefix."""
    try:
        return records[int(ref)]
    except (ValueError, IndexError):
        pass
    exact = [r for r in records if r.get("id") == ref]
    if exact:
        return exact[-1]
    prefixed = [r for r in records if str(r.get("id", "")).startswith(ref)]
    if len(prefixed) == 1:
        return prefixed[0]
    return None


def _throughput_of(rec: dict) -> Optional[float]:
    """The comparable throughput figure: rows/s (bench) wins over
    bytes/s (shuffle) — compare like with like."""
    tp = rec.get("throughput") or {}
    for key in ("rows_per_s", "bytes_per_s"):
        value = tp.get(key)
        if value:
            return float(value)
    return None


def _stall_total(rec: dict) -> float:
    return sum(float(v) for v in (rec.get("stall_by_cause") or {}).values())


def _fmt_ts(ts: Any) -> str:
    try:
        return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(float(ts)))
    except (TypeError, ValueError):
        return "?"


def _summary_row(idx: int, rec: dict) -> str:
    job = (rec.get("job") or {}).get("id") or "-"
    tp = _throughput_of(rec)
    return (
        f"{idx:>4}  {rec.get('id', '?'):<24} {_fmt_ts(rec.get('ts')):<19} "
        f"{rec.get('kind', '?'):<7} {rec.get('status', '?'):<9} "
        f"{job:<14} plan={rec.get('plan', '-'):<12} "
        f"dur={rec.get('duration_s', '-'):<8} "
        f"tp={('%.1f' % tp) if tp is not None else '-'} "
        f"stall={_stall_total(rec):.1f}s "
        f"alerts={sum((rec.get('alerts_fired') or {}).values())}"
    )


def cmd_list(records: List[dict], args) -> int:
    rows = records[-args.limit:] if args.limit else records
    offset = len(records) - len(rows)
    if args.json:
        print(json.dumps(rows, indent=2))
        return 0
    if not rows:
        print("ledger is empty")
        return 0
    for i, rec in enumerate(rows):
        print(_summary_row(offset + i, rec))
    return 0


def cmd_show(records: List[dict], args) -> int:
    rec = _resolve(records, args.ref)
    if rec is None:
        print(f"no record matches {args.ref!r}", file=sys.stderr)
        return 3
    print(json.dumps(rec, indent=2, sort_keys=True))
    return 0


def _diff_rows(base: dict, head: dict) -> List[str]:
    out: List[str] = []

    def row(label: str, b: Any, h: Any) -> None:
        if b != h:
            out.append(f"  {label:<22} {b!r:>14} -> {h!r}")

    row("status", base.get("status"), head.get("status"))
    row("plan", base.get("plan"), head.get("plan"))
    row("duration_s", base.get("duration_s"), head.get("duration_s"))
    row("throughput", _throughput_of(base), _throughput_of(head))
    row("stall_total_s", round(_stall_total(base), 3),
        round(_stall_total(head), 3))
    causes = set(base.get("stall_by_cause") or {}) \
        | set(head.get("stall_by_cause") or {})
    for cause in sorted(causes):
        row(f"stall[{cause}]",
            (base.get("stall_by_cause") or {}).get(cause, 0.0),
            (head.get("stall_by_cause") or {}).get(cause, 0.0))
    row("critical_path",
        (base.get("critical") or {}).get("run_critical_path"),
        (head.get("critical") or {}).get("run_critical_path"))
    row("audit_ok", (base.get("audit") or {}).get("ok"),
        (head.get("audit") or {}).get("ok"))
    row("alerts_fired", base.get("alerts_fired") or {},
        head.get("alerts_fired") or {})
    bknobs: Dict[str, str] = base.get("knobs") or {}
    hknobs: Dict[str, str] = head.get("knobs") or {}
    for knob in sorted(set(bknobs) | set(hknobs)):
        row(f"knob {knob}", bknobs.get(knob), hknobs.get(knob))
    bterms: Dict[str, dict] = base.get("plan_terms") or {}
    hterms: Dict[str, dict] = head.get("plan_terms") or {}
    for term in sorted(set(bterms) | set(hterms)):
        row(
            f"plan_term {term}",
            (bterms.get(term) or {}).get("value"),
            (hterms.get(term) or {}).get("value"),
        )
    return out


def cmd_diff(records: List[dict], args) -> int:
    base = _resolve(records, args.base)
    head = _resolve(records, args.head)
    if base is None or head is None:
        missing = args.base if base is None else args.head
        print(f"no record matches {missing!r}", file=sys.stderr)
        return 3
    print(f"base: {base.get('id')} ({_fmt_ts(base.get('ts'))})")
    print(f"head: {head.get('id')} ({_fmt_ts(head.get('ts'))})")
    rows = _diff_rows(base, head)
    if not rows:
        print("no differences in compared fields")
    else:
        for line in rows:
            print(line)
    return 0


def _profile_shift_lines(base: dict, head: dict) -> List[str]:
    """Human-readable profile-digest shift between two records (empty
    when either record lacks a ``profile`` section): the top frames
    whose SELF-time share grew or shrank, by name — fraction-based, so
    runs of different lengths compare meaningfully."""
    bprof, hprof = base.get("profile"), head.get("profile")
    if not bprof or not hprof:
        return []
    try:
        from ray_shuffling_data_loader_tpu.telemetry import profiler

        shift = profiler.diff_digests(bprof, hprof, n=3)
    except Exception:
        return []
    out: List[str] = []
    for row in shift.get("regressed", []):
        out.append(
            "profile: self-time share of %s rose %.1f%% -> %.1f%% "
            "(+%.1f points)" % (
                row["frame"], 100 * row["base_frac"],
                100 * row["head_frac"], 100 * row["delta_frac"],
            )
        )
    for row in shift.get("improved", []):
        out.append(
            "profile: self-time share of %s fell %.1f%% -> %.1f%%" % (
                row["frame"], 100 * row["base_frac"],
                100 * row["head_frac"],
            )
        )
    return out


def _plan_term_lines(base: dict, head: dict) -> List[str]:
    """Plan-compiler decision shifts between two records (empty when
    either lacks a ``plan_terms`` section — ISSUE 20): names the term
    whose effective value or provenance changed, so a throughput
    verdict can be attributed to the planner decision that moved."""
    bterms, hterms = base.get("plan_terms"), head.get("plan_terms")
    if not bterms or not hterms:
        return []
    out: List[str] = []
    for term in sorted(set(bterms) | set(hterms)):
        if term.startswith("_"):
            continue  # bookkeeping entries (_replans)
        b, h = bterms.get(term) or {}, hterms.get(term) or {}
        bval, hval = b.get("value"), h.get("value")
        bsrc, hsrc = b.get("source"), h.get("source")
        if bval != hval:
            out.append(
                f"plan: term {term} changed {bval!r} ({bsrc}) -> "
                f"{hval!r} ({hsrc})"
            )
        elif bsrc != hsrc:
            out.append(
                f"plan: term {term} kept value {hval!r} but its source "
                f"changed {bsrc} -> {hsrc}"
            )
    breplans = (bterms.get("_replans") or {}).get("value", 0)
    hreplans = (hterms.get("_replans") or {}).get("value", 0)
    if breplans != hreplans:
        out.append(
            f"plan: mid-run re-plans {breplans} -> {hreplans}"
        )
    return out


def cmd_regress(records: List[dict], args) -> int:
    spec = args.regress
    if ".." not in spec:
        print("--regress wants BASE..HEAD", file=sys.stderr)
        return 2
    base_ref, _, head_ref = spec.partition("..")
    base = _resolve(records, base_ref)
    head = _resolve(records, head_ref)
    if base is None or head is None:
        missing = base_ref if base is None else head_ref
        print(f"no record matches {missing!r}", file=sys.stderr)
        return 3
    failures: List[str] = []
    btp, htp = _throughput_of(base), _throughput_of(head)
    if btp and htp is not None:
        drop = (btp - htp) / btp
        if drop > args.drop_frac:
            failures.append(
                f"throughput dropped {drop:.1%} "
                f"({btp:.1f} -> {htp:.1f}, limit {args.drop_frac:.0%})"
            )
    bstall, hstall = _stall_total(base), _stall_total(head)
    if bstall > 0:
        rise = (hstall - bstall) / bstall
        if rise > args.stall_rise_frac:
            failures.append(
                f"stall seconds rose {rise:.1%} "
                f"({bstall:.1f}s -> {hstall:.1f}s, "
                f"limit {args.stall_rise_frac:.0%})"
            )
    elif hstall > 0 and btp and htp:
        # A base with zero recorded stall: any material stall showing
        # up in HEAD while throughput also moved is worth flagging.
        if (btp - htp) / btp > args.drop_frac:
            failures.append(
                f"stalls appeared ({hstall:.1f}s) alongside a "
                f"throughput drop"
            )
    if head.get("status") == "failed" and base.get("status") == "done":
        failures.append("head run failed where base succeeded")
    print(f"base: {base.get('id')}  head: {head.get('id')}")
    profile_lines = _profile_shift_lines(base, head)
    plan_lines = _plan_term_lines(base, head)
    if failures:
        for f in failures:
            print(f"REGRESSION: {f}")
        # The profiling plane's whole point (ISSUE 17): when the gate
        # trips, NAME the frame the time moved into, not just that it
        # moved. Same for the planner (ISSUE 20): name the plan term
        # that changed alongside the throughput verdict.
        for line in profile_lines:
            print(line)
        for line in plan_lines:
            print(line)
        return 1
    for line in profile_lines:
        print(line)
    for line in plan_lines:
        print(line)
    print(
        f"ok: throughput {btp if btp is not None else '-'} -> "
        f"{htp if htp is not None else '-'}, "
        f"stall {bstall:.1f}s -> {hstall:.1f}s"
    )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--ledger", default=None,
        help="ledger NDJSON path (default: RSDL_RUN_LEDGER resolution)",
    )
    parser.add_argument(
        "--regress", metavar="BASE..HEAD", default=None,
        help="exit 1 if HEAD regressed vs BASE beyond thresholds",
    )
    parser.add_argument("--drop-frac", type=float, default=0.2,
                        help="tolerated relative throughput drop")
    parser.add_argument("--stall-rise-frac", type=float, default=0.5,
                        help="tolerated relative stall-seconds rise")
    sub = parser.add_subparsers(dest="cmd")
    p_list = sub.add_parser("list", help="one line per record")
    p_list.add_argument("--limit", type=int, default=0)
    p_list.add_argument("--json", action="store_true")
    p_show = sub.add_parser("show", help="full record JSON")
    p_show.add_argument("ref")
    p_diff = sub.add_parser("diff", help="field-level comparison")
    p_diff.add_argument("base")
    p_diff.add_argument("head")
    args = parser.parse_args(argv)

    path = args.ledger if args.ledger else runledger.ledger_path()
    if path is None:
        print(
            "no ledger: pass --ledger or set RSDL_RUN_LEDGER",
            file=sys.stderr,
        )
        return 3
    records = runledger.read(path)
    if args.regress:
        if not records:
            print(f"ledger {path} is empty or missing", file=sys.stderr)
            return 3
        return cmd_regress(records, args)
    if args.cmd == "show":
        return cmd_show(records, args)
    if args.cmd == "diff":
        return cmd_diff(records, args)
    if args.cmd is None:
        args.limit, args.json = 0, False
    return cmd_list(records, args)


if __name__ == "__main__":
    sys.exit(main())
