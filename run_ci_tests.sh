#!/usr/bin/env bash
# CI test entry (reference run_ci_tests.sh:8-11 wraps pytest likewise).
# Tests force the CPU backend with 8 virtual devices via tests/conftest.py.
set -euo pipefail
cd "$(dirname "$0")"
python -m pytest tests/ -v --durations=10 -x
