#!/usr/bin/env bash
# CI test entry (reference run_ci_tests.sh:8-11 wraps pytest likewise),
# two-tiered (VERDICT r3 item 7):
#   fast tier — in-process tests, fail-fast (-x), target <8 min;
#   slow tier — multi-process/subprocess tests (@pytest.mark.slow), run
#   WITHOUT -x so one flaky subprocess test cannot kill the whole lane.
# Tests force the CPU backend with 8 virtual devices via tests/conftest.py.
# RSDL_CI_TIER=fast|slow runs a single tier (CI matrix lanes); default both.
set -euo pipefail
cd "$(dirname "$0")"
tier="${RSDL_CI_TIER:-all}"
rc=0
if [ "$tier" != "slow" ]; then
  python -m pytest tests/ -m "not slow" -v --durations=10 -x
fi
if [ "$tier" != "fast" ]; then
  python -m pytest tests/ -m slow -v --durations=10 || rc=$?
fi
exit $rc
