#!/usr/bin/env bash
# CI test entry (reference run_ci_tests.sh:8-11 wraps pytest likewise),
# two-tiered (VERDICT r3 item 7):
#   fast tier — in-process tests, fail-fast (-x), target <8 min;
#   slow tier — multi-process/subprocess tests (@pytest.mark.slow), run
#   WITHOUT -x so one flaky subprocess test cannot kill the whole lane.
# Tests force the CPU backend with 8 virtual devices via tests/conftest.py.
# RSDL_CI_TIER=fast|slow runs a single tier (CI matrix lanes); default both.
set -euo pipefail
cd "$(dirname "$0")"
tier="${RSDL_CI_TIER:-all}"
rc=0
if [ "$tier" != "slow" ]; then
  # Static-analysis lane (ISSUE 14), exit-code gated and FIRST: the
  # invariant suite (gate-integrity lazy-import graph, knob registry vs
  # TUNING.md, metric/event vocabulary vs observability.md, determinism
  # hygiene, lock discipline, flush-before-done barriers) is pure AST —
  # seconds, no runtime — so a structural violation fails the lane
  # before any test minute is spent. docs/static-analysis.md has the
  # checker catalog and the suppression policy.
  python tools/rsdl_lint.py
  # Telemetry is env-gated and DEFAULT OFF: this pass asserts tier-1 is
  # clean with it disabled (the zero-overhead path).
  python -m pytest tests/ -m "not slow" -v --durations=10 -x
  # ... and must not perturb the data plane when ENABLED: re-run the
  # core data-path tests with tracing + metrics on, spooling to a throwaway
  # dir (every spawned worker/actor inherits the env and spools spans).
  RSDL_TRACE=1 RSDL_METRICS=1 RSDL_TRACE_DIR="$(mktemp -d)" \
    python -m pytest tests/test_telemetry.py tests/test_shuffle.py \
      tests/test_batch_queue.py tests/test_dataset.py \
      tests/test_jax_dataset.py tests/test_stats.py \
      -m "not slow" -q -x
  # Audit lane (ISSUE 2): the data-correctness digests on — the shuffle,
  # queue, dataset, and device-staging suites must pass with every stage
  # folding exactly-once digests, and the audit suite itself verifies the
  # verdicts (incl. the injected-fault and determinism checks).
  RSDL_AUDIT=1 RSDL_AUDIT_DIR="$(mktemp -d)" RSDL_METRICS=1 \
    python -m pytest tests/test_audit.py tests/test_shuffle.py \
      tests/test_batch_queue.py tests/test_dataset.py \
      tests/test_jax_dataset.py tests/test_audit_report.py \
      -m "not slow" -q -x
  # Chaos lane (ISSUE 3): the fault-injection plane armed with a fixed-
  # seed low-probability schedule across the core data-path suites —
  # recovery (bounded stage re-execution + transport retry) must make
  # the injected crashes/resets INVISIBLE to every existing test, and
  # the dedicated chaos harness proves each failure class reconciles
  # exactly-once under RSDL_AUDIT (docs/robustness.md). The xN caps
  # keep the lane deterministic-by-construction: at most 1 crash per
  # worker (2 workers) and 2 resets per driver process can never
  # exhaust a 3-attempt retry budget, so no probabilistic flake mode
  # exists regardless of task placement.
  # RSDL_TCP_ZEROCOPY rides along so recovery is proven over the
  # vectored-framing transport path too (ISSUE 5), not just the legacy
  # pickle frames; RSDL_TCP_STREAMS=2 keeps striping on so transport
  # fault sites exercise per-stream connections (ISSUE 6).
  # tests/test_slo.py rides the chaos lane for its wedge-alert proof
  # (ISSUE 9): an injected wedge fault must fire — and later resolve —
  # the default wedged-worker alert with audit ok=true (the test arms
  # its own deterministic RSDL_FAULTS schedule, overriding the lane's).
  RSDL_AUDIT=1 RSDL_AUDIT_DIR="$(mktemp -d)" RSDL_METRICS=1 \
    RSDL_TCP_ZEROCOPY=1 RSDL_TCP_STREAMS=2 \
    RSDL_FAULTS="task.map/task:crash-entry:0.03x1,task.reduce/task:crash-exit:0.03x1,transport.send/driver:reset:0.02x2" \
    RSDL_FAULTS_SEED=1234 \
    python -m pytest tests/test_chaos.py tests/test_shuffle.py \
      tests/test_batch_queue.py tests/test_dataset.py \
      tests/test_slo.py \
      -m "not slow" -q -x
  # Observability lane (ISSUE 4): the live obs plane on — metrics
  # spool/aggregation + the RSDL_OBS_PORT scrape endpoint enabled for
  # the telemetry/obs suites (core data-path suites ride along so the
  # endpoint demonstrably doesn't perturb them; the smoke test binds
  # its own free port, so a taken lane port only warns).
  # The decision plane (ISSUE 9) rides the obs lane: capacity-ledger
  # accounting + zero-overhead proof, online-vs-post-hoc critical-path
  # parity, and SLO rule-engine semantics.
  RSDL_METRICS=1 RSDL_OBS_PORT=18431 \
    python -m pytest tests/test_obs.py tests/test_telemetry.py \
      tests/test_epoch_report.py tests/test_shuffle.py \
      tests/test_capacity.py tests/test_critical.py \
      -m "not slow" -q -x
  # Epoch critical-path report, gated BOTH ways against the committed
  # fixture pair: a clean run must exit 0 (and name the dominant
  # stage), an injected regression must exit non-zero.
  python tools/epoch_report.py \
    --trace tests/fixtures/epoch_report/trace.json \
    --epoch-csv tests/fixtures/epoch_report/epoch_stats.csv \
    --bench tests/fixtures/epoch_report/bench_clean.json \
    --baseline tests/fixtures/epoch_report/baseline.json
  if python tools/epoch_report.py \
    --trace tests/fixtures/epoch_report/trace.json \
    --bench tests/fixtures/epoch_report/bench_regressed.json \
    --baseline tests/fixtures/epoch_report/baseline.json > /dev/null; then
    echo "epoch_report failed to flag the injected regression" >&2
    exit 1
  fi
  # Device-direct lane (ISSUE 8): reducer outputs in staging layout
  # forced ON across the core data-path suites — batch-aligned packed
  # bodies + boundary remainders must be invisible to every existing
  # consumer (bit-identical streams), reconcile exactly-once under
  # RSDL_AUDIT (packed segments digest through their logical column
  # views), and survive the chaos schedule (a retried reduce re-packs
  # against the same rank-stream offsets). Exit-code gated like every
  # other lane.
  RSDL_DEVICE_DIRECT=on \
    RSDL_AUDIT=1 RSDL_AUDIT_DIR="$(mktemp -d)" RSDL_METRICS=1 \
    RSDL_FAULTS="task.map/task:crash-entry:0.03x1,task.reduce/task:crash-exit:0.03x1" \
    RSDL_FAULTS_SEED=4321 \
    python -m pytest tests/test_device_direct.py \
      tests/test_device_direct_audit.py tests/test_jax_dataset.py \
      tests/test_dataset.py tests/test_shuffle.py \
      -m "not slow" -q -x
  # Elastic lane (ISSUE 10): autoscaler + tiered store eviction +
  # graceful drain, chaos-proven. The membership/drain/evict tests run
  # under a low-prob ambient fault schedule (same xN-capped convention
  # as the chaos lane) with audit strict + metrics on; the acceptance
  # test — scale-up, a crash mid-drain degrading into the failover
  # backstop, a shm→spill→drop eviction re-materialized from lineage,
  # audit ok=true and ledger residency zero at cleanup — arms its own
  # deterministic schedule on top. Exit-code gated.
  RSDL_AUDIT=1 RSDL_AUDIT_DIR="$(mktemp -d)" RSDL_METRICS=1 \
    RSDL_FAULTS="task.map/task:crash-entry:0.03x1,task.reduce/task:crash-exit:0.03x1" \
    RSDL_FAULTS_SEED=555 \
    python -m pytest tests/test_elastic.py -m "not slow" -q -x
  # Decode-plane lane (ISSUE 11): row-group parallelism FORCED (2
  # threads on any host), column pushdown derived from staging layouts,
  # and the cross-epoch shared decode cache — all under the audit-STRICT
  # chaos schedule, so bit-identity of the parallel/selective/pushdown
  # decode paths is proven by exactly-once digests, not just unit
  # asserts. The dedicated suite owns the shared-cache assertions.
  RSDL_DECODE_ROWGROUPS=2 RSDL_DECODE_PUSHDOWN=on \
    RSDL_DECODE_CACHE_SHARED=on \
    RSDL_AUDIT=1 RSDL_AUDIT_STRICT=1 RSDL_AUDIT_DIR="$(mktemp -d)" \
    RSDL_METRICS=1 \
    RSDL_FAULTS="task.map/task:crash-entry:0.03x1,task.reduce/task:crash-exit:0.03x1" \
    RSDL_FAULTS_SEED=777 \
    python -m pytest tests/test_decode_plane.py -m "not slow" -q -x
  # Block-plan leg (ISSUE 12): the plan family switched to block:1 with
  # the selective schedule FORCED ON, under the same audit-STRICT chaos
  # schedule — exactly-once coverage must hold when the plan family
  # changes mid-fleet-of-faults, per-reducer row-group selections are
  # disjoint by construction (each group decoded once per epoch), and
  # the stream-equality tests prove selective==materialized under the
  # BLOCK plan too. The shared-cache tests are excluded: a forced
  # selective schedule never publishes decode-cache segments, so their
  # epoch-0 index-schedule assertions cannot hold by design.
  RSDL_SHUFFLE_PLAN=block RSDL_SELECTIVE_READS=on \
    RSDL_DECODE_ROWGROUPS=2 \
    RSDL_AUDIT=1 RSDL_AUDIT_STRICT=1 RSDL_AUDIT_DIR="$(mktemp -d)" \
    RSDL_METRICS=1 \
    RSDL_FAULTS="task.map/task:crash-entry:0.03x1,task.reduce/task:crash-exit:0.03x1" \
    RSDL_FAULTS_SEED=888 \
    python -m pytest tests/test_decode_plane.py -m "not slow" \
      -k "not shared_cache" -q -x
  # ... and the decode knobs must be invisible to the core data-path
  # suites: forced row-group parallelism + pushdown ride along (shared
  # cache deliberately NOT set here — cross-run cache hits legitimately
  # change epoch-0 schedules, which test_shuffle asserts).
  RSDL_DECODE_ROWGROUPS=2 RSDL_DECODE_PUSHDOWN=on \
    RSDL_AUDIT=1 RSDL_AUDIT_DIR="$(mktemp -d)" RSDL_METRICS=1 \
    python -m pytest tests/test_shuffle.py tests/test_dataset.py \
      tests/test_jax_dataset.py -m "not slow" -q -x
  # Planner lane (ISSUE 20): the cost-based plan compiler FORCED ON over
  # the shuffle/decode/device-direct suites under strict audit + the
  # same low-prob xN-capped fault schedule — planned runs must stay
  # exactly-once and bit-identical for fixed seed + fixed plan, with
  # every planner-chosen knob (plan family, selective engagement,
  # decode threads, window depth, native threads) riding the stage-task
  # knob channel instead of the workers' stale env snapshots. The
  # planner suite itself owns the cost-model units, override precedence,
  # replan recording, and the zero-overhead-off fresh-interpreter proof.
  RSDL_PLAN=auto \
    RSDL_AUDIT=1 RSDL_AUDIT_STRICT=1 RSDL_AUDIT_DIR="$(mktemp -d)" \
    RSDL_METRICS=1 \
    RSDL_FAULTS="task.map/task:crash-entry:0.03x1,task.reduce/task:crash-exit:0.03x1" \
    RSDL_FAULTS_SEED=2020 \
    python -m pytest tests/test_planner.py tests/test_shuffle.py \
      tests/test_decode_plane.py tests/test_device_direct.py \
      -m "not slow" -k "not shared_cache" -q -x
  # Resume lane (ISSUE 13): the durable epoch-state plane under chaos.
  # Journal fold/identity units, graceful suspend (programmatic +
  # SIGTERM), the SIGKILL-the-driver kill-and-resume legs (per-rank
  # delivered_seq digests bit-identical to an uninterrupted same-seed
  # control, journaled-complete epochs re-execute zero stage tasks,
  # capacity residency folds to zero), the degraded resume with the
  # store segments dropped, the zero-overhead-off fresh-interpreter
  # proof, and tools/replay.py's divergence gate — all with strict
  # audit on and the fixed-seed xN-capped fault schedule riding into
  # every child driver (recovery is exactly-once, so injected crashes
  # must be invisible to digest equality across the preemption). The
  # checkpoint suite rides along: torn-publish debris pruning and the
  # cursor's plan-family stream identity share this failure model.
  # Chaos tests stay function-scoped-runtime per the established
  # recipe; the kill legs own no pytest-process runtime at all.
  RSDL_AUDIT=1 RSDL_AUDIT_STRICT=1 RSDL_AUDIT_DIR="$(mktemp -d)" \
    RSDL_METRICS=1 \
    RSDL_FAULTS="task.map/task:crash-entry:0.03x1,task.reduce/task:crash-exit:0.03x1" \
    RSDL_FAULTS_SEED=1313 \
    python -m pytest tests/test_resume.py tests/test_checkpoint.py \
      -m "not slow" -q -x
  # Service lane (ISSUE 15): the multi-tenant shuffle service — two
  # concurrent jobs under a low-prob xN-capped fault schedule with
  # STRICT per-job audit (the two-job concurrency test proves per-job
  # ok=true AND delivered_seq digests bit-identical to solo same-seed
  # runs; the chaos leg proves one job's crashed reducer never touches
  # the neighbor's epochs), plus the name-collision regression,
  # fair-share/admission units, cross-job cache-hot, and the
  # zero-overhead-off fresh-interpreter proof. The suite arms
  # RSDL_SERVICE itself per test (function-scoped runtimes); the
  # lane-level schedule rides into every spawned worker.
  RSDL_FAULTS="task.map/task:crash-entry:0.03x1,task.reduce/task:crash-exit:0.03x1" \
    RSDL_FAULTS_SEED=1515 \
    python -m pytest tests/test_service.py -m "not slow" -q -x
  # Temporal + decision obs smoke (ISSUES 7/9), exit-code gated:
  # against a MID-FLIGHT shuffle with the obs endpoint up, /timeseries
  # must serve a non-empty rate series, `rsdl_top --once --json` must
  # render a frame, /capacity must show live per-epoch residency,
  # /critical must name a critical-path stage, a deliberately-tripped
  # SLO rule must FIRE and RESOLVE on /alerts (both transitions event-
  # logged), and /events must carry the full epoch lifecycle afterwards
  # (tools/obs_smoke.py asserts all of it; its exit code is the gate).
  # The fleet plane rides along (ISSUE 16): the smoke arms the service
  # plane, so /jobs must list the running tenant mid-flight and the
  # job=-filtered /events must return the tenant's stamped events (and
  # nothing for a bogus id).
  RSDL_METRICS=1 python tools/obs_smoke.py
  # Relay lane (ISSUE 19): cross-host telemetry federation. The unit
  # suite proves the protocol (receiver restamping for clock-skew
  # safety, CRC/gap/overlap idempotency, shared-filesystem skip,
  # bounded drop-ahead, sink-death degradation) and the federation
  # smoke is the live gate: a second host process joins over TCP with
  # NO shared spool tree and the driver's /metrics must show >= 2
  # distinct host= labels MID-FLIGHT with a fresh relay source on
  # /healthz (exit-code gated; the two-host no-shared-spool chaos
  # acceptance test runs in the slow tier).
  RSDL_METRICS=1 python -m pytest tests/test_relay.py -m "not slow" -q -x
  RSDL_METRICS=1 python tools/obs_smoke.py --federation > /dev/null
  # Profile lane (ISSUE 17): the continuous sampling profiler armed
  # across the core data-path + profiler suites — every process (driver,
  # task workers, actor hosts) runs the sampler daemon and spools, and
  # none of it may perturb the data plane (bit-identical streams, same
  # green tests). The profiler suite itself proves folding, tagging,
  # merge, diff math, and the zero-overhead-off fresh-interpreter
  # contract.
  RSDL_PROFILE=1 RSDL_METRICS=1 \
    python -m pytest tests/test_profiler.py tests/test_shuffle.py \
      tests/test_batch_queue.py tests/test_dataset.py \
      tests/test_jax_dataset.py -m "not slow" -q -x
  # Run-ledger regression gate (ISSUE 16), gated BOTH ways against the
  # committed fixture pair: the clean base..head must exit 0, the
  # fixture with an injected throughput drop + stall rise must exit
  # non-zero — and (ISSUE 17) its verdict must NAME the frame the
  # regression's time moved into, from the records' profile digests.
  python tools/run_ledger.py \
    --ledger tests/fixtures/run_ledger/clean.ndjson --regress 0..1
  if regress_out=$(python tools/run_ledger.py \
    --ledger tests/fixtures/run_ledger/regressed.ndjson \
    --regress 0..1); then
    echo "run_ledger --regress failed to flag the regressed fixture" >&2
    exit 1
  fi
  if ! grep -q "runtime.store:_spill_segment" <<<"$regress_out"; then
    echo "run_ledger --regress did not name the regressed frame" >&2
    exit 1
  fi
  # TCP-plane lane (ISSUE 5/6): the two-process loopback "two-host"
  # bench at a small shape — a worker host joins over real TCP (own shm
  # dir), the windowed-fetch microbench runs all framings (legacy
  # pickle, RSDL_TCP_ZEROCOPY vectored, and RSDL_TCP_STREAMS=2 striped),
  # and the end-to-end two-host shuffle — striping on cluster-wide —
  # must reconcile exactly-once over the wire (the bench exits non-zero
  # on any error OR an audit mismatch, so the exit code IS the gate).
  RSDL_BENCH_TCP_WINDOWS=12 RSDL_BENCH_TCP_WINDOW_MB=1 \
    RSDL_BENCH_TCP_SHUFFLE_GB=0.02 RSDL_BENCH_TCP_STREAMS=2 \
    python bench.py --plane tcp > /dev/null
fi
if [ "$tier" != "fast" ]; then
  python -m pytest tests/ -m slow -v --durations=10 || rc=$?
fi
exit $rc
