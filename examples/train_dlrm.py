"""End-to-end distributed training example: DLRM over per-epoch-shuffled data.

The TPU-native counterpart of the reference's Horovod example
(``examples/horovod/ray_torch_shuffle.py:39-347``): generate (or reuse) the
synthetic DATA_SPEC dataset, shuffle it every epoch, and train a
data-parallel model on the shuffled batches, measuring per-batch wait times
(the trainer-stall north-star metric, reference ``:195-231``).

Differences by design, not omission:

* One process drives *all local TPU chips* through a ``('data', 'model')``
  mesh — the per-GPU-process + Horovod topology collapses into JAX SPMD.
  Gradient exchange is the ``psum`` XLA inserts for the sharded train step
  (reference uses ``hvd.DistributedOptimizer`` over NCCL, ``:183-193``).
  Multi-host pods: run one copy per host under ``jax.distributed`` — the
  dataset then stages each host's shard and batches are globally sharded.
* The train step is REAL (forward/backward/update on the flagship DLRM);
  the reference mocks it with ``time.sleep`` (``:214``). Pass
  ``--mock-train-step-time`` to reproduce the reference's loader-only
  measurement mode.

Run (CPU smoke): JAX_PLATFORMS=cpu python examples/train_dlrm.py \
    --num-rows 100000 --num-files 4 --batch-size 4096 --epochs 2
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    # Workload (reference arg names where they exist, :39-121).
    p.add_argument("--num-rows", type=int, default=10 ** 6)
    p.add_argument("--num-files", type=int, default=10)
    p.add_argument("--num-row-groups-per-file", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=250_000)
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--num-reducers", type=int, default=8)
    p.add_argument("--max-concurrent-epochs", type=int, default=2)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--data-dir", type=str, default="example_data")
    p.add_argument(
        "--mock-train-step-time",
        type=float,
        default=None,
        help="Replace the real train step with a sleep of this many seconds "
        "(the reference's default mode, ray_torch_shuffle.py:214).",
    )
    # Model / optimization.
    p.add_argument(
        "--model",
        choices=("dlrm", "transformer"),
        default="dlrm",
        help="Model family: the flagship DLRM or the TabTransformer "
        "encoder (models/transformer.py).",
    )
    p.add_argument("--embed-dim", type=int, default=32)
    p.add_argument("--learning-rate", type=float, default=1e-3)
    p.add_argument(
        "--model-parallelism",
        type=int,
        default=1,
        help="Size of the mesh 'model' axis (shards large embedding vocabs).",
    )
    # Checkpoint / resume (no reference analog — the loader had none,
    # SURVEY §5; preemptible TPU pods need it).
    p.add_argument(
        "--checkpoint-dir",
        type=str,
        default=None,
        help="Enable checkpointing to this directory; if it already holds a "
        "checkpoint, training resumes from it (mid-epoch batch cursor "
        "included).",
    )
    p.add_argument(
        "--checkpoint-every",
        type=int,
        default=50,
        help="Steps between checkpoints.",
    )
    p.add_argument(
        "--loader",
        choices=("auto", "resident", "mapreduce"),
        default="auto",
        help="Batch delivery path: 'resident' shuffles each epoch on "
        "device (permutation + gather in HBM; needs the packed dataset "
        "to fit the device budget), 'mapreduce' is the general host "
        "pipeline, 'auto' picks resident when it fits.",
    )
    # Gradient plane (reference: Horovod op=Average/Adasum + fp16
    # compression flags, ray_torch_shuffle.py:183-193).
    p.add_argument(
        "--grad-reduce",
        choices=("pjit", "mean", "adasum"),
        default="pjit",
        help="'pjit' (default): sharding-driven step, XLA derives the "
        "all-reduce. 'mean'/'adasum': the explicit shard_map step with a "
        "hand-written collective — 'adasum' is the hvd.Adasum analog "
        "(adaptive summation). Both need --model-parallelism 1 "
        "(replicated params).",
    )
    p.add_argument(
        "--grad-bf16",
        action="store_true",
        help="bf16 gradient wire compression (the fp16-compression "
        "analog; explicit --grad-reduce modes only).",
    )
    p.add_argument(
        "--smoke",
        action="store_true",
        help="Tiny CI workload preset (overrides the size knobs).",
    )
    args = p.parse_args(argv)
    if args.grad_reduce != "pjit" and args.model_parallelism != 1:
        p.error("--grad-reduce mean/adasum requires --model-parallelism 1")
    if args.grad_bf16 and args.grad_reduce == "pjit":
        p.error("--grad-bf16 needs an explicit mode (--grad-reduce mean/adasum)")
    if args.smoke:
        args.num_rows = 50_000
        args.num_files = 4
        args.num_row_groups_per_file = 1
        args.batch_size = 4096
        args.epochs = 2
        args.num_reducers = 4
        args.embed_dim = 8
        args.data_dir = os.path.join(args.data_dir, "smoke")
    return args


def get_data(args):
    """Generate the dataset once and reuse it across runs (the reference
    caches the filename list in a pickle, ``ray_torch_shuffle.py:294-314``)."""
    from ray_shuffling_data_loader_tpu.data_generation import (
        cached_generate_data,
    )

    t0 = time.perf_counter()
    filenames, num_bytes = cached_generate_data(
        args.num_rows,
        args.num_files,
        args.num_row_groups_per_file,
        args.data_dir,
        seed=args.seed,
    )
    if time.perf_counter() - t0 > 1.0:
        print(f"Generated {num_bytes / 1e9:.2f} GB.")
    else:
        print(f"Reusing {len(filenames)} cached files in {args.data_dir}")
    return filenames


def main(argv=None) -> int:
    args = parse_args(argv)

    import jax

    from ray_shuffling_data_loader_tpu.utils import force_platform_from_env

    # Honor the user's platform choice even under TPU plugins that
    # override JAX_PLATFORMS (the CPU smoke invocation depends on this).
    force_platform_from_env()

    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.data_generation import (
        DATA_SPEC,
        LABEL_COLUMN,
    )
    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
    from ray_shuffling_data_loader_tpu.models import dlrm_for_data_spec
    from ray_shuffling_data_loader_tpu.parallel import (
        batch_sharding,
        init_state,
        make_train_step,
    )
    from ray_shuffling_data_loader_tpu.parallel.mesh import make_mesh

    from ray_shuffling_data_loader_tpu import resident as resident_mod

    runtime.init()
    os.makedirs(args.data_dir, exist_ok=True)
    filenames = get_data(args)

    # Mesh over every local chip: batch along 'data', big vocabs along
    # 'model' (the Horovod example instead pins one GPU per worker process,
    # ray_torch_shuffle.py:144-151).
    mesh = make_mesh(model_parallelism=args.model_parallelism)
    print(f"mesh: {dict(mesh.shape)} on {jax.device_count()} devices")

    feature_columns = [c for c in DATA_SPEC if c != LABEL_COLUMN]

    # Loader choice (see resident.py): epoch shuffle on device when the
    # packed dataset fits the budget, host map/reduce otherwise.
    if args.loader == "mapreduce":
        use_resident = False
    else:
        # SPMD on pods: every process evaluates this same call, so the
        # pod-consistent vote is safe (resident engages only when every
        # host's budget agrees).
        fits = resident_mod.fits_device(
            filenames,
            len(feature_columns),
            mesh=mesh,
            num_rows=args.num_rows,
            pod_consistent=True,
        )
        use_resident = args.loader == "resident" or fits
        if use_resident and not fits:
            # Say WHY auto would have declined, so the one warning that
            # matters (a genuine budget overrun on a real accelerator)
            # isn't drowned by deliberate CPU/pod opt-ins.
            if jax.process_count() > 1:
                print(
                    "note: pod auto-select declined (some host's budget "
                    "vote was no); every process is forcing resident"
                )
            elif jax.local_devices()[0].platform == "cpu":
                print(
                    "note: resident loader forced on the CPU backend "
                    "(auto prefers map/reduce there — see BENCHLOG.md)"
                )
            else:
                print(
                    "warning: --loader resident forced but the packed "
                    "dataset may exceed the device memory budget"
                )
    print(f"loader: {'device-resident' if use_resident else 'map/reduce'}")

    if args.model == "transformer":
        from ray_shuffling_data_loader_tpu.models import (
            transformer_for_data_spec,
        )

        model = transformer_for_data_spec(embed_dim=args.embed_dim)
    else:
        model = dlrm_for_data_spec(embed_dim=args.embed_dim)
    optimizer = optax.adam(args.learning_rate)
    example = {
        c: jnp.zeros((args.batch_size,), jnp.int32) for c in feature_columns
    }
    state, state_shardings = init_state(model, optimizer, mesh, example)
    if args.grad_reduce == "pjit":
        train_step = make_train_step(model, optimizer, mesh, state_shardings)
    else:
        # Explicit gradient plane (replicated params): hand-written
        # pmean or Adasum collective under shard_map — the literal
        # Horovod-allreduce analog, selectable like the reference's
        # op=Average/Adasum flag (ray_torch_shuffle.py:183-193).
        from ray_shuffling_data_loader_tpu.parallel import (
            make_psum_train_step,
        )

        train_step = make_psum_train_step(
            model,
            optimizer,
            mesh,
            grad_dtype=jnp.bfloat16 if args.grad_bf16 else None,
            grad_reduce=args.grad_reduce,
        )
        print(
            f"gradient plane: explicit {args.grad_reduce}"
            + (" + bf16 wire" if args.grad_bf16 else "")
        )

    # Compile off the hot path, with inputs placed exactly as real batches
    # will arrive (committed + mesh-sharded). AOT lower/compile: no
    # execution, so the donated state buffer stays live for the loop.
    bsh = batch_sharding(mesh, 1)
    warm_feats = {k: jax.device_put(v, bsh) for k, v in example.items()}
    warm_labels = jax.device_put(
        jnp.zeros((args.batch_size,), jnp.float32), bsh
    )
    train_step = train_step.lower(state, warm_feats, warm_labels).compile()

    # Checkpoint/resume: restore state + batch cursor if a checkpoint
    # exists, and save every --checkpoint-every steps.
    ckpt_mgr = None
    start_epoch, resume_skip, global_step = 0, 0, 0
    stream_config = None
    if args.checkpoint_dir:
        from ray_shuffling_data_loader_tpu import BatchCursor, CheckpointManager

        ckpt_mgr = CheckpointManager(args.checkpoint_dir)
        stream_config = BatchCursor.stream_config(
            seed=args.seed,
            batch_size=args.batch_size,
            num_trainers=1,
            num_reducers=args.num_reducers,
            num_files=len(filenames),
            drop_last=True,
        )
        restored, cursor = ckpt_mgr.restore(
            target=state, shardings=state_shardings
        )
        if cursor is not None:
            # The two loaders produce different (both deterministic)
            # batch streams, so a resume must keep the loader the
            # checkpoint was written under. Cursors from before the
            # resident loader existed carry no key and mean map/reduce.
            ckpt_loader = (cursor.config or {}).get("loader", "mapreduce")
            if args.loader not in ("auto", ckpt_loader):
                raise SystemExit(
                    f"--loader {args.loader} conflicts with this "
                    f"checkpoint's batch stream (written under "
                    f"{ckpt_loader}); resume with --loader {ckpt_loader}"
                )
            if use_resident != (ckpt_loader == "resident"):
                print(
                    f"checkpoint forces loader {ckpt_loader} (overriding "
                    f"the auto choice above); if this machine cannot fit "
                    f"the resident buffer, restart with a fresh "
                    f"--checkpoint-dir"
                )
            use_resident = ckpt_loader == "resident"
            if "loader" in (cursor.config or {}):
                stream_config["loader"] = ckpt_loader
            cursor.validate(stream_config)
            state = restored if restored is not None else state
            start_epoch = cursor.epoch
            resume_skip = cursor.batches_yielded
            global_step = cursor.step
            print(
                f"resuming from step {global_step}: epoch {start_epoch}, "
                f"skipping {resume_skip} already-trained batches"
            )
        else:
            stream_config["loader"] = (
                "resident" if use_resident else "mapreduce"
            )

    if use_resident:
        ds = resident_mod.DeviceResidentShufflingDataset(
            filenames,
            num_epochs=args.epochs,
            batch_size=args.batch_size,
            feature_columns=feature_columns,
            label_column=LABEL_COLUMN,
            seed=args.seed,
            mesh=mesh,
            num_rows=args.num_rows,
        )
    else:
        ds = JaxShufflingDataset(
            filenames,
            num_epochs=args.epochs,
            num_trainers=1,
            batch_size=args.batch_size,
            rank=0,
            feature_columns=feature_columns,
            label_column=LABEL_COLUMN,
            num_reducers=args.num_reducers,
            max_concurrent_epochs=args.max_concurrent_epochs,
            seed=args.seed,
            mesh=mesh,
            start_epoch=start_epoch,
        )

    # Train loop with per-batch wait-time measurement (reference ``_train``,
    # ray_torch_shuffle.py:195-231).
    all_wait_times = []
    loss = float("nan")
    for epoch in range(start_epoch, args.epochs):
        skip = resume_skip if epoch == start_epoch else 0
        ds.set_epoch(epoch, skip_batches=skip)
        epoch_start = time.perf_counter()
        wait_times = []
        num_batches = skip
        last_done = time.perf_counter()
        for features, labels in ds:
            wait_times.append(time.perf_counter() - last_done)
            if args.mock_train_step_time is not None:
                time.sleep(args.mock_train_step_time)
            else:
                state, metrics = train_step(state, features, labels)
                jax.block_until_ready(state.step)
                loss = float(metrics["loss"])
            num_batches += 1
            global_step += 1
            if ckpt_mgr is not None and global_step % args.checkpoint_every == 0:
                from ray_shuffling_data_loader_tpu import BatchCursor

                ckpt_mgr.save(
                    global_step,
                    cursor=BatchCursor(
                        epoch=epoch,
                        batches_yielded=num_batches,
                        config=stream_config,
                    ),
                    state=state,
                )
            last_done = time.perf_counter()
        epoch_s = time.perf_counter() - epoch_start
        all_wait_times.extend(wait_times)
        if not wait_times:
            print(
                f"epoch {epoch}: 0 batches — batch_size ({args.batch_size}) "
                f"exceeds the rows available per trainer and drop_last "
                f"discarded the partial tail"
            )
            continue
        wt = np.asarray(wait_times)
        print(
            f"epoch {epoch}: {num_batches} batches in {epoch_s:.2f}s, "
            f"loss={loss:.4f}, batch wait mean={wt.mean():.4f}s "
            f"std={wt.std():.4f} max={wt.max():.4f} min={wt.min():.4f}"
        )

    if not all_wait_times:
        print("no batches were delivered; nothing to summarize")
        return 1
    wt = np.asarray(all_wait_times)
    staging = ds.stats.as_dict()
    print(
        f"total: {len(all_wait_times)} batches; batch wait "
        f"mean={wt.mean():.4f}s std={wt.std():.4f} max={wt.max():.4f} "
        f"min={wt.min():.4f}"
    )
    print(
        f"staging: {staging['bytes_staged'] / 1e9:.3f} GB to HBM, "
        f"stall {staging['stall_s']:.3f}s over {staging['stalls']} stalls"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
