"""Pod training recipe: DLRM over pod-global batches under ``jax.distributed``.

The missing piece the round-1 review called out: an *example-side* recipe
for running the trainer across TPU-VM hosts (the reference's analog is the
Horovod-over-Ray driver, ``/root/reference/examples/horovod/
ray_torch_shuffle.py:319-344``, which `RayExecutor` fans out one process
per GPU). On a TPU pod the topology is one process per host:

1. every host runs THIS script (gcloud ``--worker=all``, see
   ``benchmarks/launch_tpu_pod.sh``);
2. ``jax.distributed.initialize()`` discovers the pod (no args needed on
   Cloud TPU) and gives each process its ``process_index``;
3. process 0 starts the shuffle runtime cluster (head) and kicks off the
   shuffle; other hosts join over DCN via the published address file on
   the shared filesystem (or ``--cluster-address``);
4. each host consumes its rank's shard through ``JaxShufflingDataset``,
   which assembles **pod-global arrays** via
   ``jax.make_array_from_process_local_data`` over a global ``('data',)``
   mesh — the jitted train step then runs SPMD across the whole pod, with
   gradient ``psum`` riding the ICI (no NCCL, no parameter server).

Single-host smoke (2 simulated processes, CPU):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/train_dlrm_pod.py --simulate-pod 2

Real pod (v5e-16, 4 hosts): see benchmarks/launch_tpu_pod.sh, which runs
this script on every worker with a shared --rendezvous-dir.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-rows", type=int, default=200_000)
    p.add_argument("--num-files", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=8_192)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--num-reducers", type=int, default=8)
    p.add_argument("--embed-dim", type=int, default=16)
    p.add_argument("--vocab-cap", type=int, default=1000)
    p.add_argument("--seed", type=int, default=29)
    p.add_argument(
        "--rendezvous-dir",
        type=str,
        default="pod_rendezvous",
        help="Shared dir (NFS/GCS-fuse on a real pod) for the runtime "
        "cluster address + data paths.",
    )
    p.add_argument(
        "--coordinator",
        type=str,
        default=None,
        help="host:port for jax.distributed on non-Cloud-TPU setups "
        "(Cloud TPU pods auto-discover with no args).",
    )
    p.add_argument(
        "--num-processes",
        type=int,
        default=None,
        help="With --coordinator: total process count.",
    )
    p.add_argument(
        "--process-id", type=int, default=None, help=argparse.SUPPRESS
    )
    p.add_argument(
        "--platform",
        default=None,
        help="Pin the JAX platform via the config API (e.g. 'cpu'; "
        "JAX_PLATFORMS alone is overridden by experimental TPU plugins).",
    )
    p.add_argument(
        "--simulate-pod",
        type=int,
        default=None,
        metavar="N",
        help="Launch N local processes with a local coordinator (CPU "
        "smoke of the full pod flow).",
    )
    p.add_argument(
        "--loader",
        choices=("mapreduce", "resident"),
        default="mapreduce",
        help="'resident' stages each host's addressable row range into "
        "device memory once and shuffles every epoch on device (needs "
        "the packed dataset to fit the pod's HBM; see resident.py).",
    )
    return p.parse_args(argv)


def train_main(args) -> int:
    import jax

    if args.platform:
        # The config API, not JAX_PLATFORMS: experimental TPU plugins
        # override the env var and would still try (and possibly hang on)
        # accelerator bring-up in a CPU smoke run.
        jax.config.update("jax_platforms", args.platform)

    # 1. Pod discovery. On Cloud TPU, initialize() needs no arguments.
    if args.coordinator:
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    else:
        jax.distributed.initialize()
    rank = jax.process_index()
    world = jax.process_count()

    import numpy as np
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.data_generation import (
        DATA_SPEC,
        LABEL_COLUMN,
        cached_generate_data,
    )
    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
    from ray_shuffling_data_loader_tpu.models import dlrm_for_data_spec
    from ray_shuffling_data_loader_tpu.parallel import (
        init_state,
        make_train_step,
    )

    rdv = args.rendezvous_dir
    os.makedirs(rdv, exist_ok=True)
    # A persistent rendezvous dir may hold a PREVIOUS run's address file;
    # ranks that matched on a bare filename could join a dead head. Scope
    # the filename to THIS run with a nonce agreed over jax.distributed
    # (broadcast from process 0) — stale files can never match it.
    if world > 1:
        from jax.experimental import multihost_utils

        nonce = int(
            multihost_utils.broadcast_one_to_all(
                jnp.asarray(np.random.randint(0, 2**31), jnp.int32)
            )
        )
        addr_file = os.path.join(rdv, f"cluster_address_{nonce}")
    else:
        addr_file = os.path.join(rdv, "cluster_address")

    # 2. Shuffle-runtime topology mirrors the pod: host 0 is the cluster
    #    head, everyone else joins over DCN.
    if rank == 0:
        ctx = (
            runtime.init_cluster(num_workers=4)
            if world > 1
            else runtime.init(num_workers=4)
        )
        filenames, num_bytes = cached_generate_data(
            args.num_rows,
            args.num_files,
            2,
            os.path.join(rdv, "data"),
            seed=args.seed,
        )
        if world > 1:
            with open(addr_file + ".tmp", "w") as f:
                f.write(ctx.cluster.address)
            os.rename(addr_file + ".tmp", addr_file)
        print(
            f"[pod] rank 0: cluster up, {num_bytes/1e9:.2f} GB over "
            f"{len(filenames)} files",
            flush=True,
        )
    else:
        deadline = time.time() + 300
        while not os.path.exists(addr_file):
            if time.time() > deadline:
                raise TimeoutError("rank 0 never published the cluster address")
            time.sleep(0.5)
        with open(addr_file) as f:
            runtime.init(address=f.read().strip(), num_workers=4)
        filenames = sorted(
            os.path.join(rdv, "data", name)
            for name in os.listdir(os.path.join(rdv, "data"))
            if name.endswith(".snappy")
        )

    # Canonical file order on EVERY rank: rank 0 holds the generator's
    # numeric-order list, other ranks listdir'd lexicographically — the
    # resident loader maps row offsets from this order, so divergence
    # would silently corrupt the global buffer (mapreduce is order-
    # insensitive, but one canonical order costs nothing).
    filenames = sorted(filenames)

    # 3. Pod-global mesh over EVERY device in the pod; batches assemble as
    #    global arrays, so the train step is one SPMD program.
    mesh = Mesh(np.array(jax.devices()), ("data",))
    feature_columns = [c for c in DATA_SPEC if c != LABEL_COLUMN]
    model = dlrm_for_data_spec(
        embed_dim=args.embed_dim, vocab_cap=args.vocab_cap
    )
    optimizer = optax.adam(1e-3)
    example = {
        c: jnp.zeros((args.batch_size,), jnp.int32) for c in feature_columns
    }
    state, shardings = init_state(model, optimizer, mesh, example)
    step_fn = make_train_step(model, optimizer, mesh, shardings)

    if args.loader == "resident":
        from ray_shuffling_data_loader_tpu.resident import (
            DeviceResidentShufflingDataset,
        )

        # Every process stages its addressable row range; the buffer
        # spans the pod and epoch shuffles are SPMD device gathers.
        ds = DeviceResidentShufflingDataset(
            filenames,
            num_epochs=args.epochs,
            batch_size=args.batch_size,
            feature_columns=feature_columns,
            label_column=LABEL_COLUMN,
            seed=args.seed,
            mesh=mesh,
        )
    else:
        ds = JaxShufflingDataset(
            filenames,
            num_epochs=args.epochs,
            num_trainers=world,
            batch_size=args.batch_size,
            rank=rank,
            feature_columns=feature_columns,
            label_column=LABEL_COLUMN,
            num_reducers=args.num_reducers,
            seed=args.seed,
            mesh=mesh,
            queue_name="pod-queue",
        )

    # 4. Train. Every process steps in lockstep on its shard of the global
    #    batch; collectives ride ICI. Ranks can receive different batch
    #    counts (reducer outputs split by rank), and the jitted step is
    #    collective — so each step is gated on an all-ranks-have-a-batch
    #    sync. Batches STREAM through the prefetch ring (materializing a
    #    whole epoch of device-resident batches would blow the HBM budget
    #    on a real pod workload and serialize all H2D staging).
    from jax.experimental import multihost_utils

    def _all_have_next(batch) -> bool:
        flags = multihost_utils.process_allgather(
            jnp.asarray([0 if batch is None else 1], jnp.int32)
        ).reshape(-1)
        return int(flags.min()) == 1

    steps_done = 0
    loss = float("nan")
    for epoch in range(args.epochs):
        ds.set_epoch(epoch)
        it = iter(ds)
        steps = 0
        batch = next(it, None)
        while _all_have_next(batch):
            features, label = batch
            state, metrics = step_fn(state, features, label)
            steps += 1
            steps_done += 1
            batch = next(it, None)
        if steps:
            loss = float(metrics["loss"])
        # Drain any leftover (dropped) batches so their task_done acks
        # release the epoch window for the next epoch.
        while batch is not None:
            batch = next(it, None)
        print(
            f"[pod] rank {rank}: epoch {epoch} done, "
            f"{steps} steps, loss {loss:.4f}",
            flush=True,
        )
    multihost_utils.sync_global_devices("train-done")
    stats = ds.stats.as_dict()
    print(
        f"[pod] rank {rank}: {steps_done} steps total, "
        f"{stats['bytes_staged']/1e9:.3f} GB staged, "
        f"stall {stats['stall_s']:.2f}s",
        flush=True,
    )
    runtime.shutdown()
    return 0


def simulate_pod(args) -> int:
    """Run the full pod flow as N local processes (CPU smoke)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    procs = []
    for pid in range(args.simulate_pod):
        cmd = [
            sys.executable,
            os.path.abspath(__file__),
            "--coordinator",
            f"127.0.0.1:{port}",
            "--num-processes",
            str(args.simulate_pod),
            "--process-id",
            str(pid),
            "--rendezvous-dir",
            args.rendezvous_dir,
            "--num-rows",
            str(args.num_rows),
            "--batch-size",
            str(args.batch_size),
            "--epochs",
            str(args.epochs),
            "--platform",
            args.platform or "cpu",
            "--loader",
            args.loader,
        ]
        env = dict(os.environ, RSDL_ADVERTISE_HOST="127.0.0.1")
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


if __name__ == "__main__":
    _args = parse_args()
    if _args.simulate_pod:
        sys.exit(simulate_pod(_args))
    sys.exit(train_main(_args))
