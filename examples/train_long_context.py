"""Long-context training recipe: causal LM with sequence parallelism.

End-to-end demonstration of the long-context path (no reference analog —
the reference repo is a tabular loader with a mocked train step): a
causal transformer trains over sequences sharded across a mesh axis, so
activation memory per device scales with ``seq / sp`` instead of
``seq``. The mesh is 2-D ``(data, sp)``: batch over ``data``, sequence
over ``sp``; gradients reduce over both axes automatically under the
sharding-annotated ``jit``.

Runs anywhere — CPU smoke with 8 virtual devices:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_long_context.py --dp 2 --sp 4

On a TPU slice, drop the env vars and size ``--dp/--sp`` to the chips;
``--attention ulysses`` switches the sequence schedule (heads must be a
multiple of ``sp``).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dp", type=int, default=2, help="data-axis size")
    p.add_argument("--sp", type=int, default=4, help="sequence-axis size")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--embed-dim", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument(
        "--attention", choices=("ring", "ulysses", "dense"), default="ring"
    )
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.steps < 2:
        p.error("--steps must be >= 2 (the run asserts the loss falls)")
    return args


def main(argv=None) -> int:
    args = parse_args(argv)

    import jax

    from ray_shuffling_data_loader_tpu.utils import force_platform_from_env

    force_platform_from_env()

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_shuffling_data_loader_tpu.models import (
        CausalLM,
        next_token_loss,
        synthetic_tokens,
    )
    import functools

    from ray_shuffling_data_loader_tpu.ops import (
        attention_reference,
        make_ring_attention,
        make_ulysses_attention,
    )

    devices = jax.devices()
    need = args.dp * args.sp
    if len(devices) < need:
        raise SystemExit(
            f"need {need} devices for dp={args.dp} x sp={args.sp}, "
            f"have {len(devices)}"
        )
    mesh = Mesh(
        np.array(devices[:need]).reshape(args.dp, args.sp), ("data", "sp")
    )
    print(f"mesh: {dict(mesh.shape)}, seq {args.seq_len} -> "
          f"{args.seq_len // args.sp} per device", flush=True)

    if args.attention == "ring":
        attention_fn = make_ring_attention(
            mesh, "sp", causal=True, batch_axis="data"
        )
    elif args.attention == "ulysses":
        attention_fn = make_ulysses_attention(
            mesh, "sp", causal=True, batch_axis="data"
        )
    else:
        # Explicitly the XLA dense reference — the numerics baseline for
        # the two sequence schedules. (attention_fn=None would mean the
        # model's default, i.e. the flash auto-policy, not dense.)
        attention_fn = functools.partial(attention_reference, causal=True)

    model = CausalLM(
        vocab_size=args.vocab,
        max_seq_len=args.seq_len,
        embed_dim=args.embed_dim,
        num_layers=args.layers,
        num_heads=args.heads,
        attention_fn=attention_fn,
    )
    tokens_host = synthetic_tokens(
        args.batch, args.seq_len, args.vocab, seed=args.seed
    )
    token_sharding = NamedSharding(mesh, P("data", "sp"))
    tokens = jax.device_put(jnp.asarray(tokens_host), token_sharding)

    params = model.init(jax.random.key(args.seed), tokens)
    optimizer = optax.adam(args.lr)
    opt_state = optimizer.init(params)
    replicated = NamedSharding(mesh, P())

    @jax.jit
    def step(params, opt_state, tokens):
        def loss_fn(params):
            return next_token_loss(model.apply(params, tokens), tokens)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params = jax.device_put(params, replicated)
    opt_state = jax.device_put(opt_state, replicated)

    first = last = None
    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens)
        last = float(loss)
        if first is None:
            first = last
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {last:.4f}", flush=True)
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    print(
        f"{args.steps} steps in {dt:.1f}s ({args.attention} attention); "
        f"loss {first:.4f} -> {last:.4f}",
        flush=True,
    )
    if not last < first:
        print("warning: loss did not decrease", flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
