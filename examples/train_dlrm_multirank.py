"""Multi-process data-parallel trainers on one host (late-joiner path).

The reference's Horovod example runs one Torch process per GPU, with rank 0
creating the named queue and workers connecting by name with retry
(``ray_torch_shuffle.py:143-163``, ``dataset.py:75-84``). This example runs
the same topology on this runtime: N trainer processes, each consuming its
disjoint shard of every epoch's shuffled batches through the shared queue.

On a TPU pod the analog is one process per TPU-VM host under
``jax.distributed`` with ``JaxShufflingDataset`` assembling pod-global
arrays; here ranks consume host batches so the example runs anywhere:

    python examples/train_dlrm_multirank.py --num-trainers 3
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-trainers", type=int, default=2)
    p.add_argument("--num-rows", type=int, default=200_000)
    p.add_argument("--num-files", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=10_000)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--num-reducers", type=int, default=8)
    p.add_argument("--seed", type=int, default=17)
    p.add_argument("--data-dir", type=str, default="example_data_multirank")
    # internal: set for spawned rank processes
    p.add_argument("--rank", type=int, default=None, help=argparse.SUPPRESS)
    return p.parse_args(argv)


def run_rank(args) -> int:
    """One trainer rank: rank 0 owns the queue + shuffle; others join the
    session (``$RSDL_RUNTIME_DIR``) and connect by queue name with retry."""
    from ray_shuffling_data_loader_tpu import ShufflingDataset, runtime
    from ray_shuffling_data_loader_tpu.data_generation import (
        cached_generate_data,
    )

    runtime.init()
    # Same spec as the driver -> cache hit, same filename list.
    filenames, _ = cached_generate_data(
        args.num_rows, args.num_files, 2, args.data_dir, seed=args.seed
    )
    ds = ShufflingDataset(
        filenames,
        num_epochs=args.epochs,
        num_trainers=args.num_trainers,
        batch_size=args.batch_size,
        rank=args.rank,
        num_reducers=args.num_reducers,
        seed=args.seed,
    )
    total_rows = 0
    for epoch in range(args.epochs):
        ds.set_epoch(epoch)
        t0 = time.perf_counter()
        rows = sum(b.num_rows for b in ds)
        total_rows += rows
        print(
            f"[rank {args.rank}] epoch {epoch}: {rows} rows in "
            f"{time.perf_counter() - t0:.2f}s",
            flush=True,
        )
    print(f"[rank {args.rank}] total {total_rows} rows", flush=True)
    if args.rank == 0:
        runtime.shutdown()
    return 0


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.rank is not None:
        return run_rank(args)

    # Driver: generate data, create the session, then launch one process
    # per rank. Rank 0 must start first (it owns the queue); later ranks
    # join via the exported runtime dir — the late-joiner retry handles
    # any startup skew.
    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.data_generation import (
        cached_generate_data,
    )

    ctx = runtime.init()
    os.makedirs(args.data_dir, exist_ok=True)
    cached_generate_data(
        args.num_rows, args.num_files, 2, args.data_dir, seed=args.seed
    )

    env = dict(os.environ, RSDL_RUNTIME_DIR=ctx.runtime_dir)
    procs = []
    for rank in range(args.num_trainers):
        procs.append(
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)]
                + [a for a in sys.argv[1:]]
                + ["--rank", str(rank)],
                env=env,
            )
        )
        if rank == 0:
            time.sleep(0.5)  # queue actor up before late joiners connect
    codes = [p.wait() for p in procs]
    per_rank_expected = args.num_rows * args.epochs
    print(
        f"all ranks done, exit codes {codes}; "
        f"{per_rank_expected} rows/epoch split across "
        f"{args.num_trainers} ranks per epoch"
    )
    return max(codes)


if __name__ == "__main__":
    sys.exit(main())
