"""Elastic control plane: autoscaler + graceful drain + tiered evictor.

ROADMAP item 5, closing the loop PR 9 opened: the decision plane can
*say* where the bottleneck is (``/critical`` sole-active shares), who
is wedged (straggler attribution), and whose bytes are resident where
(the capacity ledger) — this module is the driver-side control loop
that *acts* on those verdicts, with three actuators:

* **Autoscaler** (:meth:`ElasticController.autoscale_once`): when the
  live critical-path verdict lands on a shuffle stage with a dominant
  sole-active share (or a worker is wedged), add capacity — more
  :class:`~.tasks.WorkerPool` workers single-host, a fresh
  :class:`~.cluster.HostAgent` admitted via
  ``ClusterScheduler.add_agent`` in cluster mode. When the shuffle
  stages fall off the critical path, shed what this controller added,
  through the graceful-drain path, never a kill.
* **Graceful drain** (:meth:`ElasticController.drain_host`): the
  *planned*-migration half of the robustness story. ``retire_agent``
  marks the agent draining (dispatch stops placing new tasks there),
  the controller waits out its in-flight tasks under a bounded
  deadline (``RSDL_DRAIN_DEADLINE_S``), re-homes the host's live store
  segments to the session owner (recorded as capacity-ledger
  ``transition`` ops), then completes the retirement with
  ``remove_agent`` + registry ``unregister_host`` (which sweeps the
  host's actor names). Anything the deadline cuts off — including the
  agent crashing mid-drain — degrades into the fault plane's
  ``_drop_agent``/lineage re-execution machinery (PR 3): a drain ends
  in either a clean handover or the already-chaos-proven failover,
  never a hang.
* **Tiered evictor** (:meth:`ElasticController.evict_once`): under
  ``RSDL_STORE_CAPACITY_BYTES`` pressure (watermarked on the ledger's
  ``shm_used_frac``), demote cold epochs' segments shm→spill
  (``ObjectStore.demote`` — readable in place, ledger ``transition``)
  and drop spill segments past the age rung (``drop_segments`` —
  readers re-materialize from lineage on the next touch). Eviction of
  an epoch still inside the in-flight window is forbidden by
  construction: candidates are fenced on
  ``shuffle.protected_epochs()`` and unknown-epoch segments are never
  touched.

Lifecycle: ``runtime.init()``'s session-owner bring-up calls
:func:`maybe_start` iff ``RSDL_ELASTIC`` is ``auto``/``on`` (and
metrics are on — the loop is blind without its input planes); the loop
ticks at the sampler cadence (``RSDL_ELASTIC_PERIOD_S``, default the
timeseries period). Zero overhead when off: ``RSDL_ELASTIC`` unset
means this module is never imported, no thread runs, and no
``transition`` ledger record is ever produced (fresh-interpreter
tested).

Surfacing: structured ``scale.*`` / ``evict.*`` events on ``/events``,
``elastic.*`` counters/gauges (``rsdl_elastic_*`` on a scrape — the
``headroom_low`` / ``drain_stuck`` default SLO rules key on
``elastic.shm_headroom_frac`` / ``elastic.drain_age_seconds``), the
``cluster`` membership section on ``/status``, and
``scale_events`` / ``evicted_gb`` / ``drains`` embedded by
``bench.py`` into its result JSON next to ``telemetry_final``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_shuffling_data_loader_tpu import telemetry
from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics

ENV_ELASTIC = "RSDL_ELASTIC"
ENV_PERIOD_S = "RSDL_ELASTIC_PERIOD_S"
ENV_MIN_WORKERS = "RSDL_ELASTIC_MIN_WORKERS"
ENV_MAX_WORKERS = "RSDL_ELASTIC_MAX_WORKERS"
ENV_UP_THRESHOLD = "RSDL_ELASTIC_UP_THRESHOLD"
ENV_DOWN_THRESHOLD = "RSDL_ELASTIC_DOWN_THRESHOLD"
ENV_COOLDOWN_S = "RSDL_ELASTIC_COOLDOWN_S"
ENV_DRAIN_DEADLINE_S = "RSDL_DRAIN_DEADLINE_S"
ENV_EVICT_HIGH = "RSDL_EVICT_HIGH_WATERMARK"
ENV_EVICT_LOW = "RSDL_EVICT_LOW_WATERMARK"
ENV_EVICT_COOLDOWN_S = "RSDL_EVICT_COOLDOWN_S"
ENV_EVICT_DROP_AGE_S = "RSDL_EVICT_DROP_AGE_S"

# The live-verdict stage names that mean "the shuffle plane is the
# bottleneck" (critical.STAGE_ORDER vocabulary minus the consumer side).
SHUFFLE_STAGES = (
    "map", "plan", "reduce", "gather-reduce", "selective-reduce"
)

_UNKNOWN_EPOCH = "-"


def mode() -> str:
    return os.environ.get(ENV_ELASTIC, "").strip().lower()


def enabled() -> bool:
    """Is the elastic plane requested? (``auto``/``on``/``1``; default
    off — the caller gates the *import* on this same env var, so the
    off path never even loads this module.)"""
    return mode() not in ("", "off", "0", "false")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class ElasticController:
    """One driver-side controller instance: policy knobs + the three
    actuators. Constructed by :func:`start` (the env-gated loop) or
    directly by tests/operators for forced ticks."""

    def __init__(self, ctx=None):
        if ctx is None:
            from ray_shuffling_data_loader_tpu import runtime

            ctx = runtime.get_context()
        self._ctx = ctx
        self.min_workers = max(1, int(_env_float(ENV_MIN_WORKERS, 1)))
        self.max_workers = max(
            self.min_workers,
            int(_env_float(ENV_MAX_WORKERS, 2 * (os.cpu_count() or 1))),
        )
        self.up_threshold = _env_float(ENV_UP_THRESHOLD, 0.5)
        self.down_threshold = _env_float(ENV_DOWN_THRESHOLD, 0.1)
        self.cooldown_s = _env_float(ENV_COOLDOWN_S, 30.0)
        self.drain_deadline_s = _env_float(ENV_DRAIN_DEADLINE_S, 30.0)
        self.evict_high = _env_float(ENV_EVICT_HIGH, 0.85)
        self.evict_low = _env_float(ENV_EVICT_LOW, 0.6)
        self.evict_cooldown_s = _env_float(ENV_EVICT_COOLDOWN_S, 5.0)
        self.drop_age_s = _env_float(ENV_EVICT_DROP_AGE_S, 300.0)
        self._lock = threading.Lock()
        self._last_scale_ts = float("-inf")
        self._last_evict_ts = float("-inf")
        # Agents THIS controller added (cluster mode): the only ones
        # scale-down may drain — the bootstrap hosts belong to the
        # operator, not the policy.
        self._added_agents: List[Tuple[str, Any]] = []  # (host_id, handle)
        self._drain_started: Dict[Tuple, float] = {}  # address -> mono ts
        # Lifetime totals (bench embeds these next to telemetry_final).
        self.scale_events = 0
        self.evicted_bytes = 0
        self.drains = 0

    # -- shared signal reads -------------------------------------------------

    def _protected_epochs(self) -> set:
        """The in-flight eviction fence, via ``sys.modules`` so a
        controller on a non-shuffling process never imports the shuffle
        driver."""
        shuffle_mod = sys.modules.get(
            "ray_shuffling_data_loader_tpu.shuffle"
        )
        if shuffle_mod is None:
            return set()
        try:
            return {int(e) for e in shuffle_mod.protected_epochs()}
        except Exception:
            return set()

    def _trial_in_flight(self) -> bool:
        shuffle_mod = sys.modules.get(
            "ray_shuffling_data_loader_tpu.shuffle"
        )
        if shuffle_mod is None:
            return False
        try:
            return bool(shuffle_mod.live_status().get("running"))
        except Exception:
            return False

    def _shm_frac(self, view: Dict[str, Any]) -> Optional[float]:
        """Used fraction of the shm budget. Prefer this controller's
        OWN store budget over the view's (``capacity.view`` only knows
        the budget when a full runtime session is live — a controller
        driving a bare store must not read tmpfs-relative numbers)."""
        budget = getattr(self._ctx.store, "capacity_bytes", None)
        if budget:
            return self._shm_resident(view) / budget
        frac = view.get("shm_used_frac")
        return None if frac is None else float(frac)

    @staticmethod
    def _shm_resident(view: Dict[str, Any]) -> int:
        """Bytes physically occupying shm (shm + logical cache tier) —
        delegates to capacity's one definition so the evictor's
        watermark math and ``shm_used_frac`` can never drift."""
        from ray_shuffling_data_loader_tpu.telemetry import capacity

        return capacity.shm_resident_bytes(view.get("totals", {}))

    def _shm_budget(self, view: Dict[str, Any]) -> Optional[int]:
        budget = getattr(self._ctx.store, "capacity_bytes", None)
        if budget:
            return int(budget)
        budget = (view.get("host") or {}).get("capacity_bytes")
        return int(budget) if budget else None

    def publish_gauges(self, now: Optional[float] = None) -> None:
        """The gauges the SLO default rules key on, refreshed per tick:
        ``elastic.shm_headroom_frac`` (1 - used fraction of the shm
        budget; the ``headroom_low`` input), ``elastic.drain_age_seconds``
        (age of the oldest still-active drain, 0 when none; the
        ``drain_stuck`` input), ``elastic.workers``, and
        ``elastic.draining_agents``. Never raises."""
        if not _metrics.enabled():
            return
        now = time.monotonic() if now is None else now
        try:
            from ray_shuffling_data_loader_tpu.telemetry import capacity

            frac = self._shm_frac(capacity.view())
            if frac is not None:
                _metrics.registry.gauge("elastic.shm_headroom_frac").set(
                    max(0.0, 1.0 - float(frac))
                )
        except Exception:
            pass
        self._publish_drain_gauges(now)
        try:
            _metrics.registry.gauge("elastic.workers").set(
                float(self._sched_width())
            )
        except Exception:
            pass

    def _publish_drain_gauges(self, now: Optional[float] = None) -> None:
        """Just the drain-age/count gauges — cheap enough for the drain
        wait loop's poll cadence (the full :meth:`publish_gauges` folds
        the whole capacity ledger and belongs on the tick)."""
        if not _metrics.enabled():
            return
        now = time.monotonic() if now is None else now
        try:
            with self._lock:
                started = list(self._drain_started.values())
            age = max((now - t for t in started), default=0.0)
            _metrics.registry.gauge("elastic.drain_age_seconds").set(age)
            _metrics.registry.gauge("elastic.draining_agents").set(
                len(started)
            )
        except Exception:
            pass

    # -- autoscaler ----------------------------------------------------------

    def autoscale_once(self, now: Optional[float] = None) -> Optional[str]:
        """One policy decision from the live verdicts: returns ``"up"``,
        ``"down"``, or ``None``. Only acts mid-trial (between trials
        there is no critical path to read), under a cooldown so one
        slow epoch cannot thrash membership."""
        now = time.monotonic() if now is None else now
        if not self._trial_in_flight():
            return None
        with self._lock:
            if now - self._last_scale_ts < self.cooldown_s:
                return None
        try:
            from ray_shuffling_data_loader_tpu.telemetry import critical

            current = critical.analyze().get("current") or {}
        except Exception:
            return None
        stage = current.get("critical_path")
        shares = current.get("sole_share") or {}
        shuffle_share = sum(
            float(shares.get(s, 0.0)) for s in SHUFFLE_STAGES
        )
        wedged = 0
        try:
            from ray_shuffling_data_loader_tpu.telemetry import stragglers

            wedged = len(stragglers.analyze().get("wedged") or [])
        except Exception:
            pass
        if (
            stage in SHUFFLE_STAGES
            and float(shares.get(stage, 0.0)) >= self.up_threshold
        ) or wedged:
            if self._scale_up(
                reason="wedged-worker" if wedged else f"critical:{stage}",
                share=round(float(shares.get(stage, 0.0)), 4),
            ):
                with self._lock:
                    self._last_scale_ts = now
                return "up"
            return None
        if shuffle_share <= self.down_threshold and not wedged:
            if self._scale_down(share=round(shuffle_share, 4)):
                with self._lock:
                    self._last_scale_ts = now
                return "down"
        return None

    def _sched_width(self) -> int:
        """Current scheduler capacity WITHOUT side effects: on a
        RuntimeContext whose worker pool is still lazy, reading the
        ``scheduler`` property would spawn the pool just to count it —
        report the configured size instead."""
        ctx = self._ctx
        if (
            getattr(ctx, "cluster", None) is None
            and hasattr(ctx, "_pool")
            and ctx._pool is None
        ):
            return int(getattr(ctx, "_num_workers", 0) or 0)
        return int(getattr(ctx.scheduler, "width", 0) or 0)

    def _workers_now(self) -> int:
        return self._sched_width()

    def _scale_up(self, reason: str, **fields) -> bool:
        sched = self._ctx.scheduler
        if self._workers_now() >= self.max_workers:
            return False
        if hasattr(sched, "add_workers"):  # single-host WorkerPool
            before = sched.num_workers
            after = sched.add_workers(1)
            if after <= before:
                return False
            detail = {"workers": after}
        elif hasattr(sched, "add_agent"):  # ClusterScheduler
            detail = self._spawn_scale_agent()
            if detail is None:
                return False
        else:
            return False
        with self._lock:
            self.scale_events += 1
        _metrics.safe_inc("elastic.scale_events_total", direction="up")
        telemetry.emit_event(
            "scale.up", _flush=True, reason=reason, **detail, **fields
        )
        return True

    def _spawn_scale_agent(self) -> Optional[Dict[str, Any]]:
        """Cluster-mode scale-up: spawn a fresh HostAgent (one worker)
        on this host, register it as a synthetic cluster host (so
        scheduler rebuilds keep it), and admit it to the rotation."""
        from .actor import spawn_actor
        from .cluster import HostAgent

        ctx = self._ctx
        advertise = (
            getattr(ctx.cluster, "advertise_host", None)
            if ctx.cluster is not None
            else None
        )
        try:
            # host= makes the agent bind TCP on the advertise address
            # (the canonical start_host_services spawn does the same):
            # its address is published cluster-wide below, and a unix
            # socket would be unreachable from every other host.
            agent = spawn_actor(
                HostAgent,
                ctx.runtime_dir,
                1,
                advertise,
                runtime_dir=ctx.runtime_dir,
                host=advertise,
                daemon=False,
            )
        except Exception:
            return None
        host_id = f"elastic-{agent.pid}:{ctx.session}"
        cluster = ctx.cluster
        if cluster is not None and hasattr(cluster, "registry"):
            try:
                cluster.registry.call(
                    "register_host",
                    host_id,
                    list(agent.address),
                    list(cluster.store_address),
                    1,
                )
            except Exception:
                pass
        sched = ctx.scheduler
        if hasattr(sched, "add_agent"):
            sched.add_agent(agent, num_workers=1)
        with self._lock:
            self._added_agents.append((host_id, agent))
        return {"agent": str(agent.address), "host_id": host_id}

    def _scale_down(self, **fields) -> bool:
        sched = self._ctx.scheduler
        if hasattr(sched, "retire_workers"):  # single-host WorkerPool
            if sched.num_workers <= self.min_workers:
                return False
            retired = sched.retire_workers(1)
            with self._lock:
                self.scale_events += 1
            _metrics.safe_inc(
                "elastic.scale_events_total", direction="down"
            )
            telemetry.emit_event(
                "scale.down", _flush=True,
                workers=sched.num_workers, retired_pids=retired, **fields,
            )
            return True
        with self._lock:
            added = list(self._added_agents)
        if not added:
            return False  # never drain a bootstrap host on policy alone
        host_id, agent = added[-1]
        outcome = self.drain_host(agent, host_id=host_id)
        if outcome is None:
            return False
        with self._lock:
            self.scale_events += 1
            self._added_agents = [
                (h, a) for h, a in self._added_agents if h != host_id
            ]
        _metrics.safe_inc("elastic.scale_events_total", direction="down")
        telemetry.emit_event(
            "scale.down", _flush=True, agent=str(agent.address),
            host_id=host_id, outcome=outcome, **fields,
        )
        return True

    # -- graceful drain ------------------------------------------------------

    def drain_host(
        self,
        agent_or_address,
        host_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        store_handle=None,
    ) -> Optional[str]:
        """Planned migration of one host agent out of the cluster.

        Protocol: ``retire_agent`` (dispatch stops placing new tasks) →
        wait for its in-flight tasks under ``deadline_s`` (pinging the
        agent each poll — a crash mid-drain is detected, not waited
        out) → re-home its live store segments to this host (ledger
        ``transition`` ops) → ``remove_agent`` + registry
        ``unregister_host`` (sweeping its actor names). A blown
        deadline, a mid-drain crash, or a failed re-home falls back to
        ``_drop_agent``: the chaos-proven failover/lineage machinery
        owns whatever the planned path could not hand over.

        Returns ``"drained"`` (clean), ``"backstop"`` (degraded to
        failover), or ``None`` (not a cluster scheduler / unknown
        agent)."""
        sched = self._ctx.scheduler
        if not hasattr(sched, "retire_agent"):
            return None
        agent = sched.retire_agent(agent_or_address)
        if agent is None:
            return None
        deadline_s = (
            self.drain_deadline_s if deadline_s is None else deadline_s
        )
        address = tuple(agent.address)
        started = time.monotonic()
        with self._lock:
            self.drains += 1
            self._drain_started[address] = started
        _metrics.safe_inc("elastic.drains_total")
        telemetry.emit_event(
            "scale.drain", _flush=True, agent=str(agent.address),
            host_id=host_id, deadline_s=deadline_s,
        )
        alive = True
        try:
            deadline = started + max(0.0, deadline_s)
            while sched.in_flight_on(address) > 0:
                self._publish_drain_gauges()
                if time.monotonic() >= deadline:
                    break
                if not agent.ping(timeout=2.0):
                    # Crash mid-drain: no point waiting out the window.
                    alive = False
                    break
                time.sleep(0.05)
            drained = alive and sched.in_flight_on(address) == 0
            if drained:
                try:
                    self._rehome_segments(agent, store_handle=store_handle)
                except Exception:
                    drained = False
            if drained:
                sched.remove_agent(address)
                self._unregister_host(host_id, address)
                telemetry.emit_event(
                    "scale.drain_done", _flush=True,
                    agent=str(agent.address), host_id=host_id,
                    waited_s=round(time.monotonic() - started, 3),
                )
                return "drained"
            # Backstop: the fault plane's failover path. _drop_agent
            # fires the agent.evicted event + on_agent_dead membership
            # eviction; in-flight tasks fail over and lost segments
            # re-materialize from lineage — precisely the chaos-proven
            # degradation a drain must collapse into, never a hang.
            _metrics.safe_inc("elastic.drain_backstops_total")
            telemetry.emit_event(
                "scale.drain_backstop", _flush=True,
                agent=str(agent.address), host_id=host_id,
                agent_alive=alive,
                in_flight=sched.in_flight_on(address),
            )
            sched._drop_agent(agent)
            self._unregister_host(host_id, address)
            return "backstop"
        finally:
            with self._lock:
                self._drain_started.pop(address, None)
            self.publish_gauges()

    def _unregister_host(self, host_id: Optional[str], address) -> None:
        cluster = getattr(self._ctx, "cluster", None)
        if cluster is None or not hasattr(cluster, "registry"):
            return
        try:
            hosts = cluster.registry.call("hosts")
        except Exception:
            return
        for hid, info in hosts.items():
            if hid == host_id or tuple(info.get("agent") or ()) == tuple(
                address
            ):
                try:
                    # unregister_host also sweeps the host's actor-name
                    # records, so post-drain lookups fail fast.
                    cluster.registry.call_oneway("unregister_host", hid)
                except Exception:
                    pass

    def _rehome_segments(self, agent, store_handle=None) -> int:
        """Adopt the draining host's live segments into this host's
        store (same object ids — local readers resolve them without a
        fetch; remote readers that still dial the dead owner degrade to
        lineage re-execution, the backstop). Segments already visible
        here (shared-filesystem same-machine hosts) move nothing but
        still count as accounted-for. Each adopted segment lands a
        ledger ``transition`` op (same tier — a host move, not a tier
        move, so per-tier residency stays exact)."""
        store = self._ctx.store
        cluster = getattr(self._ctx, "cluster", None)
        if store_handle is None and cluster is not None:
            try:
                hosts = cluster.registry.call("hosts")
                for info in hosts.values():
                    if tuple(info.get("agent") or ()) == tuple(
                        agent.address
                    ):
                        store_handle = cluster._peer_store(
                            tuple(info["store"])
                        )
                        break
            except Exception:
                store_handle = None
        if store_handle is None:
            return 0
        prefix = f"{store.session}-"
        moved = 0
        try:
            segments = store_handle.call("list_segments", prefix)
        except Exception:
            return 0
        for object_id, nbytes in segments:
            if store._find_segment(object_id) is not None:
                continue
            data = store_handle.call("fetch", object_id)
            path = os.path.join(
                store._placement_dir(len(data)), object_id
            )
            tmp = f"{path}.rehome-{os.getpid()}.tmp"
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                os.rename(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except FileNotFoundError:
                    pass
                raise
            moved += len(data)
            self._ledger_transition(object_id, len(data), store.tier_of(path))
        if moved:
            telemetry.emit_event(
                "scale.rehomed", nbytes=moved, agent=str(agent.address)
            )
        return moved

    @staticmethod
    def _ledger_transition(object_id: str, nbytes: int, tier: str) -> None:
        if not _metrics.enabled():
            return
        try:
            from ray_shuffling_data_loader_tpu.telemetry import capacity

            capacity.note("transition", object_id, nbytes=nbytes, tier=tier)
        except Exception:
            pass

    # -- tiered evictor ------------------------------------------------------

    @staticmethod
    def _last_touch(seg: Dict[str, Any]) -> float:
        return float(seg.get("last_touch") or seg["ts"])

    def _candidates(self, tier: str) -> List[Dict[str, Any]]:
        """Live ledger segments on ``tier`` eligible for eviction:
        epoch known (unknown-epoch segments are never touched — we
        cannot prove them cold) and outside the in-flight window.

        Ordering is by LAST ACCESS, not creation age (ISSUE 11): the
        coldest epoch — the one whose segments were read least recently
        per the ledger's ``touch`` ops — evicts first, then segments
        within it least-recently-touched first. An old epoch a resumed
        reader is actively re-reading stays warm; under the old
        creation-age order it was always the first casualty."""
        from ray_shuffling_data_loader_tpu.telemetry import capacity

        protected = self._protected_epochs()
        claimed: set = set()
        if tier == "cache" and os.environ.get("RSDL_SERVICE"):
            # Service plane (ISSUE 15): shared decode-cache segments a
            # LIVE job claims are in active cross-job use — dropping
            # one would silently un-share a hot dataset mid-run. The
            # claim set is refcounted per job and released at job end,
            # so unclaimed segments stay ordinary candidates.
            try:
                from ray_shuffling_data_loader_tpu.runtime.service import (
                    claimed_cache_ids,
                )

                claimed = claimed_cache_ids()
            except Exception:
                claimed = set()
        live = capacity.live_segments()
        # Epoch warmth across ALL tiers: a spill read keeps the epoch's
        # shm segments warm too — the epoch is demonstrably in use.
        epoch_touch: Dict[str, float] = {}
        for seg in live:
            key = seg["epoch"]
            epoch_touch[key] = max(
                epoch_touch.get(key, 0.0), self._last_touch(seg)
            )
        out = []
        for seg in live:
            if seg["tier"] != tier or seg["epoch"] == _UNKNOWN_EPOCH:
                continue
            try:
                epoch = int(seg["epoch"])
            except (TypeError, ValueError):
                continue
            if epoch in protected:
                continue
            if claimed and (
                seg["id"] in claimed
                or any(i in claimed for i in (seg["ids"] or []))
            ):
                continue
            out.append(seg)
        out.sort(
            key=lambda s: (
                epoch_touch.get(s["epoch"], 0.0),
                int(s["epoch"]),
                self._last_touch(s),
                s["ts"],
            )
        )
        return out

    def evict_once(
        self,
        now: Optional[float] = None,
        force: bool = False,
        force_drop: bool = False,
    ) -> Dict[str, int]:
        """One eviction pass. Under shm pressure (used fraction >= the
        high watermark; or ``force``) demote cold epochs' segments
        oldest-first until residency projects under the low watermark,
        then drop spill segments older than the drop-age rung
        (``force_drop`` ignores the age — the operator's/test's
        explicit last rung). Returns the pass's stats (also accumulated
        for bench)."""
        now = time.time() if now is None else float(now)
        stats = {
            "demoted": 0, "demoted_bytes": 0,
            "dropped": 0, "dropped_bytes": 0,
        }
        if not _metrics.enabled():
            return stats
        from ray_shuffling_data_loader_tpu.telemetry import capacity

        view = capacity.view(now=now)
        frac = self._shm_frac(view)
        pressured = frac is not None and float(frac) >= self.evict_high
        mono = time.monotonic()
        with self._lock:
            cooled = mono - self._last_evict_ts >= self.evict_cooldown_s
        if not (force or force_drop) and not (pressured and cooled):
            self.publish_gauges()
            return stats
        with self._lock:
            self._last_evict_ts = mono
        store = self._ctx.store
        budget = self._shm_budget(view)
        resident = self._shm_resident(view)
        target = self.evict_low * budget if budget else None
        demoted_epochs: set = set()
        dropped_epochs: set = set()
        if force or pressured:
            # First rung: shed shared decode-cache segments (logical
            # "cache" tier, ISSUE 11), coldest-last-touch first. They
            # are the cheapest bytes to lose — lineage re-materializes
            # them from Parquet on the next claim (the chaos-proven
            # _recover_lost_cache path), no epoch state is at risk.
            for seg in self._candidates("cache"):
                if (
                    not force
                    and target is not None
                    and resident <= target
                ):
                    break
                freed = store.drop_segments(seg["ids"] or [seg["id"]])
                if freed:
                    stats["dropped"] += 1
                    stats["dropped_bytes"] += freed
                    resident -= freed
                    dropped_epochs.add(seg["epoch"])
            for seg in self._candidates("shm"):
                if (
                    not force
                    and target is not None
                    and resident <= target
                ):
                    break
                moved = store.demote(seg["ids"] or [seg["id"]])
                if moved:
                    stats["demoted"] += 1
                    stats["demoted_bytes"] += moved
                    resident -= moved
                    demoted_epochs.add(seg["epoch"])
        for seg in self._candidates("spill"):
            # The age rung keys on last ACCESS, not creation: a spill
            # segment a reader touched recently is demonstrably needed.
            if (
                not force_drop
                and now - self._last_touch(seg) < self.drop_age_s
            ):
                continue
            freed = store.drop_segments(seg["ids"] or [seg["id"]])
            if freed:
                stats["dropped"] += 1
                stats["dropped_bytes"] += freed
                dropped_epochs.add(seg["epoch"])
        with self._lock:
            self.evicted_bytes += (
                stats["demoted_bytes"] + stats["dropped_bytes"]
            )
        if stats["demoted"]:
            _metrics.safe_inc(
                "elastic.evicted_bytes_total",
                float(stats["demoted_bytes"]), action="demote",
            )
            telemetry.emit_event(
                "evict.demote", _flush=True,
                segments=stats["demoted"],
                nbytes=stats["demoted_bytes"],
                epochs=sorted(demoted_epochs),
            )
        if stats["dropped"]:
            _metrics.safe_inc(
                "elastic.evicted_bytes_total",
                float(stats["dropped_bytes"]), action="drop",
            )
            telemetry.emit_event(
                "evict.drop", _flush=True,
                segments=stats["dropped"],
                nbytes=stats["dropped_bytes"],
                epochs=sorted(dropped_epochs),
            )
        self.publish_gauges()
        return stats

    # -- the loop ------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> None:
        """One control-loop iteration: refresh gauges, run the
        autoscaler policy, run the evictor pass. Never raises."""
        try:
            self.publish_gauges()
        except Exception:
            pass
        try:
            self.autoscale_once()
        except Exception:
            pass
        try:
            self.evict_once(now=now)
        except Exception:
            pass

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "scale_events": self.scale_events,
                "evicted_gb": round(self.evicted_bytes / 2**30, 6),
                "drains": self.drains,
            }


# ---------------------------------------------------------------------------
# Module lifecycle (the env-gated loop runtime.init brings up)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_controller: Optional[ElasticController] = None
_thread: Optional[threading.Thread] = None
_stop_event: Optional[threading.Event] = None


def controller() -> Optional[ElasticController]:
    return _controller


def period_s() -> float:
    """Control-loop cadence: ``RSDL_ELASTIC_PERIOD_S``, defaulting to
    the timeseries sampler period so verdicts and actions share a
    clock."""
    env = os.environ.get(ENV_PERIOD_S, "").strip()
    if env:
        try:
            return max(0.1, float(env))
        except ValueError:
            pass
    try:
        from ray_shuffling_data_loader_tpu.telemetry import timeseries

        return timeseries.period_s()
    except Exception:
        return 2.0


def running() -> bool:
    return _thread is not None and _thread.is_alive()


def start(ctx=None, period: Optional[float] = None) -> None:
    """Start the control loop (idempotent; session owner only — one
    controller per session, like the obs server and sampler)."""
    global _controller, _thread, _stop_event
    if not _metrics.enabled():
        return
    interval = period_s() if period is None else max(0.1, float(period))
    with _lock:
        if _thread is not None and _thread.is_alive():
            return
        _controller = ElasticController(ctx)
        stop_event = threading.Event()
        _stop_event = stop_event
        ctl = _controller

        def _loop():
            while not stop_event.wait(interval):
                ctl.tick()

        _thread = threading.Thread(
            target=_loop, name="rsdl-elastic", daemon=True
        )
        _thread.start()


def maybe_start(ctx=None) -> bool:
    """Start iff ``RSDL_ELASTIC`` requests it AND metrics are on (the
    loop's inputs — critical path, stragglers, capacity — are all
    metrics-plane folds; without them the policy would be guessing)."""
    if not enabled():
        return False
    if not _metrics.enabled():
        import logging

        logging.getLogger(__name__).warning(
            "%s=%s requested but RSDL_METRICS is off — the elastic "
            "loop needs the decision plane's signals; not starting",
            ENV_ELASTIC, mode(),
        )
        return False
    start(ctx)
    return True


def stop() -> None:
    """Stop the loop and join its thread (session shutdown, tests)."""
    global _thread, _stop_event, _controller
    with _lock:
        thread, _thread = _thread, None
        stop_event, _stop_event = _stop_event, None
        _controller = None
    if stop_event is not None:
        stop_event.set()
    if thread is not None:
        thread.join(timeout=5.0)


def summary() -> Dict[str, Any]:
    """Lifetime totals for bench embedding (empty when no controller
    ever ran in this process)."""
    ctl = _controller
    return ctl.summary() if ctl is not None else {}
