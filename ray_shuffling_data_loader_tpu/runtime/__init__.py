"""Host runtime substrate: the TPU-native replacement for Ray core.

The reference (ray-project/ray_shuffling_data_loader) is pure Python on top of
Ray's C++ runtime — tasks/actors, plasma object store, named actors
(SURVEY.md §2b). This package provides the equivalent substrate for TPU-VM
hosts:

* :mod:`.store` — shared-memory columnar object store (data plane).
* :mod:`.actor` — named async actor endpoints over unix/TCP sockets
  (control plane; ``ray.get_actor`` ≙ :func:`connect_actor`).
* :mod:`.tasks` — spawned worker pool with futures and ``wait``
  (``@ray.remote`` tasks ≙ :func:`submit`).

``init()`` creates (or joins, via the ``RSDL_RUNTIME_DIR`` env var or an
explicit ``address=``) a *session*: a runtime directory holding the actor
registry plus a session id that prefixes every shared-memory segment. This
mirrors ``ray.init(address=...)`` joining an existing cluster
(reference ``benchmarks/benchmark.py:216-256``).
"""

from __future__ import annotations

import atexit
import json
import os
import secrets
import shutil
import tempfile
import threading
from typing import Callable, Optional

# The fault-injection plane is NOT imported here (ISSUE 14
# gate-integrity): ``runtime.faults`` resolves through the PEP 562
# ``__getattr__`` at the bottom of this module, so importing the
# runtime package never executes the plane's module body.
from .actor import (  # noqa: F401
    ActorDiedError,
    ActorHandle,
    RemoteError,
    connect_actor as _connect_actor,
    resolve_actor as _resolve_actor,
    spawn_actor as _spawn_actor,
)
from .retry import RetryPolicy  # noqa: F401
from .store import (  # noqa: F401
    ColumnBatch,
    ObjectCorruptError,
    ObjectLostError,
    ObjectRef,
    ObjectStore,
    StoreStats,
)
from .tasks import TaskError, TaskFuture, WorkerPool, wait  # noqa: F401

_ENV_DIR = "RSDL_RUNTIME_DIR"
_CLUSTER_FILE = "cluster.json"


class RuntimeContext:
    def __init__(self, runtime_dir: str, owner: bool, num_workers: int):
        self.runtime_dir = runtime_dir
        self.owner = owner
        self.session = os.path.basename(runtime_dir)
        self.store = ObjectStore(self.session)
        self.cluster = None  # ClusterClient when joined to a cluster
        self._owns_cluster_services = False
        self._pool: Optional[WorkerPool] = None
        self._pool_lock = threading.Lock()
        self._num_workers = num_workers
        self._owned_actors = []
        self._owned_names = []

    @property
    def pool(self) -> WorkerPool:
        # Lazy: pure consumers (worker trainer ranks) never pay for a pool.
        with self._pool_lock:
            if self._pool is None:
                # Workers must join THIS session (not create orphan ones),
                # even when the driver joined via init(address=...) with no
                # env var exported.
                self._pool = WorkerPool(
                    self._num_workers,
                    env={_ENV_DIR: self.runtime_dir},
                )
            return self._pool

    @property
    def scheduler(self):
        """Where tasks go: the cluster-wide round-robin scheduler when
        joined to a cluster, else the local worker pool (same ``submit``
        surface). Under the multi-job service plane (``RSDL_SERVICE``,
        ISSUE 15) the base scheduler is wrapped for fair-share
        interleaving across jobs — env-guarded BEFORE the import, so a
        service-off process never loads the plane."""
        base = (
            self.cluster.scheduler()
            if self.cluster is not None
            else self.pool
        )
        if os.environ.get("RSDL_SERVICE"):
            try:
                from .service import wrap_scheduler

                return wrap_scheduler(base)
            except Exception:
                pass
        return base

    def shutdown(self):
        if self.cluster is not None:
            # Release cluster-wide names this process claimed, so reruns
            # against a persistent cluster can reuse them.
            for name in self._owned_names:
                try:
                    self.cluster.unregister_named_actor(name)
                except Exception:
                    pass
        if self.cluster is not None and self._owns_cluster_services:
            self.cluster.leave()
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None
        for handle in self._owned_actors:
            try:
                handle.terminate(grace_period_s=2.0)
            except Exception:
                pass
        self._owned_actors.clear()
        # Federation teardown AFTER the pool/actors (their exit
        # barriers flushed spools) and BEFORE the rmtree: the shipper's
        # final ship moves shutdown-time records to the driver while
        # they still exist. sys.modules only — a session that never
        # relayed must not import the plane to no-op its stop.
        import sys as _sys

        _relay = _sys.modules.get(
            "ray_shuffling_data_loader_tpu.telemetry.relay"
        )
        if _relay is not None:
            try:
                _relay.stop()
            except Exception:
                pass
        self.cluster = None
        if self.owner:
            self.store.cleanup()
            shutil.rmtree(self.runtime_dir, ignore_errors=True)


_context: Optional[RuntimeContext] = None
_context_lock = threading.Lock()


def _maybe_start_obs_server(ctx: RuntimeContext) -> None:
    """Bring up the live observability endpoint (telemetry.obs_server)
    iff ``RSDL_OBS_PORT`` is set — one env read at session bring-up,
    nothing at all on the hot path. Only the session OWNER serves
    (spawned workers and task processes join with ``owner=False`` and
    inherit the same env; letting each of them bind the port would just
    race). A bind failure is logged inside maybe_start, never fatal."""
    # The continuous profiling plane (ISSUE 17) runs in EVERY process
    # that joins a session — owner or not (a joined trainer rank's
    # consume path is exactly what a fleet profile must cover). Env-
    # gated BEFORE the import: RSDL_PROFILE unset means the module is
    # never loaded, no thread, no spool file.
    if os.environ.get("RSDL_PROFILE"):
        try:
            from ray_shuffling_data_loader_tpu.telemetry import profiler

            profiler.start()
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "profiler bring-up failed", exc_info=True
            )
    if not ctx.owner:
        return
    if os.environ.get("RSDL_OBS_PORT"):
        try:
            from ray_shuffling_data_loader_tpu.telemetry import obs_server

            obs_server.maybe_start()
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "obs server bring-up failed", exc_info=True
            )
    # The temporal half (ISSUE 7): the timeseries sampler runs with the
    # endpoint (or headless under RSDL_TS=1) whenever metrics are on —
    # it is what /timeseries, rsdl_top sparklines, and the straggler
    # gauges' history come from. Same zero-overhead contract: no env
    # set, no import, no thread.
    if os.environ.get("RSDL_OBS_PORT") or os.environ.get("RSDL_TS"):
        try:
            from ray_shuffling_data_loader_tpu.telemetry import metrics
            from ray_shuffling_data_loader_tpu.telemetry import timeseries

            if metrics.enabled() and (
                os.environ.get("RSDL_OBS_PORT") or timeseries.forced_on()
            ):
                timeseries.start()
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "timeseries sampler bring-up failed", exc_info=True
            )
    # The elastic control plane (ISSUE 10): autoscaler + tiered evictor
    # + graceful drain, env-gated RSDL_ELASTIC=auto|off. Same
    # zero-overhead contract as the planes above: env unset means no
    # import, no control-loop thread, and no ledger transition records.
    mode = os.environ.get("RSDL_ELASTIC", "").strip().lower()
    if mode and mode not in ("off", "0", "false"):
        try:
            from .elastic import maybe_start as _elastic_maybe_start

            _elastic_maybe_start(ctx)
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "elastic control-loop bring-up failed", exc_info=True
            )
    # The spool-federation plane (ISSUE 19): head sessions serve the
    # relay sink, non-head sessions run the shipper that tails the
    # local spool trees. RSDL_RELAY=auto|off, env-gated BEFORE the
    # import — unset means no relay module, no shipper thread, no sink
    # socket anywhere in the session.
    mode = os.environ.get("RSDL_RELAY", "").strip().lower()
    if mode and mode not in ("off", "0", "false"):
        try:
            from ray_shuffling_data_loader_tpu.telemetry import relay

            relay.maybe_start(ctx)
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "relay bring-up failed", exc_info=True
            )


def _stop_obs_server() -> None:
    """Stop the endpoint + timeseries sampler + elastic control loop if
    (and only if) their modules were ever loaded — shutdown must not
    import http.server (or the temporal/elastic planes) on runs that
    never served."""
    import sys as _sys

    for name in (
        "ray_shuffling_data_loader_tpu.telemetry.obs_server",
        "ray_shuffling_data_loader_tpu.telemetry.timeseries",
        "ray_shuffling_data_loader_tpu.runtime.elastic",
        "ray_shuffling_data_loader_tpu.runtime.service",
    ):
        mod = _sys.modules.get(name)
        if mod is not None:
            try:
                mod.stop()
            except Exception:
                pass


def _new_session_dir() -> str:
    # Keep the path short: unix socket paths are capped at ~107 chars.
    base = tempfile.gettempdir()
    runtime_dir = os.path.join(base, f"rsdl-{secrets.token_hex(4)}")
    os.makedirs(os.path.join(runtime_dir, "actors"))
    return runtime_dir


def _attach_cluster_client(ctx: RuntimeContext, record: dict, owns: bool):
    """Wire a ClusterClient (from a cluster.json record) into the context:
    registry/agent/store handles + the store's remote data-plane hooks."""
    from .cluster import ClusterClient

    # Task workers joining via the runtime dir need the cluster's bearer
    # token before their first TCP frame.
    if record.get("token") and not os.environ.get("RSDL_CLUSTER_TOKEN"):
        os.environ["RSDL_CLUSTER_TOKEN"] = record["token"]

    client = ClusterClient(
        registry=ActorHandle(tuple(record["registry"])),
        host_id=record["host_id"],
        advertise_host=record["advertise"],
        agent=ActorHandle(tuple(record["agent"])),
        store_server=ActorHandle(tuple(record["store"])),
        is_head=record.get("is_head", False),
        registry_address=tuple(record["registry"])[1:],
    )
    ctx.cluster = client
    ctx._owns_cluster_services = owns
    ctx.store.owner_address = tuple(record["store"])
    ctx.store.remote_fetch = client.fetch_remote
    ctx.store.remote_fetch_into = client.fetch_remote_into
    ctx.store.remote_free = client.free_remote
    return client


def _bootstrap_cluster_host(
    ctx: RuntimeContext,
    registry: ActorHandle,
    advertise: str,
    num_workers: int,
    is_head: bool,
) -> None:
    """Start this host's agent + store server, register with the cluster,
    and persist ``cluster.json`` so local task workers joining via
    ``$RSDL_RUNTIME_DIR`` inherit the cluster hooks (their refs must carry
    this host's owner address or remote reducers could never fetch them)."""
    from .cluster import start_host_services

    agent, store_server = start_host_services(
        ctx.runtime_dir, num_workers, advertise
    )
    ctx._owned_actors += [agent, store_server]
    host_id = f"{advertise}:{ctx.session}"
    registry.call(
        "register_host",
        host_id,
        list(agent.address),
        list(store_server.address),
        num_workers,
    )
    record = {
        "registry": list(registry.address),
        "agent": list(agent.address),
        "store": list(store_server.address),
        "host_id": host_id,
        "advertise": advertise,
        "is_head": is_head,
        "token": os.environ.get("RSDL_CLUSTER_TOKEN"),
    }
    with open(os.path.join(ctx.runtime_dir, _CLUSTER_FILE), "w") as f:
        json.dump(record, f)
    _attach_cluster_client(ctx, record, owns=True)


def init(
    address: Optional[str] = None,
    num_workers: Optional[int] = None,
) -> RuntimeContext:
    """Create or join a runtime session.

    Args:
        address: What to join. ``None`` creates a new single-host session
            owned by this process. A filesystem path joins an existing
            session's runtime directory (also read from
            ``$RSDL_RUNTIME_DIR``). A ``tcp://head:port`` address joins a
            multi-host cluster as a worker host (the ``ray.init(address=...)``
            analog; see :func:`init_cluster` for the head side).
        num_workers: Size of the lazy task worker pool. Defaults to
            ``os.cpu_count()``.
    """
    global _context
    with _context_lock:
        if _context is not None:
            return _context
        if num_workers is None:
            num_workers = max(1, os.cpu_count() or 1)
        address = address or os.environ.get(_ENV_DIR)
        if address and address.startswith("tcp://"):
            from .cluster import (
                default_advertise_host,
                parse_cluster_address,
            )

            host, port, token = parse_cluster_address(address)
            if token:
                # Must land before the first TCP frame (the registry ping).
                os.environ["RSDL_CLUSTER_TOKEN"] = token
            runtime_dir = _new_session_dir()
            os.environ[_ENV_DIR] = runtime_dir
            ctx = RuntimeContext(runtime_dir, owner=True, num_workers=num_workers)
            registry = ActorHandle(("tcp", host, port))
            registry.wait_ready()
            _context = ctx
            atexit.register(shutdown)
            try:
                _bootstrap_cluster_host(
                    ctx,
                    registry,
                    default_advertise_host(),
                    num_workers,
                    is_head=False,
                )
            except BaseException:
                # A half-joined context must not survive as the global
                # session: a retrying init() would get back a context with
                # cluster=None and silently run single-host.
                _context = None
                try:
                    ctx.shutdown()
                except Exception:
                    pass
                raise
            _maybe_start_obs_server(ctx)
            return ctx
        if address:
            if not os.path.isdir(address):
                raise ValueError(f"no runtime session at {address!r}")
            ctx = RuntimeContext(address, owner=False, num_workers=num_workers)
            # Task workers on a cluster host inherit the host's cluster
            # wiring (owner stamping + remote fetch).
            cluster_file = os.path.join(address, _CLUSTER_FILE)
            if os.path.exists(cluster_file):
                with open(cluster_file) as f:
                    _attach_cluster_client(ctx, json.load(f), owns=False)
        else:
            runtime_dir = _new_session_dir()
            os.environ[_ENV_DIR] = runtime_dir
            ctx = RuntimeContext(runtime_dir, owner=True, num_workers=num_workers)
        _context = ctx
        atexit.register(shutdown)
        _maybe_start_obs_server(ctx)
        return ctx


def init_cluster(
    listen_host: str = "0.0.0.0",
    listen_port: int = 0,
    advertise_host: Optional[str] = None,
    num_workers: Optional[int] = None,
) -> RuntimeContext:
    """Start a cluster head: session + registry + this host's services.

    Worker hosts join with ``init(address=ctx.cluster.address)`` (or the
    ``python -m ray_shuffling_data_loader_tpu.runtime.cluster join`` CLI).
    """
    global _context
    from .cluster import ClusterRegistry, default_advertise_host

    with _context_lock:
        if _context is not None:
            raise RuntimeError("runtime already initialized")
        if num_workers is None:
            num_workers = max(1, os.cpu_count() or 1)
        runtime_dir = _new_session_dir()
        os.environ[_ENV_DIR] = runtime_dir
        ctx = RuntimeContext(runtime_dir, owner=True, num_workers=num_workers)
        _context = ctx
        atexit.register(shutdown)
    try:
        # Mint the cluster's bearer token before any TCP endpoint exists;
        # every spawned service inherits it via the environment and every
        # joiner receives it inside the printed tcp:// address.
        os.environ.setdefault("RSDL_CLUSTER_TOKEN", secrets.token_hex(16))
        advertise = advertise_host or default_advertise_host()
        bind_host = advertise if listen_host == "0.0.0.0" else listen_host
        registry = _spawn_actor(
            ClusterRegistry,
            runtime_dir=runtime_dir,
            host=bind_host,
            port=listen_port,
        )
        ctx._owned_actors.append(registry)
        _bootstrap_cluster_host(
            ctx, registry, advertise, num_workers, is_head=True
        )
        _maybe_start_obs_server(ctx)
    except BaseException:
        with _context_lock:
            _context = None
        try:
            ctx.shutdown()
        except Exception:
            pass
        raise
    return ctx


def is_initialized() -> bool:
    return _context is not None


def get_context() -> RuntimeContext:
    if _context is None:
        raise RuntimeError(
            "runtime not initialized; call "
            "ray_shuffling_data_loader_tpu.runtime.init() first"
        )
    return _context


def ensure_initialized() -> RuntimeContext:
    return _context if _context is not None else init()


def shutdown() -> None:
    global _context
    with _context_lock:
        if _context is None:
            return
        ctx, _context = _context, None
    if ctx.owner:
        # The obs endpoint is session-scoped: release the port (and its
        # daemon thread) with the session so a later init() can rebind.
        _stop_obs_server()
    # Spool one last registry snapshot while the runtime dir still
    # exists — for every process, not just the owner: a JOINED process
    # (a trainer rank with consume-side counters) leaving the session is
    # exactly the exit this plane must not lose metrics at. (The owner's
    # own file dies with its rmtree below, but with an RSDL_METRICS_DIR
    # override the spool outlives the session.) Metrics-gated BEFORE
    # the import (ISSUE 14): a disabled run must not load the export
    # plane just to no-op its flush.
    try:
        from ray_shuffling_data_loader_tpu.telemetry import metrics

        if metrics.enabled():
            from ray_shuffling_data_loader_tpu.telemetry import (
                export as _metrics_export,
            )

            _metrics_export.safe_flush()
    except Exception:
        pass
    # Same barrier for the profiling plane (ISSUE 17): stop the sampler
    # and spool its final aggregate while the runtime dir still exists.
    # sys.modules only — a run that never profiled must not import it.
    import sys as _sys

    prof = _sys.modules.get(
        "ray_shuffling_data_loader_tpu.telemetry.profiler"
    )
    if prof is not None:
        try:
            prof.stop()
        except Exception:
            pass
    if os.environ.get(_ENV_DIR) == ctx.runtime_dir and ctx.owner:
        del os.environ[_ENV_DIR]
    ctx.shutdown()


# -- convenience wrappers bound to the current session ----------------------


def _scoped_actor_name(name: Optional[str]) -> Optional[str]:
    """Job-scope a named-actor name under the service plane
    (ISSUE 15): two concurrent jobs spawning the same logical name
    (batch queue, stats collector) get distinct actors instead of
    racing on one registry record. Idempotent; identity without an
    ambient job or with the plane off (env-guarded before the import —
    the zero-overhead contract)."""
    if name is None or not os.environ.get("RSDL_SERVICE"):
        return name
    try:
        from .service import scoped_name

        return scoped_name(name)
    except Exception:
        return name


def submit(fn: Callable, *args, **kwargs) -> TaskFuture:
    """Submit a task to the current scheduler (cluster-wide round-robin when
    in a cluster, else the local pool)."""
    return get_context().scheduler.submit(fn, *args, **kwargs)


def spawn_actor(
    cls,
    *args,
    name: Optional[str] = None,
    host_id: Optional[str] = None,
    **kwargs,
) -> ActorHandle:
    """Spawn an actor process; named actors are discoverable session-wide
    (and, in cluster mode, cluster-wide: the actor binds TCP and registers
    with the head's registry).

    ``host_id`` (cluster mode) is the placement hint: the actor is spawned
    by THAT host's agent and runs there — the analog of the reference's
    SPREAD placement groups / per-actor resource reservations
    (``benchmarks/benchmark.py:125-130``, ``batch_queue.py:46-65``
    ``actor_options``). Use :func:`cluster_hosts` to enumerate candidate
    ids; the actor is reaped with that host's agent (and terminated on
    this session's shutdown like any locally-owned actor)."""
    ctx = get_context()
    name = _scoped_actor_name(name)
    if host_id is not None:
        if ctx.cluster is None:
            raise ValueError("host_id placement requires cluster mode")
        if host_id != ctx.cluster.host_id:
            hosts = ctx.cluster.registry.call("hosts")
            info = hosts.get(host_id)
            if info is None:
                raise ValueError(
                    f"unknown host_id {host_id!r}; "
                    f"cluster hosts: {sorted(hosts)}"
                )
            agent = ActorHandle(tuple(info["agent"]))
            # The registry keeps dead hosts until eviction, so a
            # half-dead agent must fail (letting callers' fallback pick
            # another host) rather than wedge the trial forever. A short
            # ping filters the common case cheaply — valid even while the
            # agent is mid-spawn, because spawn_named_actor is async and
            # its blocking bring-up runs off the event loop. The spawn
            # itself gets a generous bound so a slow-but-healthy spawn
            # (first-touch jax init in the actor ctor) isn't false-failed
            # — on a true mid-spawn wedge the agent may still finish the
            # spawn later and hold the orphan until session teardown
            # reaps it (bounded, and preferable to an unbounded hang).
            if not agent.ping(timeout=5.0):
                raise ActorDiedError(
                    f"host {host_id!r} agent unreachable (ping timeout); "
                    "host may be dead but not yet evicted"
                )
            # The client bound tracks the agent-side readiness deadline
            # (spawn_actor's RSDL_SPAWN_READY_TIMEOUT_S) plus slack, so
            # the AGENT always resolves a slow spawn first — a shorter
            # client bound would false-fail legitimate spawns and leak a
            # duplicate actor on the remote host.
            ready_s = float(
                os.environ.get("RSDL_SPAWN_READY_TIMEOUT_S", "600")
            )
            address, _pid = agent.call_with_timeout(
                "spawn_named_actor", cls, list(args), kwargs, name,
                timeout=ready_s + 30.0,
            )
            # pid deliberately omitted: it belongs to the REMOTE host;
            # terminate() must only use the TCP path, never signal a
            # same-numbered local process.
            handle = ActorHandle(tuple(address), pid=None, name=name)
            ctx._owned_actors.append(handle)
            if name is not None:
                # Record the TARGET host on the name record: when that
                # host drains/retires, the registry sweeps the name so
                # lookups fail fast into the retry path instead of
                # timing out against a dead address.
                ctx.cluster.register_named_actor(
                    name, handle, host_id=host_id
                )
                ctx._owned_names.append(name)
            return handle
    if ctx.cluster is not None:
        kwargs.setdefault("host", ctx.cluster.advertise_host)
    handle = _spawn_actor(
        cls, *args, name=name, runtime_dir=ctx.runtime_dir, **kwargs
    )
    ctx._owned_actors.append(handle)
    if name is not None and ctx.cluster is not None:
        ctx.cluster.register_named_actor(name, handle)
        ctx._owned_names.append(name)
    return handle


def cluster_hosts() -> list:
    """Sorted host ids currently registered in the cluster (the calling
    host first); empty outside cluster mode. The enumeration side of
    actor placement (``spawn_actor(host_id=...)``)."""
    ctx = get_context()
    if ctx.cluster is None:
        return []
    hosts = ctx.cluster.registry.call("hosts")
    own = ctx.cluster.host_id
    return sorted(hosts, key=lambda h: (h != own, h))


def connect_actor(name: str, num_retries: int = 5) -> ActorHandle:
    """Discover a named actor: local session registry first, then (cluster
    mode) the head's registry, with exponential backoff — parity with the
    reference's ``connect_queue_actor`` retry loop
    (``batch_queue.py:358-380``)."""
    ctx = get_context()
    name = _scoped_actor_name(name)
    fallback = (
        ctx.cluster.lookup_named_actor if ctx.cluster is not None else None
    )
    return _connect_actor(
        name,
        ctx.runtime_dir,
        num_retries=num_retries,
        fallback_resolver=fallback,
    )


def resolve_actor(name: str) -> Optional[ActorHandle]:
    ctx = get_context()
    name = _scoped_actor_name(name)
    handle = _resolve_actor(name, ctx.runtime_dir)
    if handle is None and ctx.cluster is not None:
        handle = ctx.cluster.lookup_named_actor(name)
    return handle


def put_columns(columns) -> ObjectRef:
    return get_context().store.put_columns(columns)


def get_columns(ref: ObjectRef) -> ColumnBatch:
    return get_context().store.get_columns(ref)


def free(refs) -> None:
    get_context().store.free(refs)


def store_stats() -> StoreStats:
    return get_context().store.store_stats()


def __getattr__(name):
    # PEP 562 lazy resolution for the fault-injection plane (ISSUE 14
    # gate-integrity): `runtime.faults` and `from ...runtime import
    # faults` both keep working, but the plane's module body executes
    # only on first touch. After that first import the package
    # attribute exists for real and this hook is never consulted again.
    if name == "faults":
        # importlib, NOT `from . import faults`: the from-import form
        # re-enters this __getattr__ while the attribute is still
        # unbound and recurses forever.
        import importlib

        return importlib.import_module(f"{__name__}.faults")
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
