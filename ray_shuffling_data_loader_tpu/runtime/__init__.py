"""Host runtime substrate: the TPU-native replacement for Ray core.

The reference (ray-project/ray_shuffling_data_loader) is pure Python on top of
Ray's C++ runtime — tasks/actors, plasma object store, named actors
(SURVEY.md §2b). This package provides the equivalent substrate for TPU-VM
hosts:

* :mod:`.store` — shared-memory columnar object store (data plane).
* :mod:`.actor` — named async actor endpoints over unix/TCP sockets
  (control plane; ``ray.get_actor`` ≙ :func:`connect_actor`).
* :mod:`.tasks` — spawned worker pool with futures and ``wait``
  (``@ray.remote`` tasks ≙ :func:`submit`).

``init()`` creates (or joins, via the ``RSDL_RUNTIME_DIR`` env var or an
explicit ``address=``) a *session*: a runtime directory holding the actor
registry plus a session id that prefixes every shared-memory segment. This
mirrors ``ray.init(address=...)`` joining an existing cluster
(reference ``benchmarks/benchmark.py:216-256``).
"""

from __future__ import annotations

import atexit
import os
import secrets
import shutil
import tempfile
import threading
from typing import Callable, Optional

from .actor import (  # noqa: F401
    ActorDiedError,
    ActorHandle,
    RemoteError,
    connect_actor as _connect_actor,
    resolve_actor as _resolve_actor,
    spawn_actor as _spawn_actor,
)
from .store import ColumnBatch, ObjectRef, ObjectStore, StoreStats  # noqa: F401
from .tasks import TaskError, TaskFuture, WorkerPool, wait  # noqa: F401

_ENV_DIR = "RSDL_RUNTIME_DIR"


class RuntimeContext:
    def __init__(self, runtime_dir: str, owner: bool, num_workers: int):
        self.runtime_dir = runtime_dir
        self.owner = owner
        self.session = os.path.basename(runtime_dir)
        self.store = ObjectStore(self.session)
        self._pool: Optional[WorkerPool] = None
        self._pool_lock = threading.Lock()
        self._num_workers = num_workers
        self._owned_actors = []

    @property
    def pool(self) -> WorkerPool:
        # Lazy: pure consumers (worker trainer ranks) never pay for a pool.
        with self._pool_lock:
            if self._pool is None:
                # Workers must join THIS session (not create orphan ones),
                # even when the driver joined via init(address=...) with no
                # env var exported.
                self._pool = WorkerPool(
                    self._num_workers,
                    env={_ENV_DIR: self.runtime_dir},
                )
            return self._pool

    def shutdown(self):
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None
        for handle in self._owned_actors:
            try:
                handle.terminate(grace_period_s=2.0)
            except Exception:
                pass
        self._owned_actors.clear()
        if self.owner:
            self.store.cleanup()
            shutil.rmtree(self.runtime_dir, ignore_errors=True)


_context: Optional[RuntimeContext] = None
_context_lock = threading.Lock()


def init(
    address: Optional[str] = None,
    num_workers: Optional[int] = None,
) -> RuntimeContext:
    """Create or join a runtime session.

    Args:
        address: Path of an existing session's runtime directory to join
            (also read from ``$RSDL_RUNTIME_DIR``). ``None`` creates a new
            session owned by this process.
        num_workers: Size of the lazy task worker pool. Defaults to
            ``os.cpu_count()``.
    """
    global _context
    with _context_lock:
        if _context is not None:
            return _context
        if num_workers is None:
            num_workers = max(1, os.cpu_count() or 1)
        address = address or os.environ.get(_ENV_DIR)
        if address:
            if not os.path.isdir(address):
                raise ValueError(f"no runtime session at {address!r}")
            ctx = RuntimeContext(address, owner=False, num_workers=num_workers)
        else:
            # Keep the path short: unix socket paths are capped at ~107 chars.
            base = tempfile.gettempdir()
            runtime_dir = os.path.join(
                base, f"rsdl-{secrets.token_hex(4)}"
            )
            os.makedirs(os.path.join(runtime_dir, "actors"))
            os.environ[_ENV_DIR] = runtime_dir
            ctx = RuntimeContext(runtime_dir, owner=True, num_workers=num_workers)
        _context = ctx
        atexit.register(shutdown)
        return ctx


def is_initialized() -> bool:
    return _context is not None


def get_context() -> RuntimeContext:
    if _context is None:
        raise RuntimeError(
            "runtime not initialized; call "
            "ray_shuffling_data_loader_tpu.runtime.init() first"
        )
    return _context


def ensure_initialized() -> RuntimeContext:
    return _context if _context is not None else init()


def shutdown() -> None:
    global _context
    with _context_lock:
        if _context is None:
            return
        ctx, _context = _context, None
    if os.environ.get(_ENV_DIR) == ctx.runtime_dir and ctx.owner:
        del os.environ[_ENV_DIR]
    ctx.shutdown()


# -- convenience wrappers bound to the current session ----------------------


def submit(fn: Callable, *args, **kwargs) -> TaskFuture:
    return get_context().pool.submit(fn, *args, **kwargs)


def spawn_actor(cls, *args, name: Optional[str] = None, **kwargs) -> ActorHandle:
    ctx = get_context()
    handle = _spawn_actor(
        cls, *args, name=name, runtime_dir=ctx.runtime_dir, **kwargs
    )
    ctx._owned_actors.append(handle)
    return handle


def connect_actor(name: str, num_retries: int = 5) -> ActorHandle:
    return _connect_actor(
        name, get_context().runtime_dir, num_retries=num_retries
    )


def resolve_actor(name: str) -> Optional[ActorHandle]:
    return _resolve_actor(name, get_context().runtime_dir)


def put_columns(columns) -> ObjectRef:
    return get_context().store.put_columns(columns)


def get_columns(ref: ObjectRef) -> ColumnBatch:
    return get_context().store.get_columns(ref)


def free(refs) -> None:
    get_context().store.free(refs)


def store_stats() -> StoreStats:
    return get_context().store.store_stats()
