"""Host-side shared-memory object store.

This is the data plane of the runtime: the TPU-native replacement for Ray's
plasma object store (used by the reference for every shuffle intermediate and
for batch delivery — reference ``dataset.py:136-139``, ``shuffle.py:112-124``).
Bulk data never transits the control-plane sockets; producers write columnar
buffers into per-object shared-memory segments and ship only small
:class:`ObjectRef` handles (the reference ships ``ray.ObjectRef`` lists through
its queue actor, ``dataset.py:195-196``).

Design (TPU-first, not a port):

* Objects are **columnar**: a batch is a set of named, dtype-tagged,
  contiguous 64-byte-aligned buffers. This is the layout ``jax.device_put``
  wants — a reducer output can be staged into HBM without any row-wise
  re-packing (the reference instead passes pandas DataFrames and pays
  ``pd.concat``/``torch.as_tensor`` copies, ``torch_dataset.py:223``).
* Segments are plain files in ``/dev/shm`` mapped with ``mmap`` — the same
  mechanism a C++ store would use (``shm_open``), zero-copy across processes,
  and free of the CPython ``resource_tracker`` bookkeeping that
  ``multiprocessing.shared_memory`` imposes.
* Reads return **zero-copy numpy views** over the mapping; the mapping is kept
  alive by the returned :class:`ColumnBatch`.

The store has no server process: the filesystem is the index. Utilization
introspection (`store_stats`) replaces the reference's raylet
``FormatGlobalMemoryInfo`` gRPC probe (``stats.py:675-683``).
"""

from __future__ import annotations

import json
import mmap
import os
import secrets
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics

from . import transport as _transport

# Fault-injection plane (ISSUE 14 gate-integrity): lazy proxy — the
# store's fault sites pay one proxy getattr, the import happens only if
# a site actually runs.
from ray_shuffling_data_loader_tpu._lazy import lazy_module

faults = lazy_module("ray_shuffling_data_loader_tpu.runtime.faults")

_MAGIC = b"RSDL1\x00"
_ALIGN = 64
_HEADER = struct.Struct("<6sI")  # magic, json length


def _default_shm_dir() -> str:
    d = os.environ.get("RSDL_SHM_DIR")
    if d:
        return d
    if os.path.isdir("/dev/shm"):
        return "/dev/shm"
    import tempfile

    return tempfile.gettempdir()


def _default_spill_dir() -> str:
    d = os.environ.get("RSDL_SPILL_DIR")
    if d:
        return d
    import tempfile

    return os.path.join(tempfile.gettempdir(), "rsdl-spill")


_spill_event_last = 0.0
_SPILL_EVENT_INTERVAL_S = 5.0
_spill_lock = threading.Lock()
_spill_pending_bytes = 0
_spill_pending_events = 0


def _emit_spill_event(nbytes: int) -> None:
    """Structured event-log mark that the store hit its budget and
    started spilling. Rate-limited: a budget-pinned run places *every*
    segment on disk, and one event per 5 s per process tells the story
    without flooding the log — but the VOLUME stays exact: every call
    increments the ``store.spill_bytes_total`` counter, and the bytes
    of suppressed calls accumulate onto the next emitted event's
    ``nbytes`` (with the fold count in ``events_folded``), so summing
    the event log reproduces the true spill total. Metrics-gated
    inside emit_event/safe_inc."""
    global _spill_event_last, _spill_pending_bytes, _spill_pending_events
    _metrics.safe_inc("store.spill_bytes_total", float(nbytes))
    now = time.monotonic()
    with _spill_lock:
        _spill_pending_bytes += int(nbytes)
        _spill_pending_events += 1
        if now - _spill_event_last < _SPILL_EVENT_INTERVAL_S:
            return
        _spill_event_last = now
        pending, _spill_pending_bytes = _spill_pending_bytes, 0
        folded, _spill_pending_events = _spill_pending_events, 0
    try:
        from ray_shuffling_data_loader_tpu import telemetry

        telemetry.emit_event(
            "store.spill", nbytes=int(pending), events_folded=int(folded)
        )
    except Exception:
        pass


def _ledger_note(op: str, object_id: str, nbytes: int = 0,
                 tier: Optional[str] = None, ids=None) -> None:
    """Capacity-ledger hook (telemetry.capacity): one cached boolean
    when metrics are off — the module is never imported and the store
    path pays nothing; never raises."""
    if not _metrics.enabled():
        return
    try:
        from ray_shuffling_data_loader_tpu.telemetry import capacity

        capacity.note(op, object_id, nbytes=nbytes, tier=tier, ids=ids)
    except Exception:
        pass


def _default_capacity_bytes(shm_dir: str) -> Optional[int]:
    """Session budget for shared-memory residency. ``RSDL_STORE_CAPACITY_BYTES``
    absolute, else ``RSDL_STORE_CAPACITY_FRACTION`` (default 0.8) of the
    filesystem size — the reference provisions its object store explicitly
    per node with spilling disabled (reference
    ``benchmarks/cluster.yaml:171-181``); here the default caps tmpfs use
    below the cliff where the kernel OOM-kills or ENOSPCs mid-epoch."""
    env = os.environ.get("RSDL_STORE_CAPACITY_BYTES")
    if env:
        return int(env) if int(env) > 0 else None
    frac = float(os.environ.get("RSDL_STORE_CAPACITY_FRACTION", "0.8"))
    try:
        st = os.statvfs(shm_dir)
        return int(st.f_blocks * st.f_frsize * frac)
    except OSError:
        return None


def fetch_window_depth(default: int = 8) -> int:
    """The ONE parser of ``RSDL_FETCH_WINDOW_DEPTH``, the window-
    pipelining depth knob. Call sites pass their own default (the
    overlapped reduce uses 4 — it also bounds peak fetched-cache
    residency there; the delivery-plane prefetch pool uses 8); the env
    var, when set, overrides both."""
    env = os.environ.get("RSDL_FETCH_WINDOW_DEPTH")
    if not env:
        return default
    try:
        return max(1, int(env))
    except ValueError:
        return default


class GrowingThreadPool:
    """A ThreadPoolExecutor that widens on demand — the shared grow
    mechanism for the store's prefetch pool and the cluster client's
    striped-fetch pool (both bind a width on first use that a later,
    wider caller must be able to raise).

    Growth is by replacement, and replaced pools are RETIRED, never shut
    down: a submit racing a grow may land on the old pool, and a closed
    executor would turn that into a spurious ``RuntimeError``. Retired
    pools drain their queues then idle — bounded at one small pool per
    distinct growth step — until :meth:`shutdown`."""

    def __init__(self, thread_name_prefix: str):
        self._prefix = thread_name_prefix
        self._lock = threading.Lock()
        self._pool = None
        self._retired: list = []
        self.width = 0

    def ensure(self, width: int) -> "GrowingThreadPool":
        """Make the pool at least ``width`` wide; returns self (usable
        wherever an executor's ``submit`` is expected)."""
        import concurrent.futures

        with self._lock:
            if self._pool is None or width > self.width:
                if self._pool is not None:
                    self._retired.append(self._pool)
                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=width, thread_name_prefix=self._prefix
                )
                self.width = width
        return self

    def submit(self, fn, *args, **kwargs):
        with self._lock:
            if self._pool is None:
                raise RuntimeError("GrowingThreadPool: ensure() not called")
            pool = self._pool
        return pool.submit(fn, *args, **kwargs)

    def shutdown(self, wait: bool = False) -> None:
        with self._lock:
            pools, self._retired = list(self._retired), []
            if self._pool is not None:
                pools.append(self._pool)
                self._pool = None
            self.width = 0
        for pool in pools:
            pool.shutdown(wait=wait)


class ObjectLostError(FileNotFoundError):
    """A store object's segment is gone (freed early, host died holding
    the only copy, or an injected ``store.get:lost`` fault). Carries the
    object id so the shuffle driver's lineage recovery can re-execute
    the producing task instead of failing the epoch. Subclasses
    ``FileNotFoundError`` so pre-existing ``except OSError`` paths keep
    working."""

    def __init__(self, object_id: str, detail: str = ""):
        msg = f"store object {object_id!r} lost"
        if detail:
            msg = f"{msg} ({detail})"
        super().__init__(2, msg)
        self.object_id = object_id
        self._detail = detail

    def __reduce__(self):
        # OSError's default reduce would replay (2, msg) into our
        # (object_id, detail) signature; preserve the real fields.
        return (type(self), (self.object_id, self._detail))


class ObjectCorruptError(ObjectLostError):
    """A segment exists but its payload failed validation (bad magic, or
    an injected ``store.get:corrupt`` fault). Recovery is identical to a
    lost object: re-materialize from lineage."""

    def __init__(self, object_id: str, detail: str = "corrupt payload"):
        super().__init__(object_id, detail)


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _plan_layout(
    spec: Mapping[str, Tuple[Tuple[int, ...], "np.dtype"]],
    layout: Optional[dict] = None,
):
    """The single source of truth for the segment format: per-column meta,
    payload start, and total size for a ``{name: (shape, dtype)}`` spec.
    Used by the disk write path (``create_columns``) and the DCN wire path
    (``serialize_columns``) so the two can never drift.

    ``layout`` is an optional JSON-safe descriptor carried in the meta
    blob — device-direct delivery stamps reducer outputs with their
    staging layout (``{"kind": "device-batch", "batch": B, "columns":
    [...], "dtypes": [...]}``) so every reader of the segment, local mmap
    or cross-host fetch alike, knows the bytes are already in the
    [n_cols, batch]-packed form ``jax.device_put`` stages directly."""
    meta: List[dict] = []
    offset = 0
    for name, (shape, dtype) in spec.items():
        dtype = np.dtype(dtype)
        nbytes = int(dtype.itemsize * int(np.prod(shape, dtype=np.int64)))
        offset = _align(offset)
        meta.append(
            {
                "name": name,
                "dtype": dtype.str,
                "shape": list(shape),
                "offset": offset,
                "nbytes": nbytes,
            }
        )
        offset += nbytes
    payload_bytes = _align(offset)
    head: Dict[str, object] = {"columns": meta}
    if layout is not None:
        head["layout"] = layout
    meta_blob = json.dumps(head).encode()
    payload_start = _align(_HEADER.size + len(meta_blob))
    total = payload_start + payload_bytes
    return meta, meta_blob, payload_start, total


@dataclass(frozen=True)
class ObjectRef:
    """A small, picklable handle to a shared-memory object.

    The control-plane analog of ``ray.ObjectRef``: queues and RPC messages
    carry these, never the underlying buffers. In cluster mode ``owner`` is
    the producing host's store-server address, so any host can pull the
    segment over DCN on first use (:mod:`.cluster`); ``None`` means
    single-host/local.

    ``rows`` restricts the ref to a half-open row window of the segment —
    several refs can hardlink one physical segment (the map stage publishes
    its per-reducer partitions this way, so partitioning writes each row
    once instead of once per copy-out). Each ref owns its own directory
    link; the data dies when the last link is freed.
    """

    object_id: str
    nbytes: int
    session: str = ""
    owner: Optional[Tuple] = None
    rows: Optional[Tuple[int, int]] = None


class ColumnBatch(Mapping[str, np.ndarray]):
    """A named collection of equal-length columns backed by one mapping.

    Zero-copy view over a store segment (or plain in-memory arrays when
    constructed directly). Mapping protocol yields column name -> ndarray.
    """

    def __init__(
        self,
        columns: Dict[str, np.ndarray],
        _keepalive=None,
        layout: Optional[dict] = None,
        packed: Optional[np.ndarray] = None,
    ):
        self._columns = columns
        self._keepalive = _keepalive
        # Device-direct delivery (ISSUE 8): ``layout`` is the segment's
        # staging-layout descriptor; ``packed`` is the contiguous
        # ``[n_cols, batch]`` int32 block backing a single batch's
        # logical column views (set only on per-batch views produced by
        # :func:`iter_packed_batches` — the buffer ``jax.device_put``
        # can stage with zero host-side copies).
        self.layout = layout
        self.packed = packed
        lengths = {len(v) for v in columns.values()}
        if len(lengths) > 1:
            raise ValueError(f"ragged columns: {lengths}")
        self._num_rows = lengths.pop() if lengths else 0

    def __getitem__(self, key: str) -> np.ndarray:
        return self._columns[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._columns)

    def __len__(self) -> int:
        return len(self._columns)

    @property
    def num_rows(self) -> int:
        return self._num_rows

    @property
    def columns(self) -> Dict[str, np.ndarray]:
        return self._columns

    @property
    def nbytes(self) -> int:
        return sum(v.nbytes for v in self._columns.values())

    def take(self, indices: np.ndarray) -> "ColumnBatch":
        """Row gather: the core shuffle primitive (one gather per column,
        through the C++ kernel when built — ``native.take``)."""
        from ray_shuffling_data_loader_tpu import native

        return ColumnBatch(
            {k: native.take(v, indices) for k, v in self._columns.items()}
        )

    @staticmethod
    def concat_take(
        batches: Sequence["ColumnBatch"],
        indices: np.ndarray,
        out: Optional[Dict[str, np.ndarray]] = None,
    ) -> "ColumnBatch":
        """``concat(batches).take(indices)`` without materializing the
        concat when the native fused kernel is available (reduce-stage hot
        path; the reference pays ``pd.concat`` + ``DataFrame.sample``,
        reference ``shuffle.py:192-194``). ``out`` gathers straight into
        pre-allocated destinations (store-segment views)."""
        from ray_shuffling_data_loader_tpu import native

        batches = [b for b in batches if b is not None and b.num_rows > 0]
        if not batches:
            return ColumnBatch({})
        keys = list(batches[0])
        return ColumnBatch(
            {
                k: native.take_multi(
                    [b[k] for b in batches],
                    indices,
                    # out[k]: a missing destination must raise, not silently
                    # gather into a throwaway array.
                    out=out[k] if out is not None else None,
                )
                for k in keys
            }
        )

    def slice(self, start: int, stop: int) -> "ColumnBatch":
        """Zero-copy row slice. A device-batch segment slices along its
        batch axis, so the layout descriptor stays valid and rides the
        view."""
        return ColumnBatch(
            {k: v[start:stop] for k, v in self._columns.items()},
            _keepalive=self._keepalive,
            layout=self.layout,
        )

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame({k: v for k, v in self._columns.items()})

    @staticmethod
    def from_pandas(df) -> "ColumnBatch":
        return ColumnBatch(
            {str(c): np.ascontiguousarray(df[c].to_numpy()) for c in df.columns}
        )

    @staticmethod
    def concat(batches: Sequence["ColumnBatch"]) -> "ColumnBatch":
        batches = [b for b in batches if b is not None and b.num_rows > 0]
        if not batches:
            return ColumnBatch({})
        if len(batches) == 1:
            return batches[0]
        keys = list(batches[0])
        return ColumnBatch(
            {k: np.concatenate([b[k] for b in batches]) for k in keys}
        )


# ---------------------------------------------------------------------------
# Device-batch (packed) segment layout (ISSUE 8: device-direct delivery)
# ---------------------------------------------------------------------------
# A reducer that knows the trainer's staging layout emits its batch-
# aligned rows as ONE column named PACKED_COLUMN of shape
# ``[n_batches, n_cols, batch]`` int32: batch ``b`` is the contiguous
# ``[n_cols, batch]`` block the JAX stager ships to the device with a
# single ``device_put`` straight off the mmapped segment (float columns
# ride as int32 bit patterns and are bitcast back on device — the same
# wire trick the legacy host-side pack used). The ``layout`` descriptor
# in the segment meta names the logical columns, their true dtypes, and
# the batch size, so every consumer — local mmap, legacy pickle fetch,
# or the striped zero-copy TCP plane — can reconstruct zero-copy logical
# column views without a repack.

PACKED_COLUMN = "__packed__"
DEVICE_BATCH_KIND = "device-batch"


def is_device_batch(cb: "ColumnBatch") -> bool:
    """Does this batch hold a packed device-layout body segment?"""
    return (
        cb.layout is not None
        and cb.layout.get("kind") == DEVICE_BATCH_KIND
        and PACKED_COLUMN in cb
    )


def device_batch_rows(cb: "ColumnBatch") -> int:
    """Logical row count of a packed segment (batches x batch size)."""
    mat = cb[PACKED_COLUMN]
    return int(mat.shape[0]) * int(mat.shape[2])


def iter_packed_batches(cb: "ColumnBatch") -> Iterator["ColumnBatch"]:
    """Split a packed device-batch segment into per-batch views.

    Each yielded batch is an ordinary :class:`ColumnBatch` whose logical
    columns are ZERO-COPY views into the segment (row ``i`` of the block,
    bit-viewed back to its true dtype), with ``.packed`` set to the
    contiguous ``[n_cols, batch]`` int32 block for direct staging."""
    lay = cb.layout or {}
    mat = cb[PACKED_COLUMN]
    names = lay["columns"]
    dtypes = [np.dtype(d) for d in lay["dtypes"]]
    for b in range(mat.shape[0]):
        block = mat[b]
        cols = {
            name: block[i].view(dt)
            for i, (name, dt) in enumerate(zip(names, dtypes))
        }
        yield ColumnBatch(
            cols, _keepalive=cb._keepalive, layout=lay, packed=block
        )


class _LazyLogicalColumns(Mapping[str, np.ndarray]):
    """Logical column access over a whole packed segment without
    materializing every column: column ``name`` is the flattened
    ``mat[:, i, :]`` plane (one contiguous copy of just that column,
    built on first access). Audit digests read only the key column, so
    this keeps the audit path O(key bytes), not O(segment bytes)."""

    def __init__(self, cb: "ColumnBatch"):
        self._mat = cb[PACKED_COLUMN]
        lay = cb.layout or {}
        self._names = list(lay["columns"])
        self._dtypes = [np.dtype(d) for d in lay["dtypes"]]
        self._cache: Dict[str, np.ndarray] = {}

    def __getitem__(self, name: str) -> np.ndarray:
        out = self._cache.get(name)
        if out is None:
            try:
                i = self._names.index(name)
            except ValueError:
                raise KeyError(name) from None
            plane = self._mat[:, i, :]  # (n_batches, B), rows contiguous
            out = plane.reshape(-1).view(self._dtypes[i])
            self._cache[name] = out
        return out

    def __iter__(self) -> Iterator[str]:
        return iter(self._names)

    def __len__(self) -> int:
        return len(self._names)

    def __contains__(self, name: object) -> bool:
        return name in self._names


def logical_columns(cb: "ColumnBatch") -> Mapping[str, np.ndarray]:
    """Column-name -> 1-D logical array view of any batch: the identity
    for ordinary columnar batches, a lazy per-column flatten for packed
    device-batch segments (only accessed columns are materialized)."""
    if is_device_batch(cb):
        return _LazyLogicalColumns(cb)
    return cb.columns


class PendingColumns:
    """An allocated-but-unpublished segment with writable column views.

    Produced by :meth:`ObjectStore.create_columns`. The mapping stays alive
    as long as this object (or any view of it) does; publishing renames the
    hidden ``.tmp`` file, so readers never observe a half-written segment.
    """

    def __init__(self, store, object_id, tmp_path, path, nbytes, mm, views,
                 ledger_tier: Optional[str] = None):
        self._store = store
        self.object_id = object_id
        self._tmp = tmp_path
        self._path = path
        self.nbytes = nbytes
        self._mm = mm
        self.columns: Dict[str, np.ndarray] = views
        self._published = False
        # Logical capacity-ledger tier override (e.g. "cache" for the
        # shared decode-cache tier, ISSUE 11); None = the physical tier.
        self._ledger_tier = ledger_tier

    @property
    def num_rows(self) -> int:
        for v in self.columns.values():
            return len(v)
        return 0

    def seal(self) -> ObjectRef:
        """Publish as a single object."""
        assert not self._published, "already published"
        os.rename(self._tmp, self._path)
        self._published = True
        _ledger_note(
            "create", self.object_id, self.nbytes,
            self._ledger_tier or self._store.tier_of(self._path),
        )
        return ObjectRef(
            object_id=self.object_id,
            nbytes=self.nbytes,
            session=self._store.session,
            owner=self._store.owner_address,
        )

    def publish_slices(
        self, windows: Sequence[Tuple[int, int]]
    ) -> List[ObjectRef]:
        """Publish one hardlinked ref per row window.

        Each ref owns its own directory entry (tmpfs hardlink), so the
        per-ref ``free()`` semantics are unchanged and the physical pages
        are reclaimed when the last window is freed — a filesystem-level
        refcount standing in for Ray's distributed ref counting.
        """
        assert not self._published, "already published"
        seg_dir = os.path.dirname(self._tmp)  # shm or spill, same fs as tmp
        refs: List[ObjectRef] = []
        try:
            for start, stop in windows:
                link_id = self._store._new_object_id()
                os.link(self._tmp, os.path.join(seg_dir, link_id))
                refs.append(
                    ObjectRef(
                        object_id=link_id,
                        nbytes=self.nbytes,
                        session=self._store.session,
                        owner=self._store.owner_address,
                        rows=(int(start), int(stop)),
                    )
                )
        except BaseException:
            # Partial failure (e.g. ENOSPC mid-loop): reclaim the links
            # already created — no ref for them ever reaches a caller, and
            # each pins the whole segment.
            for ref in refs:
                try:
                    os.unlink(os.path.join(seg_dir, ref.object_id))
                except FileNotFoundError:
                    pass
            raise
        os.unlink(self._tmp)
        self._published = True
        # One ledger segment carrying every link id: the bytes stay
        # resident until the LAST window's link is freed (the fold
        # mirrors the filesystem refcount).
        _ledger_note(
            "create", self.object_id, self.nbytes,
            self._ledger_tier or self._store.tier_of(self._tmp),
            ids=[r.object_id for r in refs],
        )
        return refs

    def abort(self) -> None:
        if not self._published:
            try:
                os.unlink(self._tmp)
            except FileNotFoundError:
                pass
            self._published = True


def map_segment_file(path: str, object_id: str = "?") -> ColumnBatch:
    """mmap a published segment file into zero-copy column views."""
    fd = os.open(path, os.O_RDONLY)
    try:
        size = os.fstat(fd).st_size
        mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
    finally:
        os.close(fd)
    magic, meta_len = _HEADER.unpack_from(mm, 0)
    if magic != _MAGIC:
        raise ValueError(f"corrupt object segment {object_id!r}")
    meta = json.loads(bytes(mm[_HEADER.size : _HEADER.size + meta_len]))
    payload_start = _align(_HEADER.size + meta_len)
    cols: Dict[str, np.ndarray] = {}
    for m in meta["columns"]:
        arr = np.frombuffer(
            mm,
            dtype=np.dtype(m["dtype"]),
            count=int(np.prod(m["shape"])) if m["shape"] else 1,
            offset=payload_start + m["offset"],
        ).reshape(m["shape"])
        cols[m["name"]] = arr
    return ColumnBatch(cols, _keepalive=mm, layout=meta.get("layout"))


def serialize_columns(
    columns: Mapping[str, np.ndarray], layout: Optional[dict] = None
) -> bytes:
    """Serialize columns into the segment wire/disk format (used by the
    cluster StoreServer to ship a ref's row window without the rest of the
    segment). ``layout`` rides in the meta blob, so a fetched copy of a
    device-batch segment lands on the reader already in staging layout."""
    cols = {k: np.ascontiguousarray(v) for k, v in columns.items()}
    meta, meta_blob, payload_start, total = _plan_layout(
        {k: (v.shape, v.dtype) for k, v in cols.items()}, layout=layout
    )
    out = bytearray(total)
    out[: _HEADER.size] = _HEADER.pack(_MAGIC, len(meta_blob))
    out[_HEADER.size : _HEADER.size + len(meta_blob)] = meta_blob
    view = np.frombuffer(out, dtype=np.uint8)
    for m, arr in zip(meta, cols.values()):
        start = payload_start + m["offset"]
        view[start : start + arr.nbytes] = arr.reshape(-1).view(np.uint8)
    return bytes(out)


_PAD64 = bytes(_ALIGN)


def serialize_columns_vectored(
    columns: Mapping[str, np.ndarray], layout: Optional[dict] = None
) -> Tuple[int, List]:
    """``(total_bytes, buffers)`` for the segment wire/disk format WITHOUT
    materializing the payload: the buffers are the source column views
    themselves (plus a small header and sub-64-byte alignment pads), byte-
    identical when concatenated to :func:`serialize_columns`'s output.
    This is the zero-copy TCP plane's scatter-gather list — a window
    fetch streams straight out of the owner's mmapped segment instead of
    paying a full ``bytearray`` build plus a ``bytes()`` copy plus a
    payload pickle. Callers must keep the source mapping alive until the
    buffers are consumed."""
    cols = {
        k: (v if v.flags.c_contiguous else np.ascontiguousarray(v))
        for k, v in columns.items()
    }
    meta, meta_blob, payload_start, total = _plan_layout(
        {k: (v.shape, v.dtype) for k, v in cols.items()}, layout=layout
    )
    head = bytearray(payload_start)
    head[: _HEADER.size] = _HEADER.pack(_MAGIC, len(meta_blob))
    head[_HEADER.size : _HEADER.size + len(meta_blob)] = meta_blob
    bufs: List = [head]
    pos = payload_start
    for m, arr in zip(meta, cols.values()):
        target = payload_start + m["offset"]
        if target > pos:  # inter-column alignment gap, always < 64 B
            bufs.append(_PAD64[: target - pos])
            pos = target
        if arr.nbytes:
            bufs.append(memoryview(arr).cast("B"))
            pos += arr.nbytes
    if total > pos:  # trailing alignment pad
        bufs.append(_PAD64[: total - pos])
    return total, bufs


@dataclass
class StoreStats:
    num_objects: int = 0
    total_bytes: int = 0
    spill_bytes: int = 0  # portion of total_bytes living on disk, not shm


class ObjectStore:
    """Session-scoped object store over ``/dev/shm`` files.

    All objects created under one session share an id prefix so that
    ``cleanup()`` can reclaim everything the session produced, and
    ``store_stats()`` can report utilization for just this session.
    """

    def __init__(self, session: str, shm_dir: Optional[str] = None):
        self.session = session
        self.shm_dir = shm_dir or _default_shm_dir()
        # RSDL_SHM_DIR may name a fresh subdirectory (e.g. per-session
        # dirs isolating same-machine multi-host tests).
        os.makedirs(self.shm_dir, exist_ok=True)
        # Capacity budgeting (SURVEY §7 hard-part 4): shared-memory
        # residency for this session is capped; segments beyond the budget
        # are created in (or fetched to) the disk-backed spill dir instead
        # of dying on ENOSPC. Admission stays non-blocking, so the pipeline
        # cannot deadlock on its own backpressure.
        self.capacity_bytes: Optional[int] = _default_capacity_bytes(
            self.shm_dir
        )
        self.spill_dir = _default_spill_dir()
        if os.path.realpath(self.spill_dir) == os.path.realpath(self.shm_dir):
            # A spill dir on tmpfs defeats the point; disable budgeting.
            self.capacity_bytes = None
        # Cluster-mode hooks, installed by runtime.init when joined to a
        # cluster: refs minted here get stamped with owner_address; misses
        # on foreign refs go through remote_fetch; frees forward to owners.
        self.owner_address: Optional[Tuple] = None
        self.remote_fetch = None  # Callable[[ObjectRef], bytes]
        # Zero-copy fetch hook (RSDL_TCP_ZEROCOPY): pulls the ref's bytes
        # straight into a buffer the allocator returns (an mmapped cache
        # file) — Callable[[ObjectRef, Callable[[int], buffer]], None].
        self.remote_fetch_into = None
        self.remote_free = None  # Callable[[ObjectRef], None]
        self._foreign: set = set()  # locally cached foreign object ids
        # Grows to the largest max_parallel any prefetch call asks for.
        self._prefetch_pool = GrowingThreadPool("store-prefetch")
        # Cache names freed in this process: a prefetch thread whose fetch
        # lands AFTER the consumer already freed the ref must discard its
        # result instead of orphaning a cache file (object ids are never
        # reused, so entries can only ever refer to dead refs). Bounded in
        # free()/drop_cache: entries only matter while a prefetch could
        # still be in flight (seconds), so the set is cleared when it
        # outgrows any plausible in-flight window.
        self._freed_caches: set = set()
        # Capacity-check cache: _shm_session_bytes listdir+stats the whole
        # shm dir, so the result is reused for a short TTL with creations
        # since the last scan added on top (frees within the TTL leave the
        # estimate high — the conservative direction: spill a hair early).
        self._shm_scan_base = 0
        self._shm_scan_adjust = 0
        self._shm_scan_ts = float("-inf")

    # -- write path ---------------------------------------------------------

    def _new_object_id(self) -> str:
        return f"{self.session}-{secrets.token_hex(8)}"

    def _shm_session_bytes(self) -> int:
        """This session's shared-memory residency (inode-deduped; spilled
        segments excluded), cached for a short TTL so the data path is not
        O(resident objects) per placement decision."""
        import time as _time

        now = _time.monotonic()
        if now - self._shm_scan_ts <= 0.2:
            return self._shm_scan_base + self._shm_scan_adjust
        self._shm_scan_base = self._scan_shm_session_bytes()
        self._shm_scan_adjust = 0
        self._shm_scan_ts = now
        return self._shm_scan_base

    def _scan_shm_session_bytes(self) -> int:
        """The uncached scan. The filesystem is the shared truth across the
        session's processes — worker pools race this check and can
        overshoot by one segment each, which the budget's slack absorbs."""
        prefix = f"{self.session}-"
        total = 0
        seen = set()
        try:
            names = os.listdir(self.shm_dir)
        except FileNotFoundError:
            return 0
        for name in names:
            if name.startswith(prefix):
                try:
                    st = os.stat(os.path.join(self.shm_dir, name))
                except FileNotFoundError:
                    continue
                if st.st_ino not in seen:
                    seen.add(st.st_ino)
                    total += st.st_size
        return total

    def _placement_dir(self, nbytes: int) -> str:
        """Where a new segment of ``nbytes`` goes: shm while the session is
        under budget, else the spill dir."""
        if (
            self.capacity_bytes is not None
            and nbytes + self._shm_session_bytes() > self.capacity_bytes
        ):
            os.makedirs(self.spill_dir, exist_ok=True)
            _emit_spill_event(nbytes)
            return self.spill_dir
        # Count the imminent write against the cached estimate so rapid
        # placements between scans see each other.
        self._shm_scan_adjust += nbytes
        return self.shm_dir

    def tier_of(self, path: str) -> str:
        """Which capacity tier a segment path lives on — ``spill`` for
        the disk spill dir, ``shm`` otherwise (the ledger vocabulary)."""
        return (
            "spill"
            if os.path.dirname(path) == self.spill_dir
            else "shm"
        )

    def _find_segment(self, object_id: str) -> Optional[str]:
        """Resolve a local object id to its segment path (shm, then spill)."""
        path = os.path.join(self.shm_dir, object_id)
        if os.path.exists(path):
            return path
        spath = os.path.join(self.spill_dir, object_id)
        if os.path.exists(spath):
            return spath
        return None

    def create_columns(
        self,
        spec: Mapping[str, Tuple[Tuple[int, ...], "np.dtype"]],
        layout: Optional[dict] = None,
        ledger_tier: Optional[str] = None,
    ) -> "PendingColumns":
        """Allocate an unpublished segment and return writable column views.

        The zero-extra-copy write path: producers (shuffle map/reduce
        kernels) scatter/gather rows *directly into shared memory* instead
        of building host arrays and copying them in via :meth:`put_columns`
        — one full memory pass saved per stage. Fill the views, then
        ``seal()`` (one ref) or ``publish_slices()`` (hardlinked row-window
        refs). ``layout`` stamps the segment with a staging-layout
        descriptor (see :func:`_plan_layout`). ``ledger_tier`` overrides
        the capacity-ledger tier the publish records under (the shared
        decode-cache tier accounts as ``cache``; physical placement is
        unchanged).
        """
        if faults.enabled():
            faults.fire("store.put")
        meta, meta_blob, payload_start, total = _plan_layout(
            spec, layout=layout
        )

        object_id = self._new_object_id()
        path = os.path.join(self._placement_dir(total), object_id)
        tmp = path + ".tmp"
        fd = os.open(tmp, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, max(total, 1))
            mm = mmap.mmap(fd, max(total, 1))
        finally:
            os.close(fd)
        mm[: _HEADER.size] = _HEADER.pack(_MAGIC, len(meta_blob))
        mm[_HEADER.size : _HEADER.size + len(meta_blob)] = meta_blob
        views: Dict[str, np.ndarray] = {}
        for m in meta:
            views[m["name"]] = np.frombuffer(
                mm,
                dtype=np.dtype(m["dtype"]),
                count=int(np.prod(m["shape"], dtype=np.int64)),
                offset=payload_start + m["offset"],
            ).reshape(m["shape"])
        return PendingColumns(
            self, object_id, tmp, path, total, mm, views,
            ledger_tier=ledger_tier,
        )

    def put_columns(self, columns: Mapping[str, np.ndarray]) -> ObjectRef:
        """Write a columnar batch as one aligned segment; return its ref.
        The segment is reclaimed if the copy-in fails mid-way (``abort``
        is a no-op after a successful ``seal``)."""
        cols = {k: np.ascontiguousarray(v) for k, v in columns.items()}
        pending = self.create_columns(
            {k: (v.shape, v.dtype) for k, v in cols.items()}
        )
        try:
            for k, v in cols.items():
                pending.columns[k][...] = v
            return pending.seal()
        finally:
            pending.abort()

    def put_bytes(self, data: bytes) -> ObjectRef:
        return self.put_columns({"__bytes__": np.frombuffer(data, np.uint8)})

    # -- read path ----------------------------------------------------------

    def get_columns(self, ref: ObjectRef) -> ColumnBatch:
        """Open a segment and return zero-copy column views onto it.

        ``ref.rows`` windows slice the views (still zero-copy). When the
        segment is not on this host and the ref names a remote owner, just
        the ref's window is pulled over DCN once and cached as a local
        standalone segment; subsequent gets map the cache (the plasma
        cross-node transfer analog, SURVEY §2b).

        A missing or unreadable segment raises :class:`ObjectLostError`
        (carrying the object id) so callers with lineage — the shuffle
        driver — can re-materialize instead of failing the run."""
        if faults.enabled():
            kind = faults.should_fire("store.get")
            if kind == "lost":
                raise ObjectLostError(ref.object_id, "injected fault")
            if kind == "corrupt":
                raise ObjectCorruptError(ref.object_id, "injected fault")
        path = self._find_segment(ref.object_id)
        rows = ref.rows
        if path is None and self._is_foreign(ref):
            # Window refs cache under a window-suffixed name (the fetched
            # segment holds only the window; the name keeps that fact
            # consistent across processes on this host).
            cache_path = self._find_cache(ref)
            if cache_path is None:
                cache_path = self._cache_path(ref)
                self._materialize_remote(ref, cache_path)
            path = cache_path
            rows = None
        elif path is None:
            raise ObjectLostError(ref.object_id, "no local segment")
        try:
            batch = self._map_segment(path, ref.object_id)
        except FileNotFoundError:
            # Unlinked between the existence check and the mmap.
            raise ObjectLostError(
                ref.object_id, "segment unlinked mid-read"
            ) from None
        except ValueError as exc:
            raise ObjectCorruptError(ref.object_id, str(exc)) from exc
        # Read-tracking ledger op (ISSUE 11): every successful read
        # stamps the segment's last access — the signal last-touch
        # eviction orders cold epochs by. The AUTHORITATIVE id
        # (ref.object_id, a real ledger link id) gets the touch, so a
        # foreign window read warms the owner's segment, not just this
        # host's cache file; the cache file's own ledger entry (keyed
        # by its window-suffixed name from the fetch op) is touched
        # too when it differs. Rate-limited per id inside
        # capacity.touch; one cached boolean when metrics are off.
        self._ledger_touch(ref.object_id)
        base = os.path.basename(path)
        if base != ref.object_id:
            self._ledger_touch(base)
        if rows is not None:
            batch = batch.slice(rows[0], rows[1])
        return batch

    @staticmethod
    def _ledger_touch(object_id: str) -> None:
        if not _metrics.enabled():
            return
        try:
            from ray_shuffling_data_loader_tpu.telemetry import capacity

            capacity.touch(object_id)
        except Exception:
            pass

    def _is_foreign(self, ref: ObjectRef) -> bool:
        return (
            ref.owner is not None
            and tuple(ref.owner) != self.owner_address
            and self.remote_fetch is not None
        )

    def is_foreign(self, ref: ObjectRef) -> bool:
        """Does reading this ref require (or did it require) a cross-host
        fetch? The shuffle reduce uses this to decide whether the
        overlapped fetch/gather pipeline buys anything."""
        return self._is_foreign(ref)

    def needs_fetch(self, ref: ObjectRef) -> bool:
        """Would reading this ref RIGHT NOW pay a cross-host fetch —
        foreign, not yet cached locally, and not directly mappable
        (sessions sharing one /dev/shm)? The overlap auto-policy keys on
        this instead of :meth:`is_foreign`: a retried reduce whose first
        attempt already cached its windows has no fetch latency to hide
        and should keep the fused gather."""
        return (
            self._is_foreign(ref)
            and self._find_cache(ref) is None
            and self._find_segment(ref.object_id) is None
        )

    def _cache_name(self, ref: ObjectRef) -> str:
        # Caches carry the READER session's prefix (not the producer's):
        # every process sharing this session computes the same name, and
        # the session's ordinary prefix cleanup reclaims caches that pool
        # workers materialized and a failed task never dropped.
        name = f"{self.session}-cache-{ref.object_id}"
        if ref.rows is not None:
            name = f"{name}+w{ref.rows[0]}-{ref.rows[1]}"
        return name

    def _cache_path(self, ref: ObjectRef) -> str:
        """Placement for a NEW cache file (capacity-aware like any other
        segment; ``ref.nbytes`` is the whole-segment size, a safe
        overestimate for window refs)."""
        return os.path.join(
            self._placement_dir(ref.nbytes), self._cache_name(ref)
        )

    def _find_cache(self, ref: ObjectRef) -> Optional[str]:
        """An existing cache of ``ref`` (shm, then spill), or None."""
        name = self._cache_name(ref)
        for d in (self.shm_dir, self.spill_dir):
            path = os.path.join(d, name)
            if os.path.exists(path):
                return path
        return None

    def _map_segment(self, path: str, object_id: str) -> ColumnBatch:
        return map_segment_file(path, object_id)

    def get_bytes(self, ref: ObjectRef) -> bytes:
        return self.get_columns(ref)["__bytes__"].tobytes()

    def prefetch(self, refs, max_parallel: Optional[int] = None) -> List:
        """Start pulling foreign refs' windows into the local cache on
        background threads; returns immediately with the fetch futures.
        ``max_parallel`` defaults to :func:`fetch_window_depth` (the
        ``RSDL_FETCH_WINDOW_DEPTH`` knob; this delivery-plane path
        defaults to 8 when the env is unset, the overlapped reduce to
        4). The pool is process-lifetime but its width follows the
        LARGEST ``max_parallel`` seen: a later call asking for more
        parallelism grows the pool (by replacement — in-flight fetches
        on the old pool complete normally) instead of silently
        serializing its extra fetches behind the first caller's width.

        The ``ray.wait(fetch_local=True)`` analog (reference
        ``dataset.py:132-137``): the reference pulls ALL pending reducer
        outputs to the local node while the trainer consumes the first.
        Kicking this off as soon as a queue ``get_batch`` returns its refs
        overlaps every DCN hop with consumption, instead of stalling the
        iterator on each foreign ref in turn.

        Failures are swallowed here — the consuming ``get_columns`` retries
        the fetch synchronously and is the place errors surface.
        """
        foreign = [
            r
            for r in refs
            if isinstance(r, ObjectRef)
            and self._is_foreign(r)
            and self._find_cache(r) is None
            # Same-filesystem shortcut parity with get_columns: a
            # "foreign" segment that is directly mappable here (sessions
            # sharing one /dev/shm) needs no pull at all.
            and self._find_segment(r.object_id) is None
        ]
        if not foreign:
            return []
        # An explicit prefetch REQUEST supersedes any free/drop_cache
        # tombstone for these refs: the tombstones exist to discard a
        # late-landing fetch from BEFORE the free, but a retried reduce
        # (or a second bench plane) legitimately re-reads dropped
        # windows, and a permanent tombstone would silently no-op its
        # prefetches forever (degrading the retry to serial synchronous
        # fetches). A still-in-flight old fetch that now lands is
        # wanted again — object ids are immutable content, so the copy
        # is identical either way.
        for ref in foreign:
            self._freed_caches.discard(self._cache_name(ref))
        if max_parallel is None:
            max_parallel = fetch_window_depth(default=8)
        # Grow-on-demand (a first narrow caller must not serialize a
        # later wider one's fetches); a ref whose fetch is in flight on
        # a retired pool is at worst one redundant pull — object ids
        # are immutable content and _pull re-checks the cache.
        pool = self._prefetch_pool.ensure(max_parallel)

        def _pull(ref: ObjectRef) -> None:
            name = self._cache_name(ref)
            if name in self._freed_caches or self._find_cache(ref) is not None:
                return
            try:
                self._materialize_remote(ref, self._cache_path(ref))
            except Exception:
                return
            if name in self._freed_caches:
                # The consumer freed the ref while the fetch was in flight
                # (it cache-missed and fetched synchronously); reclaim the
                # now-orphaned copy.
                cache = self._find_cache(ref)
                if cache is not None:
                    try:
                        os.unlink(cache)
                    except FileNotFoundError:
                        pass
                self._foreign.discard(name)

        return [pool.submit(_pull, r) for r in foreign]

    def _materialize_remote(self, ref: ObjectRef, path: str) -> None:
        """Pull a foreign segment's bytes (just the ref's window) and
        publish them locally.

        With the zero-copy plane on (``RSDL_TCP_ZEROCOPY`` + cluster
        wiring), the peer's vectored reply lands via ``recv_into``
        directly in the mmapped destination file — no intermediate
        ``bytes``, no payload pickle on either side. Otherwise the legacy
        path fetches one bytes blob and writes it out.

        Concurrent readers may race here; both write a private tmp file and
        the renames are idempotent (same content), so the winner is
        irrelevant."""
        t0 = time.perf_counter() if _metrics.enabled() else None
        tmp = f"{path}.fetch-{os.getpid()}-{secrets.token_hex(4)}"
        zerocopy = (
            self.remote_fetch_into is not None
            and _transport.zerocopy_enabled()
        )
        nbytes = 0
        if zerocopy:
            holder: Dict[str, mmap.mmap] = {}

            def _alloc(n: int):
                fd = os.open(tmp, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
                try:
                    os.ftruncate(fd, max(n, 1))
                    # MAP_POPULATE prefaults the whole window in one
                    # kernel sweep: without it every 4 KB page of the
                    # fresh cache file faults individually under
                    # recv_into — measured as a large share of the
                    # per-window fetch cost (BENCHLOG r7).
                    flags = mmap.MAP_SHARED | getattr(
                        mmap, "MAP_POPULATE", 0
                    )
                    mm = mmap.mmap(fd, max(n, 1), flags=flags)
                finally:
                    os.close(fd)
                holder["mm"] = mm
                holder["n"] = n
                return mm

            try:
                self.remote_fetch_into(ref, _alloc)
                nbytes = holder.get("n", 0)
            except BaseException:
                try:
                    os.unlink(tmp)
                except FileNotFoundError:
                    pass
                raise
            finally:
                mm = holder.pop("mm", None)
                if mm is not None:
                    try:
                        mm.close()
                    except BufferError:
                        # Belt-and-braces: should be unreachable now that
                        # the transport releases its recv views on every
                        # exit, but a still-exported view must never
                        # replace the recoverable fetch error (the
                        # retry/lineage ladder keys on it); GC closes the
                        # mmap once the exception's traceback is dropped.
                        pass
        else:
            data = self.remote_fetch(ref)
            nbytes = len(data)
            with open(tmp, "wb") as f:
                f.write(data)
        os.rename(tmp, path)
        self._foreign.add(os.path.basename(path))
        _ledger_note(
            "fetch", os.path.basename(path), nbytes, self.tier_of(path)
        )
        if t0 is not None:
            # Per-window DCN latency + bytes — the TCP plane's primary
            # observability (docs/observability.md); labels carry which
            # framing served the window and how many striped streams
            # (RSDL_TCP_STREAMS; always 1 on the legacy pickle path).
            try:
                zc = "1" if zerocopy else "0"
                streams = str(_transport.tcp_streams()) if zerocopy else "1"
                _metrics.registry.histogram(
                    "store.fetch_window_seconds", zerocopy=zc,
                    streams=streams,
                ).observe(time.perf_counter() - t0)
                _metrics.registry.counter(
                    "store.fetch_window_bytes", zerocopy=zc,
                    streams=streams,
                ).inc(float(nbytes))
            except Exception:
                pass

    # -- lifecycle ----------------------------------------------------------

    def free(self, refs) -> None:
        if isinstance(refs, ObjectRef):
            refs = [refs]
        for ref in refs:
            if self._is_foreign(ref):
                # Drop the local window cache and release the authoritative
                # copy (the owner's hardlink) — the physical segment dies
                # when its last window's link is freed. Mark first so an
                # in-flight prefetch landing after this unlink cleans up.
                if len(self._freed_caches) > 8192:
                    # Entries only matter while a prefetch is in flight
                    # (seconds); cap the set instead of leaking for the
                    # process lifetime.
                    self._freed_caches.clear()
                self._freed_caches.add(self._cache_name(ref))
                cache = self._find_cache(ref)
                if cache is not None:
                    try:
                        os.unlink(cache)
                    except FileNotFoundError:
                        pass
                    _ledger_note("delete", self._cache_name(ref))
                self._foreign.discard(self._cache_name(ref))
                if self.remote_free is not None:
                    # The owner's store frees the authoritative segment
                    # in its own process — and logs its own ledger op.
                    self.remote_free(ref)
                continue
            path = self._find_segment(ref.object_id)
            if path is not None:
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
                _ledger_note("delete", ref.object_id)

    # -- tiered movement (ISSUE 10: the elastic evictor's actuators) --------

    def _segment_links(self, ids) -> Dict[str, str]:
        """``{name: path}`` for every link name of one segment that is
        currently resolvable (shm first, then spill)."""
        if isinstance(ids, str):
            ids = [ids]
        out: Dict[str, str] = {}
        for name in ids:
            path = self._find_segment(name)
            if path is not None:
                out[name] = path
        return out

    def _move_tier(self, ids, dst_dir: str, tier: str) -> int:
        """Move ALL link names of one physical segment to ``dst_dir``
        atomically-per-link: copy the inode once, hardlink the remaining
        names against the copy (same filesystem), rename over nothing,
        then unlink the sources. Readers racing the move either still
        map the old inode (their mmap survives the unlink) or re-resolve
        via ``_find_segment``, which checks both tiers. Returns the
        bytes moved (0 if the segment vanished or already lives there).
        """
        links = self._segment_links(ids)
        if not links:
            return 0
        first = next(iter(links.values()))
        if os.path.dirname(first) == dst_dir:
            return 0  # already on the target tier
        os.makedirs(dst_dir, exist_ok=True)
        names = list(links)
        primary = names[0]
        # ".tmp" suffix: a crashed move must not leave a file that
        # store_stats or a drain's list_segments would mistake for a
        # published segment.
        tmp = os.path.join(
            dst_dir,
            f"{primary}.move-{os.getpid()}-{secrets.token_hex(4)}.tmp",
        )
        try:
            nbytes = os.path.getsize(links[primary])
            with open(links[primary], "rb") as src, open(tmp, "wb") as dst:
                import shutil as _shutil

                _shutil.copyfileobj(src, dst, length=1 << 20)
            os.rename(tmp, os.path.join(dst_dir, primary))
        except OSError:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
            return 0
        for name in names[1:]:
            try:
                os.link(
                    os.path.join(dst_dir, primary),
                    os.path.join(dst_dir, name),
                )
            except FileExistsError:
                pass
            except OSError:
                # Partial link failure: roll the whole move back rather
                # than strand some names on each tier.
                for done in names[: names.index(name) + 1]:
                    try:
                        os.unlink(os.path.join(dst_dir, done))
                    except FileNotFoundError:
                        pass
                return 0
        for name, path in links.items():
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        # Keep the cached shm-residency estimate honest between scans:
        # a demotion frees budgeted shm immediately, a promotion fills
        # it (without this, a burst of promotes inside the scan TTL
        # would each see the pre-burst residency and over-admit).
        if tier == "spill":
            self._shm_scan_adjust -= nbytes
        else:
            self._shm_scan_adjust += nbytes
        _ledger_note("transition", primary, nbytes, tier)
        _metrics.safe_inc(
            "store.tier_moved_bytes_total", float(nbytes), tier=tier
        )
        return nbytes

    def demote(self, ids) -> int:
        """Demote one segment (every hardlinked name in ``ids``) from
        shm to the disk spill tier — the evictor's shm-pressure
        actuator. The segment stays readable in place (``_find_segment``
        and the StoreServer probe both tiers); only the tier moves.
        Emits the capacity-ledger ``transition`` op. Returns bytes
        moved."""
        return self._move_tier(ids, self.spill_dir, "spill")

    def promote(self, ids) -> int:
        """Promote a spilled segment back to shm — only when the move
        fits the session budget (a promote must never trigger the very
        pressure the evictor exists to relieve). Returns bytes moved."""
        links = self._segment_links(ids)
        if not links:
            return 0
        nbytes = 0
        try:
            nbytes = os.path.getsize(next(iter(links.values())))
        except OSError:
            return 0
        if (
            self.capacity_bytes is not None
            and nbytes + self._shm_session_bytes() > self.capacity_bytes
        ):
            return 0
        return self._move_tier(ids, self.shm_dir, "shm")

    def drop_segments(self, ids) -> int:
        """Unconditionally drop a segment (every link name) from
        whichever tier holds it — the evictor's last rung. Readers that
        later miss it raise :class:`ObjectLostError`, which the shuffle
        driver's lineage machinery re-materializes (PR 3). Returns
        bytes dropped."""
        links = self._segment_links(ids)
        if not links:
            return 0
        nbytes = 0
        try:
            nbytes = os.path.getsize(next(iter(links.values())))
        except OSError:
            pass
        for name, path in links.items():
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            _ledger_note("delete", name)
        return nbytes

    def drop_cache(self, refs) -> None:
        """Release only this host's fetched copy of foreign refs — the
        authoritative segments survive, so a task calling this remains
        retryable (unlike :meth:`free`)."""
        if isinstance(refs, ObjectRef):
            refs = [refs]
        for ref in refs:
            if not self._is_foreign(ref):
                continue
            if len(self._freed_caches) > 8192:
                self._freed_caches.clear()
            self._freed_caches.add(self._cache_name(ref))
            cache = self._find_cache(ref)
            if cache is not None:
                try:
                    os.unlink(cache)
                except FileNotFoundError:
                    pass
                _ledger_note("delete", self._cache_name(ref))
            self._foreign.discard(self._cache_name(ref))

    def exists(self, ref: ObjectRef) -> bool:
        return self._find_segment(ref.object_id) is not None

    def store_stats(self) -> StoreStats:
        """Utilization for this session (replaces the reference's raylet
        ``FormatGlobalMemoryInfo`` probe, ``stats.py:675-683``).

        Hardlinked slice refs share pages; bytes are counted once per inode
        while every ref still counts as an object. Spilled segments are
        included, with their share reported in ``spill_bytes``."""
        stats = StoreStats()
        prefix = f"{self.session}-"
        seen_inodes = set()
        for dirpath, is_spill in (
            (self.shm_dir, False),
            (self.spill_dir, True),
        ):
            try:
                names = os.listdir(dirpath)
            except FileNotFoundError:
                continue
            for name in names:
                if name.startswith(prefix) and not name.endswith(".tmp"):
                    try:
                        st = os.stat(os.path.join(dirpath, name))
                    except FileNotFoundError:
                        continue
                    stats.num_objects += 1
                    if st.st_ino not in seen_inodes:
                        seen_inodes.add(st.st_ino)
                        stats.total_bytes += st.st_size
                        if is_spill:
                            stats.spill_bytes += st.st_size
        return stats

    def cleanup(
        self, session: Optional[str] = None, keep=()
    ) -> None:
        """Reclaim every segment a session produced. Defaults to THIS
        session; passing another session id sweeps a *superseded* one —
        a resumed run (runtime/journal.py) re-attaches the preempted
        driver's surviving segments and owns their reclamation, since
        the session that created them can no longer clean up. ``keep``
        names object ids to spare (segments the resumed run re-attached
        and promoted into the shared decode-cache tier must outlive
        their creating session)."""
        own = session is None or session == self.session
        session = session or self.session
        keep = frozenset(keep)
        if own and not keep:
            # The blanket op: the ledger fold drops everything live.
            _ledger_note("cleanup", session)
        prefix = f"{session}-"
        for dirpath in (self.shm_dir, self.spill_dir):
            try:
                names = os.listdir(dirpath)
            except FileNotFoundError:
                continue
            for name in names:
                if name.startswith(prefix):
                    if name in keep:
                        continue
                    try:
                        os.unlink(os.path.join(dirpath, name))
                    except FileNotFoundError:
                        pass
                    if not own or keep:
                        # Per-name deletes, not the blanket cleanup op:
                        # sweeping a superseded session must not zero
                        # the CURRENT session's live fold (and a kept
                        # segment must stay live in it).
                        _ledger_note("delete", name)
        if own:
            self._foreign.clear()
