"""Resolved shuffle plan: the cost-based planner's output (ISSUE 20).

``analysis/planner.py`` is the cost model; this module is the *shape*
of what it produces and the driver-side registry the run ledger
harvests. A :class:`ResolvedPlan` carries one :class:`PlanTerm` per
planner-owned knob (``TERM_KNOBS`` in the planner names the mapping,
cross-checked against ``analysis/knob_registry.py`` by ``rsdl_lint``):
the effective value, where it came from (``env`` beats ``planned`` —
an operator-set knob is a pin the planner must never override), and
the one-line cost-model justification that lands in the
``plan.chosen`` event and the run-ledger record.

Gate: ``RSDL_PLAN=auto|on`` (checked by ``shuffle.py`` *before* any
import of this plane — zero-overhead off, fresh-interpreter-proven in
``tests/test_planner.py``; both this module and ``analysis.planner``
are ``GATED_PLANES`` entries).

Split from the planner so the ledger side (``telemetry/runledger.py``
reads :func:`current_terms` / :func:`effective_env` through
``sys.modules``) never has to touch the cost model or its footer-stats
imports.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

ENV_PLAN = "RSDL_PLAN"

# Sources, in override order: an env-set knob pins its term for the
# whole run ("env"); otherwise the compile-time cost model decides
# ("planned") and the epoch-boundary re-planner may adjust the
# mutable subset ("replanned").
SOURCE_ENV = "env"
SOURCE_PLANNED = "planned"
SOURCE_REPLANNED = "replanned"

# Terms the between-epoch re-planner may adjust mid-run: all are
# delivered-stream-invariant (window depth and thread counts change
# scheduling only; selective changes the *schedule*, and the stream is
# bit-identical across schedules — tested since ISSUE 11/12).
MUTABLE_TERMS = (
    "fetch_window_depth",
    "decode_rowgroup_threads",
    "selective",
)


@dataclass
class PlanTerm:
    """One planner decision: a knob's effective value + provenance."""

    name: str
    knob: str
    value: Any
    source: str
    why: str = ""

    def as_dict(self) -> Dict[str, Any]:
        value = self.value
        if isinstance(value, tuple):
            value = list(value)
        return {
            "value": value,
            "source": self.source,
            "knob": self.knob,
            "why": self.why,
        }


@dataclass
class ResolvedPlan:
    """Every knob the planner owns, resolved once driver-side.

    ``plan`` is the ``(family, granularity)`` spec threaded through
    ``_file_assignment`` (the seeded-assignment seam every schedule
    shares); ``projection`` feeds ``_pushdown_columns``; the rest ride
    the ``knobs`` task argument into stage tasks — explicit arguments,
    not env, because workers' env snapshots date from pool spawn (the
    PR 12 lesson).
    """

    plan: Tuple[str, int]
    projection: Optional[List[str]]
    terms: Dict[str, PlanTerm]
    model: Dict[str, Any] = field(default_factory=dict)
    replans: int = 0

    def term_value(self, name: str) -> Any:
        t = self.terms.get(name)
        return t.value if t is not None else None

    def task_knobs(self) -> Dict[str, Any]:
        """The plain-dict subset stage tasks consume (picklable, no
        import of this module on workers): effective decode/fetch/
        kernel-thread values plus the selective decision."""
        out: Dict[str, Any] = {}
        for name in (
            "decode_rowgroup_threads",
            "fetch_window_depth",
            "native_threads",
            "selective",
        ):
            value = self.term_value(name)
            if value is not None:
                out[name] = value
        return out

    def terms_dict(self) -> Dict[str, Dict[str, Any]]:
        """JSON-ready view of every term — the ``plan.chosen`` event
        payload and the run ledger's ``plan_terms`` section."""
        return {name: t.as_dict() for name, t in sorted(self.terms.items())}

    def effective_env(self) -> Dict[str, str]:
        """{knob name: effective value} for terms with a concrete
        scalar/label value — what the ledger knob snapshot overlays so
        two runs with identical env but different planner decisions
        stop looking identical (ISSUE 20 bugfix)."""
        out: Dict[str, str] = {}
        for t in self.terms.values():
            if t.value is None:
                continue
            if t.name == "plan":
                family, granularity = self.plan
                out[t.knob] = (
                    family if family == "rowwise"
                    else f"block:{granularity}"
                )
            elif t.name == "selective":
                out[t.knob] = "on" if t.value else "off"
            elif t.name == "columns":
                out[t.knob] = "planned:" + ",".join(map(str, t.value))
            else:
                out[t.knob] = str(t.value)
        return out


# -- driver-side current-plan state ------------------------------------------
# One plan per driver run; the run ledger harvests it through
# sys.modules (never importing this plane itself), and _shuffle_impl
# clears it at run end so a later planner-off run in the same process
# cannot inherit stale terms.

_lock = threading.Lock()
_current: Optional[ResolvedPlan] = None


def set_current(rplan: Optional[ResolvedPlan]) -> None:
    global _current
    with _lock:
        _current = rplan


def current() -> Optional[ResolvedPlan]:
    with _lock:
        return _current


def current_terms() -> Optional[Dict[str, Dict[str, Any]]]:
    rplan = current()
    if rplan is None:
        return None
    terms = rplan.terms_dict()
    if rplan.replans:
        terms["_replans"] = {"value": rplan.replans}
    return terms


def effective_env() -> Dict[str, str]:
    rplan = current()
    return rplan.effective_env() if rplan is not None else {}
