"""Durable epoch-state plane: the driver-side write-ahead journal.

Every recovery path before this PR (lineage re-materialization, drain
re-homing, consumer-side ``BatchCursor`` resume) assumed the *driver*
survives — its in-flight epoch-window state (plan seed, per-stage
progress, queue delivery cursors, audit partials) lived only in memory,
so a preempted driver lost the window and the run had to start over.
This module makes that state durable, which turns preemption into a
pause (reproducible-pipelines paper, PAPERS.md):

* **Journal** (``RSDL_JOURNAL=<dir>``): one append-only NDJSON file per
  run, published atomically (the run-identity header is written to a
  hidden ``.tmp`` name, fsynced, then renamed — a reader can never see
  a half-written identity) and appended with flush+fsync at the
  existing barriers: task-done (map/reduce futures resolving at the
  driver), the deliver thread (one record per reducer handed to the
  consumer — the queue delivery cursor), and the epoch reconcile
  (per-epoch audit verdict digests, which is what ``tools/replay.py``
  checks against). Write-ahead ordering with the audit spool: the
  deliver thread flushes its audit partials *before* journaling the
  cursor, so a cursor that claims "delivered" implies the delivery
  digest is on disk — a crash between the two merely re-delivers one
  reducer, which the audit reconciler's ``(rank, reducer, offset)``
  dedup and the batch queue's idempotent re-publish both absorb.

* **Resume** (``shuffle(resume_from=)`` / ``RSDL_RESUME=auto``): a
  fresh runtime reconstructs the epoch window from the journal —
  completed epochs are skipped outright, journaled stage results
  re-attach to surviving store segments (validated via
  ``store.exists``; a missing segment degrades to lineage
  re-materialization or full seeded re-execution), and the delivery
  cursor skips already-delivered reducers so the per-rank
  order-sensitive ``delivered_seq`` digest over the whole run is
  bit-identical to an uninterrupted same-seed run.
  ``RSDL_RESUME=redeliver`` keeps the stage re-attach but zeroes the
  delivery cursors — for a consumer that restarts from scratch and
  needs the in-flight epoch's full stream again (re-deliveries are
  audit-invisible: re-executed reducers are bit-identical, so their
  digest records dedup).

* **Suspend** (SIGTERM): with the journal armed, ``shuffle()`` installs
  a SIGTERM handler (main thread only; never installed when
  ``RSDL_JOURNAL`` is unset — the zero-overhead contract) that treats
  the signal as a preemption notice: stop admitting epochs, let each
  deliver thread finish its current reducer (the quiesce window),
  flush every spool, journal the suspension, and exit 0. A suspended
  job is just a paused window; the next ``RSDL_RESUME=auto`` run picks
  it up.

Zero-overhead off: with ``RSDL_JOURNAL`` unset this module is never
imported (``shuffle()`` checks the env var before importing), no file
is created, and no signal handler is installed.

See docs/robustness.md ("Preemption, suspend/resume, and replay") for
the failure model, the journal format, and the digest-equality proof
recipe.
"""

from __future__ import annotations

import json
import logging
import os
import secrets
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

ENV_JOURNAL = "RSDL_JOURNAL"
ENV_RESUME = "RSDL_RESUME"
ENV_SYNC = "RSDL_JOURNAL_SYNC"

_FORMAT_V = 1

# Identity keys that describe *where* the run happened rather than
# *what* it was — a resumed run legitimately differs in all of them
# (fresh session, fresh runtime dir) and the fault schedule may change
# between attempts without changing the delivered stream (recovery is
# exactly-once), so validation skips them. They stay recorded: replay
# needs the fault schedule, re-attach needs the old session.
_INFORMATIONAL = {
    "run_id", "ts", "session", "runtime_dir", "shm_dir",
    "faults", "faults_seed",
    # Service-plane audit lineage (ISSUE 15): the per-registration job
    # ids of every attempt in this run's resume chain. Recorded so a
    # resumed attempt can fold the preempted attempts' job-stamped
    # audit records (ids change across restarts; the stable job NAME
    # above is what's validated).
    "audit_jobs",
}


class RunSuspended(RuntimeError):
    """``shuffle()`` quiesced and journaled the window instead of
    finishing — the in-process analog of the SIGTERM handler's
    exit-0 (tests and embedding drivers catch this; the signal path
    calls ``os._exit(0)`` after the same flushes)."""

    def __init__(self, journal_path: str):
        super().__init__(
            f"run suspended; epoch window journaled at {journal_path} "
            "(resume with RSDL_RESUME=auto)"
        )
        self.journal_path = journal_path


def journal_dir() -> Optional[str]:
    """The journal directory (``RSDL_JOURNAL``), or None when the plane
    is off. Read per call — journal decisions happen a handful of times
    per run, never on the data path."""
    return os.environ.get(ENV_JOURNAL) or None


def enabled() -> bool:
    return journal_dir() is not None


def _sync_enabled() -> bool:
    """fsync-per-append (default on — the WAL contract). ``off`` trades
    durability of the last few records for latency on hosts where the
    journal dir is on slow media; the atomic header publish keeps."""
    return os.environ.get(ENV_SYNC, "").strip().lower() not in (
        "off", "0", "false"
    )


# ---------------------------------------------------------------------------
# Ref serialization (store ObjectRefs <-> JSON)
# ---------------------------------------------------------------------------


def ref_to_json(ref) -> dict:
    out: Dict[str, Any] = {
        "id": ref.object_id,
        "nbytes": int(ref.nbytes),
        "session": ref.session,
    }
    if ref.owner is not None:
        out["owner"] = list(ref.owner)
    if ref.rows is not None:
        out["rows"] = [int(ref.rows[0]), int(ref.rows[1])]
    return out


def ref_from_json(d: dict):
    from ray_shuffling_data_loader_tpu.runtime.store import ObjectRef

    return ObjectRef(
        object_id=str(d["id"]),
        nbytes=int(d.get("nbytes", 0)),
        session=str(d.get("session", "")),
        owner=tuple(d["owner"]) if d.get("owner") else None,
        rows=tuple(d["rows"]) if d.get("rows") else None,
    )


# ---------------------------------------------------------------------------
# Run identity
# ---------------------------------------------------------------------------


def run_identity(
    filenames: List[str],
    num_epochs: int,
    num_reducers: int,
    num_trainers: int,
    seed: int,
    start_epoch: int,
    narrow_to_32: bool,
    plan: str,
    columns: Optional[List[str]],
    device_layout: Optional[dict],
    job: Optional[str] = None,
) -> dict:
    """The run's stream identity — everything that determines the
    delivered batch stream (validated on resume; a mismatch REFUSES to
    resume, like ``BatchCursor.validate``) plus informational context
    (session/runtime/fault schedule — recorded for re-attach and
    replay, excluded from validation)."""
    from ray_shuffling_data_loader_tpu import runtime

    def _abs(f: str) -> str:
        return f if "://" in f else os.path.abspath(f)

    identity: Dict[str, Any] = {
        "v": _FORMAT_V,
        "seed": int(seed),
        "num_epochs": int(num_epochs),
        "num_reducers": int(num_reducers),
        "num_trainers": int(num_trainers),
        "start_epoch": int(start_epoch),
        "filenames": [_abs(f) for f in filenames],
        "narrow_to_32": bool(narrow_to_32),
        "plan": str(plan),
        "columns": list(columns) if columns is not None else None,
        "device_batch": (
            int(device_layout["batch"]) if device_layout else None
        ),
        "device_columns": (
            [str(c) for c in device_layout["columns"]]
            if device_layout
            else None
        ),
        # Informational (not validated):
        "faults": os.environ.get("RSDL_FAULTS") or None,
        "faults_seed": os.environ.get("RSDL_FAULTS_SEED") or None,
    }
    if job is not None:
        # Service plane (ISSUE 15): the job NAME (stable across
        # restarts, unlike the per-registration id) joins the VALIDATED
        # identity — two same-shaped concurrent jobs in one journal dir
        # must each auto-discover their OWN run, never each other's.
        identity["job"] = str(job)
    try:
        ctx = runtime.get_context()
        identity["session"] = ctx.session
        identity["runtime_dir"] = ctx.runtime_dir
        identity["shm_dir"] = ctx.store.shm_dir
    except Exception:
        pass
    return identity


def validate_identity(recorded: dict, current: dict) -> None:
    """Refuse a resume that would change the batch stream: every
    non-informational identity field must match (the driver-side twin
    of ``BatchCursor.validate``)."""
    keys = (set(recorded) | set(current)) - _INFORMATIONAL
    diff = {
        k: (recorded.get(k), current.get(k))
        for k in sorted(keys)
        if recorded.get(k) != current.get(k)
    }
    if diff:
        raise ValueError(
            "journal run identity does not match this shuffle call; "
            f"resuming would change the batch stream: {diff}"
        )


# ---------------------------------------------------------------------------
# Run state (the fold of one journal file)
# ---------------------------------------------------------------------------


class EpochState:
    """One epoch's journaled progress."""

    __slots__ = (
        "epoch", "schedule", "maps", "reduces", "delivered",
        "rank_rows", "sampled", "done",
    )

    def __init__(self, epoch: int):
        self.epoch = int(epoch)
        self.schedule: Optional[str] = None
        # file index -> {"refs": [refdict]|None, "counts": [int]|None,
        #               "cache_ref": refdict|None}
        self.maps: Dict[int, dict] = {}
        # reducer -> [refdict, ...] (one for legacy columnar, up to
        # three for device-direct head/body/tail)
        self.reduces: Dict[int, List[dict]] = {}
        self.delivered = 0  # delivery cursor: reducers 0..delivered-1
        self.rank_rows: Dict[int, int] = {}  # rank -> delivered rows
        self.sampled = 0  # rank-0 audit quality-sample keys taken
        self.done = False


class RunState:
    """The fold of one journal file: identity + per-epoch progress."""

    def __init__(self, path: str, run_id: str, identity: dict):
        self.path = path
        self.run_id = run_id
        self.identity = identity
        self.epochs: Dict[int, EpochState] = {}
        self.done = False
        self.suspended = False
        self.superseded = False
        self.verdicts: Dict[int, dict] = {}

    def epoch(self, e: int) -> EpochState:
        return self.epochs.setdefault(int(e), EpochState(e))

    def resumable(self) -> bool:
        return not self.done and not self.superseded

    def apply(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind == "epoch":
            st = self.epoch(rec["epoch"])
            st.schedule = rec.get("schedule") or st.schedule
        elif kind == "map":
            self.epoch(rec["epoch"]).maps[int(rec["file"])] = {
                "refs": rec.get("refs"),
                "counts": rec.get("counts"),
                "cache_ref": rec.get("cache_ref"),
            }
        elif kind == "reduce":
            self.epoch(rec["epoch"]).reduces[int(rec["reducer"])] = list(
                rec.get("refs") or []
            )
        elif kind == "deliver":
            st = self.epoch(rec["epoch"])
            r = int(rec["reducer"])
            # Delivery is reducer-ordered, so the cursor is a prefix.
            st.delivered = max(st.delivered, r + 1)
            rank = int(rec.get("rank", 0))
            st.rank_rows[rank] = (
                st.rank_rows.get(rank, 0) + int(rec.get("rows", 0))
            )
            st.sampled = max(st.sampled, int(rec.get("sampled", 0)))
        elif kind == "epoch-done":
            self.epoch(rec["epoch"]).done = True
        elif kind == "verdict":
            self.verdicts[int(rec["epoch"])] = {
                k: v for k, v in rec.items() if k != "kind"
            }
        elif kind == "suspended":
            self.suspended = True
        elif kind == "done":
            self.done = True
        elif kind == "superseded":
            self.superseded = True

    def iter_records(self, carry_cursors: bool = True):
        """Re-emit this state as journal records (the carry-forward a
        resumed run writes so its own journal is self-contained — a
        second preemption resumes from the NEW journal alone). With
        ``carry_cursors=False`` the delivery cursors are dropped
        (``redeliver`` mode: the in-flight epochs' streams will be
        re-delivered in full)."""
        for e in sorted(self.epochs):
            st = self.epochs[e]
            if st.schedule is not None:
                yield {"kind": "epoch", "epoch": e, "schedule": st.schedule}
            for i in sorted(st.maps):
                m = st.maps[i]
                rec = {"kind": "map", "epoch": e, "file": i, "carried": 1}
                if m.get("refs") is not None:
                    rec["refs"] = m["refs"]
                if m.get("counts") is not None:
                    rec["counts"] = m["counts"]
                if m.get("cache_ref") is not None:
                    rec["cache_ref"] = m["cache_ref"]
                yield rec
            for r in sorted(st.reduces):
                yield {
                    "kind": "reduce", "epoch": e, "reducer": r,
                    "refs": st.reduces[r], "carried": 1,
                }
            if carry_cursors and st.delivered > 0:
                # Collapse the per-reducer delivery history into one
                # synthetic record per rank: the fold only needs the
                # cursor (max reducer + 1) and the per-rank row
                # offsets, both of which survive the collapse.
                rank_rows = dict(st.rank_rows) or {0: 0}
                for rank, rows in sorted(rank_rows.items()):
                    yield {
                        "kind": "deliver", "epoch": e,
                        "reducer": st.delivered - 1,
                        "rank": rank, "rows": int(rows),
                        "sampled": st.sampled, "carried": 1,
                    }
            if st.done:
                yield {"kind": "epoch-done", "epoch": e, "carried": 1}
        for e in sorted(self.verdicts):
            yield {"kind": "verdict", "carried": 1, **self.verdicts[e]}


def load_run(path: str) -> RunState:
    """Fold one journal file into a :class:`RunState`. The first record
    must be the run-identity header (atomic publish guarantees it);
    torn tail lines (a crash mid-append) are skipped."""
    state: Optional[RunState] = None
    with open(path) as f:
        for line in f:
            if not line.endswith("\n"):
                break  # torn tail mid-append
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            if state is None:
                if rec.get("kind") != "run":
                    raise ValueError(
                        f"{path!r} is not a run journal (no identity "
                        "header)"
                    )
                state = RunState(
                    path, str(rec.get("run_id", "?")),
                    dict(rec.get("identity") or {}),
                )
                continue
            state.apply(rec)
    if state is None:
        raise ValueError(f"{path!r} is empty or torn before its header")
    return state


def _run_files(directory: str) -> List[str]:
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = [
        os.path.join(directory, n)
        for n in names
        if n.startswith("run-") and n.endswith(".ndjson")
    ]

    def _mtime(p: str) -> float:
        # A journal pruned between listdir and here must not crash
        # auto-discovery (load_run tolerates the same race below).
        try:
            return os.path.getmtime(p)
        except OSError:
            return 0.0

    out.sort(key=_mtime, reverse=True)
    return out


def find_resumable(
    directory: str, identity: dict
) -> Optional[RunState]:
    """The newest incomplete (not done, not superseded) run in
    ``directory`` whose identity matches — ``RSDL_RESUME=auto``'s
    discovery. Non-matching runs are skipped silently (they are
    different runs, not errors)."""
    for path in _run_files(directory):
        try:
            state = load_run(path)
        except (OSError, ValueError):
            continue
        if not state.resumable():
            continue
        try:
            validate_identity(state.identity, identity)
        except ValueError:
            continue
        return state
    return None


def resolve_resume(
    resume_from: Optional[str], identity: dict
) -> Tuple[Optional[RunState], str]:
    """``(state, mode)`` for this shuffle call. ``resume_from`` (a
    journal file, a journal dir, or ``"auto"``/``"redeliver"``) wins
    over ``RSDL_RESUME``; an explicit path with a mismatched identity
    RAISES (the refusal path), while auto discovery just starts fresh.
    Modes: ``cursor`` (skip already-delivered reducers — digest
    continuity) or ``redeliver`` (zero the cursors — a restarted
    consumer needs the in-flight epochs' full streams)."""
    spec = resume_from if resume_from is not None else (
        os.environ.get(ENV_RESUME) or ""
    )
    spec = str(spec).strip()
    if not spec or spec.lower() in ("0", "off", "false"):
        return None, "cursor"
    mode = "cursor"
    low = spec.lower()
    if low in ("auto", "1", "on", "true", "cursor"):
        directory = journal_dir()
        if not directory or not os.path.isdir(directory):
            return None, mode
        return find_resumable(directory, identity), mode
    if low == "redeliver":
        mode = "redeliver"
        directory = journal_dir()
        if not directory or not os.path.isdir(directory):
            return None, mode
        state = find_resumable(directory, identity)
        if state is not None:
            _zero_cursors(state)
        return state, mode
    # Explicit path (file or dir): identity mismatch must refuse loudly.
    path = spec
    if os.path.isdir(path):
        files = _run_files(path)
        if not files:
            raise ValueError(f"no run journals under {path!r}")
        path = files[0]
    state = load_run(path)
    validate_identity(state.identity, identity)
    if not state.resumable():
        raise ValueError(
            f"journal {path!r} records a completed (or superseded) run; "
            "nothing to resume"
        )
    return state, mode


def _zero_cursors(state: RunState) -> None:
    for st in state.epochs.values():
        if not st.done:
            st.delivered = 0
            st.rank_rows = {}
            st.sampled = 0


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------


class RunJournal:
    """Appender for one run's journal file (thread-safe: the deliver
    threads of concurrent in-flight epochs all append)."""

    def __init__(self, path: str, run_id: str):
        self.path = path
        self.run_id = run_id
        # Set by shuffle() on a resumed run; cleared (with the
        # recovery.resume_in_progress gauge) at the first delivery.
        self.resume_pending = False
        self._lock = threading.Lock()
        self._f = open(path, "a")
        self._sync = _sync_enabled()
        self._closed = False

    def append(self, kind: str, **fields: Any) -> None:
        rec = {"kind": kind, "ts": time.time(), **fields}
        try:
            with self._lock:
                if self._closed:
                    return
                self._f.write(json.dumps(rec) + "\n")
                self._f.flush()
                if self._sync:
                    os.fsync(self._f.fileno())
        except OSError:
            # The journal must never sink the run it protects; a failed
            # append merely widens the re-execution window on resume.
            logger.warning("journal append failed", exc_info=True)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._f.flush()
                if self._sync:
                    os.fsync(self._f.fileno())
            except OSError:
                pass
            self._f.close()


_current_lock = threading.Lock()
_current: Optional[RunJournal] = None


def current() -> Optional[RunJournal]:
    return _current


def current_run_id() -> Optional[str]:
    j = _current
    return j.run_id if j is not None else None


def begin_run(
    identity: dict,
    resume: Optional[RunState] = None,
    mode: str = "cursor",
) -> RunJournal:
    """Create (atomic publish) this run's journal and make it current.
    With ``resume``, the prior run's folded state is carried forward so
    the new journal is self-contained, and the prior journal is marked
    superseded (a later ``RSDL_RESUME=auto`` must find THIS run, not
    race back to the old one)."""
    global _current
    directory = journal_dir() or (
        os.path.dirname(resume.path) if resume is not None else None
    )
    if not directory:
        raise ValueError("RSDL_JOURNAL is not set")
    os.makedirs(directory, exist_ok=True)
    run_id = f"{int(time.time() * 1000):013d}-{os.getpid()}-{secrets.token_hex(3)}"
    path = os.path.join(directory, f"run-{run_id}.ndjson")
    tmp = path + ".tmp"
    header = {
        "kind": "run",
        "run_id": run_id,
        "ts": time.time(),
        "identity": identity,
    }
    with open(tmp, "w") as f:
        f.write(json.dumps(header) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)
    journal = RunJournal(path, run_id)
    if resume is not None:
        journal.append("resumed", from_run=resume.run_id)
        for rec in resume.iter_records(carry_cursors=(mode == "cursor")):
            journal.append(rec.pop("kind"), **rec)
        try:
            with open(resume.path, "a") as f:
                f.write(
                    json.dumps(
                        {
                            "kind": "superseded",
                            "by": run_id,
                            "ts": time.time(),
                        }
                    )
                    + "\n"
                )
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            logger.warning(
                "could not mark %s superseded", resume.path, exc_info=True
            )
    with _current_lock:
        _current = journal
    return journal


def end_run(journal: RunJournal, status: str = "done") -> None:
    """Seal a run: ``done`` marks it complete (never resumed again);
    any other status just closes the file, leaving it resumable."""
    global _current
    if status == "done":
        journal.append("done")
    journal.close()
    with _current_lock:
        if _current is journal:
            _current = None


# ---------------------------------------------------------------------------
# SIGTERM graceful suspend
# ---------------------------------------------------------------------------

_suspend_event = threading.Event()
_suspend_exit = threading.Event()
_handler_installed = False
_prev_handler: Any = None


def install_sigterm_handler() -> None:
    """Install the preemption-notice handler (idempotent). Only
    possible from the main thread (``signal.signal`` raises elsewhere —
    e.g. when ``ShufflingDataset`` drives the shuffle on a daemon
    thread); callers that cannot install still get programmatic
    suspend via :func:`request_suspend`."""
    global _handler_installed, _prev_handler
    if _handler_installed:
        return
    try:
        _prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
        _handler_installed = True
    except ValueError:
        logger.info(
            "journal: not on the main thread; SIGTERM suspend handler "
            "not installed (programmatic request_suspend still works)"
        )


def _on_sigterm(signum, frame) -> None:
    if _current is not None:
        # Preemption notice: quiesce, flush, exit 0 — driven by the
        # shuffle driver's loops, not from signal context.
        request_suspend(exit_process=True)
        return
    # No journaled run in flight: behave like the pre-existing world.
    prev = _prev_handler
    if callable(prev):
        prev(signum, frame)
    elif prev == signal.SIG_DFL:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def request_suspend(exit_process: bool = False) -> None:
    """Ask the in-flight run to suspend at the next barrier. The
    deliver threads finish their current reducer (the quiesce window),
    the driver stops admitting epochs, flushes every spool, journals
    the suspension, and then either exits 0 (``exit_process`` — the
    SIGTERM path) or raises :class:`RunSuspended`."""
    if exit_process:
        _suspend_exit.set()
    _suspend_event.set()


def suspend_requested() -> bool:
    return _suspend_event.is_set()


def suspend_should_exit() -> bool:
    return _suspend_exit.is_set()


def clear_suspend() -> None:
    _suspend_event.clear()
    _suspend_exit.clear()


# ---------------------------------------------------------------------------
# Resume observability (counters/gauge/events vocabulary:
# docs/observability.md)
# ---------------------------------------------------------------------------


def set_resume_in_progress(active: bool) -> None:
    """The ``recovery.resume_in_progress`` gauge (1 from resume start
    until the resumed run delivers its first reducer) — what the
    ``resume_stalled`` SLO rule watches. Metrics-gated, never raises."""
    try:
        from ray_shuffling_data_loader_tpu.telemetry import (
            metrics as _metrics,
        )

        if not _metrics.enabled():
            return
        _metrics.registry.gauge("recovery.resume_in_progress").set(
            1.0 if active else 0.0
        )
    except Exception:
        pass


def suspend_and_exit(journal: RunJournal) -> None:
    """The tail of the SIGTERM path, called by ``shuffle()`` after the
    window quiesced and the suspension is journaled: flush every
    telemetry spool that normally drains at atexit, then leave with
    exit code 0 *without* running teardown — the store segments ARE
    the suspended window and must survive for the resume."""
    try:
        from ray_shuffling_data_loader_tpu import telemetry as _t

        _t.audit.safe_flush()
        _t.export.safe_flush()
        _t.safe_flush()
    except Exception:
        pass
    for mod_name in (
        "ray_shuffling_data_loader_tpu.telemetry.events",
        "ray_shuffling_data_loader_tpu.telemetry.capacity",
        "ray_shuffling_data_loader_tpu.telemetry.stragglers",
    ):
        import sys as _sys

        mod = _sys.modules.get(mod_name)
        if mod is not None:
            try:
                mod.safe_flush()
            except Exception:
                pass
    journal.close()
    os._exit(0)
