"""Multi-host cluster plane: TCP registry, per-host agents, DCN data fetch.

SURVEY §7 M3: the reference scales by pointing ``ray.init(address="auto")``
at a Ray cluster — tasks scatter across nodes and the object store moves
bytes between them transparently. This module is the TPU-VM equivalent,
built on the same actor/transport substrate the single-host runtime uses
(everything speaks the framed-pickle protocol of :mod:`.transport`, over TCP
between hosts — the DCN control path):

* :class:`ClusterRegistry` — one actor on the head host: the cluster-wide
  name service (``ray.get_actor`` across hosts) plus the host membership
  table.
* :class:`HostAgent` — one actor per host, owning that host's spawned
  :class:`~.tasks.WorkerPool`; the head submits shuffle map/reduce tasks to
  agents round-robin, so stages scatter across all hosts' CPUs (the
  ``@ray.remote`` task-scheduling analog).
* :class:`StoreServer` — one actor per host serving raw object segments to
  other hosts. A reader whose local ``/dev/shm`` misses an object pulls the
  segment from its owner and caches it locally — the mapper→reducer and
  reducer→trainer DCN hops (reference gets this from plasma's cross-node
  transfer; SURVEY §2b).

Topology:

* head: ``runtime.init_cluster(listen_host=...)`` → session + registry +
  local agent/store-server.
* workers: ``runtime.init(address="tcp://head:port")`` → local session
  joined to the cluster (or ``python -m
  ray_shuffling_data_loader_tpu.runtime.cluster join tcp://head:port``).

Object movement stays ref-based end to end: only :class:`~.store.ObjectRef`
handles (now stamped with their owner's store-server address) cross the
control plane; bulk bytes move host-to-host exactly once, on first use.
"""

from __future__ import annotations

import concurrent.futures
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_shuffling_data_loader_tpu import telemetry

from . import transport
from .actor import ActorDiedError, ActorHandle, spawn_actor
from .store import ObjectRef


def parse_cluster_address(address: str) -> Tuple[str, int, Optional[str]]:
    """``tcp://host:port[/token]`` -> ``(host, port, token)``.

    The token is the cluster's bearer secret (see :mod:`.transport`); the
    full address string is the single thing an operator copies from the
    head to each worker host.
    """
    if not address.startswith("tcp://"):
        raise ValueError(f"not a cluster address: {address!r}")
    rest = address[len("tcp://") :]
    token = None
    if "/" in rest:
        rest, token = rest.split("/", 1)
    host, _, port = rest.rpartition(":")
    return host, int(port), token or None


def format_cluster_address(
    host: str, port: int, token: Optional[str] = None
) -> str:
    base = f"tcp://{host}:{port}"
    return f"{base}/{token}" if token else base


def default_advertise_host() -> str:
    """The IP other hosts should dial to reach this host. Overridable via
    ``RSDL_ADVERTISE_HOST`` (TPU pods: the VM's internal IP)."""
    env = os.environ.get("RSDL_ADVERTISE_HOST")
    if env:
        return env
    try:
        # No packets are sent; this just picks the outbound interface.
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        host = s.getsockname()[0]
        s.close()
        return host
    except OSError:
        return "127.0.0.1"


# ---------------------------------------------------------------------------
# Registry actor (runs on the head host)
# ---------------------------------------------------------------------------


class ClusterRegistry:
    """Cluster-wide name service + membership table.

    Single-threaded asyncio actor; no locks needed. Hosts and named actors
    register/deregister here; lookups come from every host.
    """

    def __init__(self):
        self._actors: Dict[str, Dict[str, Any]] = {}
        self._hosts: Dict[str, Dict[str, Any]] = {}

    # -- named actors (cross-host ray.get_actor analog) ----------------------

    def register_actor(
        self,
        name: str,
        address,
        pid: Optional[int],
        host_id: Optional[str] = None,
    ) -> None:
        """``host_id`` records which cluster host the actor RUNS ON (not
        who registered it) so :meth:`unregister_host` can sweep the names
        a departing host strands."""
        if name in self._actors:
            raise ValueError(f"actor name {name!r} already registered")
        self._actors[name] = {
            "address": list(address), "pid": pid, "host_id": host_id,
        }

    def unregister_actor(self, name: str) -> None:
        self._actors.pop(name, None)

    def lookup_actor(self, name: str) -> Optional[Dict[str, Any]]:
        return self._actors.get(name)

    # -- host membership -----------------------------------------------------

    def register_host(
        self,
        host_id: str,
        agent_address,
        store_address,
        num_workers: int,
    ) -> None:
        self._hosts[host_id] = {
            "agent": list(agent_address),
            "store": list(store_address),
            "num_workers": num_workers,
            "joined_at": time.time(),
        }

    def unregister_host(self, host_id: str) -> None:
        record = self._hosts.pop(host_id, None)
        # Sweep actor names stranded on the departed host: a stale record
        # would hand later lookups a dead address, turning every call into
        # a full connect-timeout instead of a fast failure into the retry
        # path. Match primarily by the record's host_id; records from
        # older callers (no host_id) fall back to an exact address match
        # against the host's registered service endpoints (matching by
        # bare IP would over-sweep same-machine multi-session tests).
        host_addrs = set()
        if record is not None:
            host_addrs = {
                tuple(record["agent"]), tuple(record["store"]),
            }
        for name in [
            n
            for n, rec in self._actors.items()
            if rec.get("host_id") == host_id
            or (
                rec.get("host_id") is None
                and tuple(rec["address"]) in host_addrs
            )
        ]:
            self._actors.pop(name, None)

    def hosts(self) -> Dict[str, Dict[str, Any]]:
        return dict(self._hosts)


# ---------------------------------------------------------------------------
# Per-host store server (DCN data plane)
# ---------------------------------------------------------------------------


def _slice_buffers(bufs, lo: int, hi: int):
    """The sub-list of scatter-gather ``bufs`` covering logical byte range
    ``[lo, hi)`` of their concatenation (views sliced at the edges; whole
    buffers passed through untouched). The striped fetch's server-side
    cut — no payload bytes are copied."""
    out = []
    pos = 0
    for b in bufs:
        view = memoryview(b).cast("B")
        n = view.nbytes
        start, stop = max(lo - pos, 0), min(hi - pos, n)
        if start < stop:
            out.append(view if (start, stop) == (0, n) else view[start:stop])
        pos += n
        if pos >= hi:
            break
    return out


class StoreServer:
    """Serves this host's shared-memory segments to remote readers.

    ``fetch`` returns raw segment-format bytes (header + columnar payload);
    the reader materializes them as a local segment and maps it zero-copy.
    One transfer per (object, reader-host) — repeated gets hit the local
    cache. A ``rows`` window ships just that window re-serialized (refs
    published via ``publish_slices`` share one physical segment; without
    slicing, every reducer would pull the whole thing — R× DCN traffic).
    """

    def __init__(self, shm_dir: str):
        from .store import _default_spill_dir

        self.shm_dir = shm_dir
        self.spill_dir = _default_spill_dir()
        self.served_count = 0
        self.served_bytes = 0
        # Tiny mapping cache (path -> mapped batch/mmap): a striped
        # fetch (RSDL_TCP_STREAMS) issues one fetch_vec per stripe of
        # the SAME segment, and re-mmapping + re-faulting it per stripe
        # was a measured per-window cost. Segments are immutable once
        # published, so a cached mapping can only ever be stale-by-
        # absence (freed), which the exists() probe in _path catches.
        self._map_cache: Dict[str, Any] = {}
        self._map_cache_cap = 8

    def _path(self, object_id: str) -> str:
        # object_ids are token_hex-based; reject anything path-like.
        if "/" in object_id or object_id.startswith("."):
            raise ValueError(f"bad object id {object_id!r}")
        path = os.path.join(self.shm_dir, object_id)
        if not os.path.exists(path):
            # Segments over the capacity budget live in the spill dir.
            spath = os.path.join(self.spill_dir, object_id)
            if os.path.exists(spath):
                return spath
        return path

    def fetch(self, object_id: str, rows=None) -> bytes:
        path = self._path(object_id)
        if rows is None:
            with open(path, "rb") as f:
                data = f.read()
        else:
            from .store import map_segment_file, serialize_columns

            batch = map_segment_file(path, object_id).slice(
                int(rows[0]), int(rows[1])
            )
            data = serialize_columns(batch.columns, layout=batch.layout)
        self.served_count += 1
        self.served_bytes += len(data)
        return data

    def fetch_vec(
        self, object_id: str, rows=None, stripe=None
    ) -> "transport.OutOfBand":
        """Zero-copy fetch (``RSDL_TCP_ZEROCOPY`` clients): the reply's
        bulk payload is a scatter-gather list of views straight over this
        host's mmapped segment — no ``serialize_columns`` materialization,
        no ``bytes`` copy, no payload pickle. Wire bytes are identical to
        :meth:`fetch`'s, so the reader's cache file is the same either
        way.

        ``stripe=(i, n)`` serves only byte range
        ``[i*total//n, (i+1)*total//n)`` of that same serialization — the
        multi-stream striped fetch (``RSDL_TCP_STREAMS``) issues one such
        call per stream on its own connection and lands each range in a
        disjoint window of one destination mapping; the concatenation
        across stripes is byte-identical to the unstriped reply. The
        reply meta carries ``{"nbytes": total, "stripe": [lo, hi]}`` so
        the client can size/position the mapping from any stripe's
        header. Per-stream wire format is the ordinary vectored frame."""
        import mmap as _mmap

        from .store import map_segment_file, serialize_columns_vectored

        path = self._path(object_id)
        cache_key = (path, rows if rows is None else tuple(rows))
        cached = self._map_cache.get(cache_key)
        if cached is not None and not os.path.exists(path):
            # The file vanished outside free() (external reaper, spill
            # cleanup): evict, or the dead entry would both pin the
            # unlinked segment's pages and block re-caching forever.
            self._map_cache.pop(cache_key, None)
            cached = None
        if cached is not None:
            total, bufs, keepalive = cached
        elif rows is None:
            fd = os.open(path, os.O_RDONLY)
            try:
                size = os.fstat(fd).st_size
                mm = _mmap.mmap(fd, size, prot=_mmap.PROT_READ)
            finally:
                os.close(fd)
            total, bufs, keepalive = size, [memoryview(mm)], mm
        else:
            batch = map_segment_file(path, object_id).slice(
                int(rows[0]), int(rows[1])
            )
            total, bufs = serialize_columns_vectored(
                batch.columns, layout=batch.layout
            )
            keepalive = batch
        if cached is None:
            if len(self._map_cache) >= self._map_cache_cap:
                # FIFO eviction is plenty: stripes of one window land
                # within milliseconds of each other.
                self._map_cache.pop(next(iter(self._map_cache)))
            self._map_cache[cache_key] = (total, bufs, keepalive)
        meta = {"nbytes": total}
        if stripe is not None:
            i, n = int(stripe[0]), int(stripe[1])
            if not (0 < n and 0 <= i < n):
                raise ValueError(f"bad stripe {stripe!r}")
            lo, hi = i * total // n, (i + 1) * total // n
            bufs = _slice_buffers(bufs, lo, hi)
            meta["stripe"] = [lo, hi]
            self.served_bytes += hi - lo
            if i > 0:
                # One logical fetch, n striped calls: count the object
                # once (stripe 0) but every stripe's bytes.
                return transport.OutOfBand(meta, bufs, keepalive=keepalive)
        else:
            self.served_bytes += total
        self.served_count += 1
        # keepalive pins the source mmap until the reply is written; the
        # actor host drops the OutOfBand right after the frame goes out.
        return transport.OutOfBand(meta, bufs, keepalive=keepalive)

    def fetch_stats(self) -> Dict[str, int]:
        """Cross-host traffic served by this host (the locality test's
        measurement; the reference's analog is plasma transfer metrics)."""
        return {"count": self.served_count, "bytes": self.served_bytes}

    def free(self, object_id: str) -> None:
        try:
            path = self._path(object_id)
            # Drop cached mappings first: a pinned mmap would keep the
            # unlinked segment's tmpfs pages alive until eviction.
            for key in [k for k in self._map_cache if k[0] == path]:
                self._map_cache.pop(key, None)
            os.unlink(path)
        except (FileNotFoundError, ValueError):
            pass

    def exists(self, object_id: str) -> bool:
        return os.path.exists(self._path(object_id))

    def list_segments(self, prefix: str) -> List[Tuple[str, int]]:
        """``(object_id, nbytes)`` of every published segment this host
        holds under the session prefix — the graceful drain's re-home
        inventory (``runtime/elastic.py``)."""
        out: Dict[str, int] = {}
        for d in (self.shm_dir, self.spill_dir):
            try:
                names = os.listdir(d)
            except FileNotFoundError:
                continue
            for name in names:
                if name.startswith(prefix) and not name.endswith(".tmp"):
                    try:
                        out.setdefault(
                            name, os.path.getsize(os.path.join(d, name))
                        )
                    except OSError:
                        pass
        return sorted(out.items())

    def put_segment(self, object_id: str, data: bytes) -> bool:
        """Adopt a re-homed segment into this host's shm dir (the drain
        path's planned migration). Idempotent: an existing copy wins —
        object ids are immutable content."""
        if "/" in object_id or object_id.startswith("."):
            raise ValueError(f"bad object id {object_id!r}")
        path = os.path.join(self.shm_dir, object_id)
        if os.path.exists(path):
            return False
        # ".tmp" suffix so a failed write is excluded from store_stats
        # and from a later drain's list_segments inventory.
        tmp = f"{path}.rehome-{os.getpid()}.tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
            os.rename(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
            raise
        return True


# ---------------------------------------------------------------------------
# Per-host task agent (cross-host task scheduling)
# ---------------------------------------------------------------------------


class HostAgent:
    """Owns one host's worker pool; executes tasks submitted by the head.

    Runs as an actor process on its host. The pool is created lazily on
    first submit (pure consumers never pay for it). ``submit`` is async so
    many tasks run concurrently under the actor's event loop while each
    awaits its pool future in a thread.
    """

    def __init__(
        self,
        runtime_dir: str,
        num_workers: int,
        advertise_host: Optional[str] = None,
    ):
        # Tasks must join THIS host's session (store segments live here).
        os.environ["RSDL_RUNTIME_DIR"] = runtime_dir
        self._runtime_dir = runtime_dir
        self._num_workers = num_workers
        self._advertise_host = advertise_host
        self._pool = None
        self._lock = threading.Lock()
        self._submitted = 0
        self._completed = 0
        self._spawned: List[ActorHandle] = []

    def _get_pool(self):
        from .tasks import WorkerPool

        with self._lock:
            if self._pool is None:
                self._pool = WorkerPool(
                    self._num_workers,
                    env={"RSDL_RUNTIME_DIR": self._runtime_dir},
                )
            return self._pool

    async def submit(self, fn, args, kwargs):
        import asyncio

        self._submitted += 1
        fut = self._get_pool().submit(fn, *args, **kwargs)
        loop = asyncio.get_running_loop()
        # TaskFuture.result re-raises TaskError; the actor host forwards it
        # to the remote caller as the reply frame.
        result = await loop.run_in_executor(None, fut.result)
        self._completed += 1
        return result

    def num_workers(self) -> int:
        return self._num_workers

    async def spawn_named_actor(self, cls, args, kwargs, name=None):
        """Spawn an actor ON THIS HOST on behalf of a remote caller — the
        placement primitive behind ``runtime.spawn_actor(host_id=...)``
        (the reference expresses the same intent with SPREAD placement
        groups + per-actor resource reservations,
        ``benchmarks/benchmark.py:125-130``, ``batch_queue.py:46-65``).

        Async on purpose: the child bring-up blocks until the actor's
        ctor finishes (possibly minutes of first-touch jax init), and a
        sync method would block the agent's event loop for that whole
        time — no pings answered, so placement health checks would
        falsely declare this host dead and concurrent spawns would
        serialize. The blocking wait runs in a thread executor instead.

        Returns ``(address, pid)``; the caller builds its own handle and
        registers any name with the head registry. The agent keeps the
        handle and reaps the actor in ``teardown`` — the caller's
        ``terminate`` only reaches the actor's TCP socket, not its pid.
        """
        import asyncio

        def _do():
            return spawn_actor(
                cls,
                *args,
                runtime_dir=self._runtime_dir,
                host=self._advertise_host,
                **kwargs,
            )

        handle = await asyncio.get_running_loop().run_in_executor(None, _do)
        if name is not None:
            handle.name = name
        with self._lock:
            self._spawned.append(handle)
        return list(handle.address), handle.pid

    def agent_stats(self) -> Dict[str, int]:
        return {"submitted": self._submitted, "completed": self._completed}

    def teardown(self) -> None:
        """Reap the worker pool (and any placement-spawned actors) before
        the actor process exits (called by the actor host on graceful
        termination)."""
        with self._lock:
            pool, self._pool = self._pool, None
            spawned, self._spawned = self._spawned, []
        for handle in spawned:
            try:
                handle.terminate(grace_period_s=2.0)
            except Exception:
                pass
        if pool is not None:
            pool.shutdown()


class PlacementProbe:
    """Diagnostic actor: reports where it actually runs. Used by the
    placement tests (``spawn_actor(host_id=...)`` must land the actor in
    the TARGET host's session) and handy for operators verifying a
    cluster's spread."""

    def info(self) -> Dict[str, Any]:
        return {
            "runtime_dir": os.environ.get("RSDL_RUNTIME_DIR"),
            "pid": os.getpid(),
        }


# ---------------------------------------------------------------------------
# Client side (lives in RuntimeContext)
# ---------------------------------------------------------------------------


def fetch_vec_striped(
    handle: ActorHandle,
    object_id: str,
    rows,
    alloc,
    n_streams: int,
    executor: concurrent.futures.Executor,
) -> None:
    """Striped zero-copy fetch: ``n_streams`` concurrent ``fetch_vec``
    calls, each pulling one byte range of the segment serialization over
    its own persistent connection (the executor's threads each hold a
    per-peer connection, so stream count = pool width) and landing it via
    ``recv_into`` in a disjoint window of ONE destination mapping.

    ``alloc(total_bytes)`` is the store's ordinary destination allocator
    (mmaps the cache tmp file); it is called exactly once, by whichever
    stripe's reply header lands first. Stripe failures (reset, tamper,
    length/total mismatch) surface as :class:`~.actor.ActorDiedError` /
    ``ConnectionError`` — the same retry-safe class as the single-stream
    fetch, so the lineage/retry ladder above needs no new cases.

    Stripe 0 runs ON THE CALLING THREAD (which already holds its own
    per-peer connection — the same one single-stream fetches use), only
    stripes 1..n-1 ride the executor: concurrent window fetches (the
    prefetch plane runs up to depth of them) therefore keep at least
    their previous one-recv-per-window concurrency as a floor even when
    the shared stripe pool is saturated, instead of all windows
    funnelling through ``n_streams`` pool threads."""
    lock = threading.Lock()
    state: Dict[str, Any] = {}

    def _window(nbytes: int, meta) -> memoryview:
        # Runs inside recv_frame, before any payload byte is read. All
        # validation failures raise ConnectionError: the frame's payload
        # is still on the wire, so the connection must be torn down (the
        # caller's except path drops it), and ConnectionError is exactly
        # what the call layer wraps into the retry-safe ActorDiedError.
        if not isinstance(meta, dict) or "nbytes" not in meta:
            raise ConnectionError(f"bad stripe reply meta: {meta!r}")
        total = int(meta["nbytes"])
        lo, hi = meta.get("stripe", (0, total))
        if hi - lo != nbytes or not (0 <= lo <= hi <= total):
            raise ConnectionError(
                f"stripe range {lo}-{hi} inconsistent with payload "
                f"{nbytes} B / total {total} B"
            )
        with lock:
            if "mm" not in state:
                state["total"] = total
                state["mm"] = alloc(total)
            elif state["total"] != total:
                raise ConnectionError(
                    f"stripe total mismatch: {total} != {state['total']}"
                )
            mm = state["mm"]
        return memoryview(mm)[lo:hi]

    _window.wants_meta = True

    def _pull(i: int) -> None:
        meta, payload = handle.call_vectored(
            "fetch_vec", object_id, rows, stripe=(i, n_streams),
            into=_window,
        )
        if payload is not None:
            # Release promptly: the store closes the destination mmap the
            # moment the fetch returns, and a surviving exported view
            # would turn that close into BufferError.
            payload.release()

    futures = [
        executor.submit(_pull, i) for i in range(1, n_streams)
    ]
    error: Optional[BaseException] = None
    try:
        _pull(0)
    except BaseException as exc:
        error = exc
    for fut in futures:
        try:
            fut.result()
        except BaseException as exc:
            error = error or exc
    if error is not None:
        raise error
    if "mm" not in state:
        raise ConnectionError("striped fetch produced no data")


class ClusterTaskFuture:
    """TaskFuture-compatible wrapper over a concurrent future (same
    ``done()/result()`` surface ``runtime.wait`` and the shuffle driver
    poll)."""

    def __init__(self, inner: concurrent.futures.Future):
        self._inner = inner
        self._waiters_lock = threading.Lock()
        self._waiters: set = set()
        self._callback_added = False

    def done(self) -> bool:
        return self._inner.done()

    def result(self, timeout: Optional[float] = None):
        return self._inner.result(timeout)

    # One permanent done-callback consulting a removable waiter set:
    # concurrent futures never drop registered callbacks, so registering
    # one per wait() call would leak O(waits) closures on slow futures
    # (shuffle's free-inputs loop waits num_reducers times per epoch).
    def _add_waiter(self, event: threading.Event) -> None:
        register = False
        with self._waiters_lock:
            self._waiters.add(event)
            if not self._callback_added:
                self._callback_added = True
                register = True
        if register:
            # OUTSIDE the lock: on an already-done future the callback
            # fires synchronously in this thread (concurrent.futures
            # contract) and _notify_waiters needs the lock.
            self._inner.add_done_callback(self._notify_waiters)

    def _notify_waiters(self, _f) -> None:
        with self._waiters_lock:
            waiters, self._waiters = self._waiters, set()
        for event in waiters:
            event.set()

    def _remove_waiter(self, event: threading.Event) -> None:
        with self._waiters_lock:
            self._waiters.discard(event)


# -- elastic membership state (ISSUE 10) ------------------------------------
# Draining/retired verdicts live at MODULE level, not on the scheduler
# instance: ClusterClient rebuilds its scheduler on every membership
# refresh, and an instance-held drain mark would silently resurrect a
# draining host mid-drain. Addresses are unique per run (fresh ports),
# so cross-run leakage is inert; tests call reset_membership().

_membership_lock = threading.Lock()
_draining_addrs: set = set()
_retired_addrs: List[str] = []
_RETIRED_CAP = 64
_live_scheduler = None  # weakref.ref to the most recent scheduler


def _addr_str(address) -> str:
    try:
        return ":".join(str(p) for p in address)
    except TypeError:
        return str(address)


def reset_membership() -> None:
    """Drop module-level drain/retire state (tests, run boundaries)."""
    global _live_scheduler
    with _membership_lock:
        _draining_addrs.clear()
        del _retired_addrs[:]
        _live_scheduler = None


def membership_section() -> Dict[str, Any]:
    """The ``cluster`` section ``/status`` embeds: live agents (with
    drain flags and in-flight counts), draining addresses, and recently
    retired agents — read from the most recent scheduler via a weakref
    so the obs server never holds one alive."""
    sched = _live_scheduler() if _live_scheduler is not None else None
    with _membership_lock:
        draining = {_addr_str(a) for a in _draining_addrs}
        retired = list(_retired_addrs)
    agents = []
    if sched is not None:
        agents = sched.agent_rows()
    return {"agents": agents, "draining": sorted(draining),
            "retired": retired}


class ClusterScheduler:
    """Round-robin task scheduler over every host's agent, with dead-agent
    failover.

    The analog of Ray's cluster scheduler for this workload: shuffle stages
    are embarrassingly parallel and uniform, so round-robin over hosts
    (each agent then queues onto its local pool) keeps all hosts' CPUs fed
    without load telemetry. An agent that dies mid-run (host preempted) is
    dropped from the rotation and its task retried on a surviving host;
    ``on_agent_dead`` (set by the owning client) evicts the host from the
    membership table.

    Elastic membership (ISSUE 10): :meth:`add_agent` admits a new host
    mid-run; :meth:`retire_agent` marks one *draining* — dispatch skips
    it while its in-flight tasks (tracked per agent) finish, the planned
    half of the drain protocol ``runtime/elastic.py`` orchestrates;
    :meth:`remove_agent` completes the retirement.
    """

    def __init__(
        self,
        agents: List[ActorHandle],
        store_to_agent: Optional[Dict[Tuple, ActorHandle]] = None,
        max_inflight: int = 64,
        width: Optional[int] = None,
    ):
        if not agents:
            raise ValueError("no host agents registered")
        self._agents = list(agents)
        # Cluster-wide worker count (sum of every host's pool), for
        # callers sizing submission windows to actual decode capacity.
        self.width = int(width) if width else len(agents)
        # store-server address -> that host's agent; lets locality hints
        # (ObjectRef.owner carries the store address) pick the host that
        # already holds a task's inputs.
        self._store_to_agent = {
            tuple(k): v for k, v in (store_to_agent or {}).items()
        }
        self._idx = 0
        self._lock = threading.Lock()
        self._inflight: Dict[Tuple, int] = {}  # address -> running calls
        # Worker counts of agents admitted via add_agent, so their
        # departure (remove_agent/_drop_agent) can give the width back
        # — bootstrap agents' shares stay in width until a membership
        # rebuild re-derives it from the registry.
        self._added_widths: Dict[Tuple, int] = {}
        self.on_agent_dead = None  # Callable[[ActorHandle], None]
        # Blocking actor calls ride threads; in-flight tasks are bounded by
        # the executor width (queued beyond that, preserving order).
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_inflight, thread_name_prefix="cluster-sched"
        )
        global _live_scheduler
        import weakref

        with _membership_lock:
            _live_scheduler = weakref.ref(self)

    @property
    def agent_addresses(self) -> set:
        with self._lock:
            return {a.address for a in self._agents}

    def _next_agent(self) -> ActorHandle:
        with _membership_lock:
            draining = set(_draining_addrs)
        with self._lock:
            if not self._agents:
                raise ActorDiedError("every cluster host agent has died")
            # Drain-aware dispatch: draining agents take no NEW tasks.
            # If every agent is draining, keep placing anyway — a drain
            # must degrade into failover, never into a submit hang.
            candidates = [
                a for a in self._agents if a.address not in draining
            ] or self._agents
            agent = candidates[self._idx % len(candidates)]
            self._idx += 1
            return agent

    # -- elastic membership (ISSUE 10) ---------------------------------------

    def add_agent(
        self,
        agent: ActorHandle,
        store_address: Optional[Tuple] = None,
        num_workers: int = 1,
    ) -> bool:
        """Admit a new host agent to the rotation mid-run (scale-up).
        Idempotent by address; un-retires/un-drains a re-added agent."""
        with _membership_lock:
            _draining_addrs.discard(agent.address)
        with self._lock:
            if any(a.address == agent.address for a in self._agents):
                return False
            self._agents.append(agent)
            if store_address is not None:
                self._store_to_agent[tuple(store_address)] = agent
            share = max(1, int(num_workers))
            self._added_widths[tuple(agent.address)] = share
            self.width += share
        return True

    def _find_agent(self, address) -> Optional[ActorHandle]:
        address = tuple(address)
        with self._lock:
            for a in self._agents:
                if tuple(a.address) == address:
                    return a
        return None

    def retire_agent(self, agent_or_address) -> Optional[ActorHandle]:
        """Mark an agent DRAINING: dispatch stops placing new tasks on
        it while its in-flight tasks finish. This is the first step of
        the planned-migration path (``runtime/elastic.py`` waits out the
        in-flight window, re-homes store segments, then calls
        :meth:`remove_agent` — or falls back to :meth:`_drop_agent`'s
        failover machinery on a blown deadline)."""
        address = tuple(getattr(agent_or_address, "address",
                                agent_or_address))
        with _membership_lock:
            _draining_addrs.add(address)
        return self._find_agent(address)

    def remove_agent(self, agent_or_address) -> bool:
        """Complete a retirement: drop the agent from the rotation and
        record it retired. Unlike :meth:`_drop_agent` this is the
        *planned* exit — no eviction counter, no task failover."""
        address = tuple(getattr(agent_or_address, "address",
                                agent_or_address))
        with self._lock:
            before = len(self._agents)
            self._agents = [
                a for a in self._agents if tuple(a.address) != address
            ]
            removed = len(self._agents) != before
            if removed:
                self.width = max(
                    1, self.width - self._added_widths.pop(address, 0)
                )
        with _membership_lock:
            _draining_addrs.discard(address)
            if removed:
                _retired_addrs.append(_addr_str(address))
                del _retired_addrs[:-_RETIRED_CAP]
        return removed

    def in_flight_on(self, agent_or_address) -> int:
        """Tasks currently running on one agent — the drain wait's
        signal."""
        address = tuple(getattr(agent_or_address, "address",
                                agent_or_address))
        with self._lock:
            return self._inflight.get(address, 0)

    def _inflight_adjust(self, address, delta: int) -> None:
        with self._lock:
            count = self._inflight.get(address, 0) + delta
            if count > 0:
                self._inflight[address] = count
            else:
                self._inflight.pop(address, None)

    def agent_rows(self) -> List[Dict[str, Any]]:
        """Per-agent membership rows for the ``/status`` cluster
        section."""
        with _membership_lock:
            draining = set(_draining_addrs)
        with self._lock:
            return [
                {
                    "address": _addr_str(a.address),
                    "draining": a.address in draining,
                    "in_flight": self._inflight.get(a.address, 0),
                }
                for a in self._agents
            ]

    def _drop_agent(self, agent: ActorHandle) -> None:
        with self._lock:
            before = len(self._agents)
            self._agents = [
                a for a in self._agents if a.address != agent.address
            ]
            removed = len(self._agents) != before
            if removed:
                self.width = max(
                    1,
                    self.width
                    - self._added_widths.pop(tuple(agent.address), 0),
                )
        with _membership_lock:
            _draining_addrs.discard(agent.address)
        if not removed:
            # Concurrent submits can race to drop the same dead agent;
            # only the actual removal counts an eviction and fires the
            # membership callback (an alert on recovery.agent_evictions
            # must read one per dead host, not one per racing task).
            return
        telemetry.metrics.safe_inc("recovery.agent_evictions")
        telemetry.emit_event(
            "agent.evicted", agent=str(getattr(agent, "address", None))
        )
        if self.on_agent_dead is not None:
            try:
                self.on_agent_dead(agent)
            except Exception:
                pass

    def _submit_once(self, agent: ActorHandle, fn, args, kwargs):
        """One submit attempt with death confirmation: ActorHandle wraps
        ANY ConnectionError/OSError into ActorDiedError, so a transient
        TCP reset would otherwise permanently evict a healthy host from
        both the rotation and the membership table. Before dropping,
        confirm with a ping on a fresh connection; an alive agent gets
        the call retried instead of its host evicted."""
        # Per-agent in-flight accounting (covers the retry attempt too):
        # the drain path waits on this count before retiring the host.
        self._inflight_adjust(agent.address, +1)
        try:
            return True, agent.call("submit", fn, args, kwargs)
        except ActorDiedError:
            # Escalating ping ladder: a loaded-but-alive host can miss a
            # single short ping (1-core CI saturates for seconds at a
            # time), and false eviction is expensive — the scheduler
            # unregisters the host, in-flight segments leak, and only the
            # 10 s heartbeat re-admits it. A genuinely dead host fails
            # each ping fast (connection refused), so the ladder costs
            # almost nothing when it matters.
            for ping_timeout in (5.0, 10.0, 20.0):
                if agent.ping(timeout=ping_timeout):
                    try:
                        # Alive — the error was a transient connection
                        # drop. Task bodies are idempotent over the
                        # store, so a retry after an ambiguous failure
                        # is safe.
                        telemetry.metrics.safe_inc(
                            "recovery.retries", site="agent.submit"
                        )
                        return True, agent.call("submit", fn, args, kwargs)
                    except ActorDiedError:
                        pass
                    break
            self._drop_agent(agent)
            return False, None
        finally:
            self._inflight_adjust(agent.address, -1)

    def _run(self, fn, args, kwargs, trace_ctx=None):
        # Task bodies are idempotent pure functions over the store (map/
        # reduce stages), so retrying on another host after an agent death
        # is safe; at most len(agents) attempts.
        # trace_ctx is the SUBMITTER thread's context, re-entered here on
        # the executor thread so the agent call (and through it the
        # worker-side span) carries (epoch, schedule, ...) — contextvars
        # don't cross the executor hop by themselves.
        with telemetry.context(**(trace_ctx or {})):
            while True:
                agent = self._next_agent()
                ok, result = self._submit_once(agent, fn, args, kwargs)
                if ok:
                    return result
                # The agent died: the task fails over to the next host in
                # the rotation (bounded — every failure evicts an agent,
                # and an empty rotation raises ActorDiedError above).
                telemetry.metrics.safe_inc("recovery.task_failover")
                telemetry.emit_event(
                    "task.failover",
                    fn=getattr(fn, "__name__", "task"),
                    agent=str(getattr(agent, "address", None)),
                )

    def submit(self, fn: Callable, *args, **kwargs) -> ClusterTaskFuture:
        inner = self._executor.submit(
            self._run, fn, args, kwargs, telemetry.outbound_context()
        )
        return ClusterTaskFuture(inner)

    def _locality_agent(self, refs) -> Optional[ActorHandle]:
        """The agent on the host owning the most input rows/bytes, or None
        when no preference exists (no owners, owner not in the cluster, or
        locality disabled via ``RSDL_DISABLE_LOCALITY``)."""
        if os.environ.get("RSDL_DISABLE_LOCALITY"):
            return None
        weights: Dict[Tuple, int] = {}
        for ref in refs:
            owner = getattr(ref, "owner", None)
            if owner is None:
                continue
            rows = getattr(ref, "rows", None)
            # Window refs weigh by row span (uniform row width across one
            # reduce's inputs); whole-segment refs by size.
            w = (
                int(rows[1]) - int(rows[0])
                if rows is not None
                else max(1, int(getattr(ref, "nbytes", 1)))
            )
            key = tuple(owner)
            weights[key] = weights.get(key, 0) + w
        if not weights:
            return None
        best = max(weights, key=weights.get)
        agent = self._store_to_agent.get(best)
        if agent is None:
            return None
        with _membership_lock:
            if agent.address in _draining_addrs:
                # A draining host may still OWN the bytes, but placement
                # there would extend its in-flight window indefinitely.
                return None
        with self._lock:
            live = {a.address for a in self._agents}
        return agent if agent.address in live else None

    def _run_preferring(self, preferred, fn, args, kwargs, trace_ctx=None):
        with telemetry.context(**(trace_ctx or {})):
            if preferred is not None:
                ok, result = self._submit_once(preferred, fn, args, kwargs)
                if ok:
                    return result
            return self._run(fn, args, kwargs)

    def submit_local_to(self, refs, fn: Callable, *args, **kwargs):
        """Locality-aware submit: place the task on the host holding the
        most of ``refs``' bytes (Ray schedules reduce tasks near their
        input objects; round-robin would ship ~(N-1)/N of all partition
        bytes across DCN unnecessarily). Falls back to round-robin when
        no host dominates or the preferred host died."""
        preferred = self._locality_agent(refs)
        inner = self._executor.submit(
            self._run_preferring, preferred, fn, args, kwargs,
            telemetry.outbound_context(),
        )
        return ClusterTaskFuture(inner)

    def shutdown(self, cancel: bool = True):
        # cancel=False: a membership-change rebuild retires this scheduler
        # but already-submitted futures must still run to completion.
        self._executor.shutdown(wait=False, cancel_futures=cancel)


class ClusterClient:
    """A host's view of the cluster: registry handle + local actors.

    Created by ``runtime.init_cluster`` (head) or ``runtime.init`` with a
    ``tcp://`` address (worker host). Wires the local store's remote-fetch
    hooks and exposes the cross-host scheduler.
    """

    def __init__(
        self,
        registry: ActorHandle,
        host_id: str,
        advertise_host: str,
        agent: ActorHandle,
        store_server: ActorHandle,
        is_head: bool,
        registry_address: Tuple[str, int],
    ):
        self.registry = registry
        self.host_id = host_id
        self.advertise_host = advertise_host
        self.agent = agent
        self.store_server = store_server
        self.is_head = is_head
        self.address = format_cluster_address(
            *registry_address, token=os.environ.get("RSDL_CLUSTER_TOKEN")
        )
        self._scheduler: Optional[ClusterScheduler] = None
        self._scheduler_lock = threading.Lock()
        self._scheduler_read_ts = 0.0
        self._peer_stores: Dict[Tuple, ActorHandle] = {}
        self._peer_lock = threading.Lock()
        # Striped-fetch stream pool (RSDL_TCP_STREAMS > 1): its threads
        # each hold one persistent authed connection per peer store.
        # Stripe 0 of every fetch runs on the calling thread, so the
        # pool serves only the EXTRA stripes — sized (streams-1) x a few
        # concurrent windows so the prefetch plane's parallel window
        # fetches don't serialize behind each other's stripes. Shares
        # the store's grow-on-demand pool semantics (retired pools are
        # never shut down mid-run, so a racing submit can't hit a
        # closed executor).
        from .store import GrowingThreadPool

        self._stripe_pool = GrowingThreadPool("store-stripe")
        # How often the scheduler re-reads cluster membership (late joiners
        # picked up; sub-second churn is not a target).
        self.membership_refresh_s = 5.0

    # -- data plane hooks (installed into ObjectStore) -----------------------

    def _peer_store(self, address: Tuple) -> ActorHandle:
        address = tuple(address)
        with self._peer_lock:
            handle = self._peer_stores.get(address)
            if handle is None:
                handle = ActorHandle(address)
                self._peer_stores[address] = handle
            return handle

    def fetch_remote(self, ref: ObjectRef) -> bytes:
        return self._peer_store(ref.owner).call(
            "fetch", ref.object_id, ref.rows
        )

    def _stripe_executor(self, streams: int):
        # Pool threads serve stripes 1..n-1 of each fetch (stripe 0 is
        # inline on the caller); x4 covers the prefetch plane's typical
        # concurrent windows, capped — each thread holds one persistent
        # connection per peer.
        return self._stripe_pool.ensure(min(16, max(1, streams - 1) * 4))

    def fetch_remote_into(self, ref: ObjectRef, alloc) -> None:
        """Zero-copy fetch: the peer streams header + payload as one
        vectored frame and the payload lands via ``recv_into`` in the
        buffer ``alloc(total_bytes)`` returns (the store mmaps the
        destination cache file) — no intermediate ``bytes`` join or
        payload pickle on either side.

        With ``RSDL_TCP_STREAMS`` > 1 the payload is striped by byte
        range over that many persistent connections, each stripe landing
        in a disjoint window of the same mapping with parallel
        ``recv_into`` (single-stream framing + single-core recv was the
        measured gap to the raw loopback ceiling — BENCHLOG r6)."""
        streams = transport.tcp_streams()
        if streams > 1:
            fetch_vec_striped(
                self._peer_store(ref.owner), ref.object_id, ref.rows,
                alloc, streams, self._stripe_executor(streams),
            )
            return
        meta, payload = self._peer_store(ref.owner).call_vectored(
            "fetch_vec", ref.object_id, ref.rows, into=alloc
        )
        if payload is None:
            # Plain reply (defensive — fetch_vec always replies vectored):
            # land the bytes through the allocator so the caller's
            # contract holds.
            data = meta
            view = memoryview(alloc(len(data))).cast("B")
            view[: len(data)] = data

    def free_remote(self, ref: ObjectRef) -> None:
        try:
            self._peer_store(ref.owner).call_oneway("free", ref.object_id)
        except ActorDiedError:
            pass

    @property
    def store_address(self) -> Tuple:
        return self.store_server.address

    # -- control plane -------------------------------------------------------

    def _read_agents(
        self,
    ) -> Tuple[List[ActorHandle], Dict[Tuple, ActorHandle]]:
        hosts = self.registry.call("hosts")
        agents: List[ActorHandle] = []
        store_to_agent: Dict[Tuple, ActorHandle] = {}
        total_workers = 0
        for info in hosts.values():
            agent = (
                self.agent
                if info["agent"] == list(self.agent.address)
                else ActorHandle(tuple(info["agent"]))
            )
            agents.append(agent)
            store_to_agent[tuple(info["store"])] = agent
            total_workers += int(info.get("num_workers", 1))
        self._total_workers = max(1, total_workers)
        return agents, store_to_agent

    def _evict_host(self, agent: ActorHandle) -> None:
        """Drop a dead agent's host from the membership table so later
        scheduler rebuilds don't resurrect it."""
        try:
            hosts = self.registry.call("hosts")
            for host_id, info in hosts.items():
                if tuple(info["agent"]) == tuple(agent.address):
                    self.registry.call_oneway("unregister_host", host_id)
        except ActorDiedError:
            pass

    def scheduler(self) -> ClusterScheduler:
        """The cluster-wide task scheduler.

        Membership is re-read every ``membership_refresh_s`` so hosts that
        join after the first submit still receive work; a rebuild preserves
        nothing but the agent set (the executor is per-scheduler, in-flight
        calls on the old one complete normally)."""
        now = time.monotonic()
        with self._scheduler_lock:
            stale = (
                now - self._scheduler_read_ts > self.membership_refresh_s
            )
            if self._scheduler is not None and not stale:
                return self._scheduler
            if self._scheduler is not None:
                agents, store_to_agent = self._read_agents()
                self._scheduler_read_ts = now
                if {a.address for a in agents} == (
                    self._scheduler.agent_addresses
                ):
                    return self._scheduler
                old, self._scheduler = self._scheduler, None
                old.shutdown(cancel=False)
            else:
                agents, store_to_agent = self._read_agents()
                self._scheduler_read_ts = now
            self._scheduler = ClusterScheduler(
                agents,
                store_to_agent,
                width=getattr(self, "_total_workers", len(agents)),
            )
            self._scheduler.on_agent_dead = self._evict_host
            return self._scheduler

    def refresh_scheduler(self) -> ClusterScheduler:
        """Force a membership re-read (joins/leaves are otherwise picked up
        within ``membership_refresh_s``)."""
        with self._scheduler_lock:
            self._scheduler_read_ts = 0.0
        return self.scheduler()

    def register_named_actor(
        self,
        name: str,
        handle: ActorHandle,
        host_id: Optional[str] = None,
    ) -> None:
        """``host_id`` names the cluster host the actor RUNS ON (the
        placement target for remote spawns, this host otherwise) so the
        registry can sweep the name when that host retires."""
        if host_id is None:
            host_id = self.host_id
        try:
            self.registry.call(
                "register_actor", name, list(handle.address), handle.pid,
                host_id,
            )
        except ValueError:
            # Name taken. If the holder is dead (crashed run that never
            # unregistered), evict the stale record and claim the name;
            # a live holder is a real conflict.
            existing = self.lookup_named_actor(name)
            if existing is not None and existing.ping(timeout=2.0):
                raise
            self.registry.call("unregister_actor", name)
            self.registry.call(
                "register_actor", name, list(handle.address), handle.pid,
                host_id,
            )

    def unregister_named_actor(self, name: str) -> None:
        try:
            self.registry.call_oneway("unregister_actor", name)
        except ActorDiedError:
            pass

    def lookup_named_actor(self, name: str) -> Optional[ActorHandle]:
        record = self.registry.call("lookup_actor", name)
        if record is None:
            return None
        return ActorHandle(
            tuple(record["address"]), pid=record.get("pid"), name=name
        )

    def reregister(self) -> None:
        """(Re-)announce this host to the registry. ``register_host`` is an
        idempotent upsert, so the periodic heartbeat in ``serve_forever``
        re-admits a host the scheduler evicted on a false-positive death
        (e.g. a transient TCP reset) — the rejoin path ADVICE r1 called
        for. Schedulers pick the host back up on their next membership
        refresh."""
        self.registry.call(
            "register_host",
            self.host_id,
            list(self.agent.address),
            list(self.store_server.address),
            self.agent.call("num_workers"),
        )

    def leave(self) -> None:
        try:
            self.registry.call_oneway("unregister_host", self.host_id)
        except ActorDiedError:
            pass
        if self._scheduler is not None:
            self._scheduler.shutdown()
        self._stripe_pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# Bootstrap helpers (used by runtime.init / init_cluster)
# ---------------------------------------------------------------------------


def start_host_services(
    runtime_dir: str,
    num_workers: int,
    advertise_host: str,
) -> Tuple[ActorHandle, ActorHandle]:
    """Spawn this host's agent + store server (TCP-bound)."""
    from .store import _default_shm_dir

    agent = spawn_actor(
        HostAgent,
        runtime_dir,
        num_workers,
        advertise_host,
        runtime_dir=runtime_dir,
        host=advertise_host,
        daemon=False,  # the agent spawns its own worker pool (and actors)
    )
    store_server = spawn_actor(
        StoreServer,
        _default_shm_dir(),
        runtime_dir=runtime_dir,
        host=advertise_host,
    )
    return agent, store_server


def serve_forever(
    poll_s: float = 1.0, heartbeat_s: float = 10.0
) -> None:
    """Block while this worker host's services run; returns when the
    registry becomes unreachable (head shut down).

    Every ``heartbeat_s`` the host re-registers with the registry — the
    membership heartbeat that re-admits a live host evicted by a
    false-positive death verdict (see ``ClusterClient.reregister``)."""
    from . import get_context

    ctx = get_context()
    if ctx.cluster is None:
        raise RuntimeError("not joined to a cluster")
    last_beat = time.monotonic()
    while True:
        time.sleep(poll_s)
        if not ctx.cluster.registry.ping(timeout=5.0):
            return
        if time.monotonic() - last_beat >= heartbeat_s:
            last_beat = time.monotonic()
            try:
                ctx.cluster.reregister()
            except ActorDiedError:
                return


def _main(argv: List[str]) -> int:
    import argparse

    from . import init, shutdown

    parser = argparse.ArgumentParser(
        prog="python -m ray_shuffling_data_loader_tpu.runtime.cluster"
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    join = sub.add_parser("join", help="join a cluster as a worker host")
    join.add_argument("address", help="head address, tcp://host:port")
    join.add_argument("--num-workers", type=int, default=None)
    args = parser.parse_args(argv)

    if args.cmd == "join":
        ctx = init(address=args.address, num_workers=args.num_workers)
        print(
            f"[rsdl] host {ctx.cluster.host_id} joined {args.address}",
            flush=True,
        )
        try:
            serve_forever()
        finally:
            shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main(sys.argv[1:]))
