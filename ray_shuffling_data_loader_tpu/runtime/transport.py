"""Control-plane message transport.

Length-prefixed pickle frames over stream sockets. Addresses are tagged
tuples so the same protocol runs over unix-domain sockets on one host and
over TCP between TPU-VM hosts (the DCN control path) — replacing Ray's gRPC
control plane (reference depends on Ray core for all RPC, ``setup.py:14-20``).
"""

from __future__ import annotations

import asyncio
import pickle
import socket
import struct
from typing import Any, Tuple

_LEN = struct.Struct("<Q")

# Address = ("unix", path) | ("tcp", host, port)
Address = Tuple


def dumps(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


loads = pickle.loads


# -- sync client side -------------------------------------------------------


class Connection:
    """A blocking framed connection (one per calling thread)."""

    def __init__(self, address: Address, timeout: float = None):
        self.address = address
        if address[0] == "unix":
            self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self.sock.connect(address[1])
        elif address[0] == "tcp":
            self.sock = socket.create_connection((address[1], address[2]))
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        else:
            raise ValueError(f"unknown address scheme: {address!r}")
        if timeout is not None:
            self.sock.settimeout(timeout)

    def send(self, obj: Any) -> None:
        payload = dumps(obj)
        self.sock.sendall(_LEN.pack(len(payload)) + payload)

    def recv(self) -> Any:
        header = self._recv_exact(_LEN.size)
        (length,) = _LEN.unpack(header)
        return loads(self._recv_exact(length))

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            chunk = self.sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionError("connection closed by peer")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# -- asyncio side (used by actor servers and async clients) -----------------


async def read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    return loads(await reader.readexactly(length))


def write_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    payload = dumps(obj)
    writer.write(_LEN.pack(len(payload)) + payload)


async def open_connection(address: Address):
    if address[0] == "unix":
        return await asyncio.open_unix_connection(address[1])
    elif address[0] == "tcp":
        return await asyncio.open_connection(address[1], address[2])
    raise ValueError(f"unknown address scheme: {address!r}")


async def start_server(address: Address, handler):
    if address[0] == "unix":
        return await asyncio.start_unix_server(handler, path=address[1])
    elif address[0] == "tcp":
        return await asyncio.start_server(handler, address[1], address[2])
    raise ValueError(f"unknown address scheme: {address!r}")
