"""Control-plane message transport.

Length-prefixed pickle frames over stream sockets. Addresses are tagged
tuples so the same protocol runs over unix-domain sockets on one host and
over TCP between TPU-VM hosts (the DCN control path) — replacing Ray's gRPC
control plane (reference depends on Ray core for all RPC, ``setup.py:14-20``).

TCP security: frames are pickles, so accepting them from arbitrary peers
would be remote code execution. Every TCP connection therefore starts with
an HMAC challenge-response on the cluster secret (``$RSDL_CLUSTER_TOKEN``,
minted by ``init_cluster`` and carried in the ``tcp://host:port/<token>``
join address): the server sends a random nonce, the client answers
``HMAC-SHA256(token, nonce)``, and non-matching peers are dropped before
any pickle is touched. The secret itself never crosses the wire, so a DCN
observer (or a copy of a logged join address *after* rotation) cannot
replay its way in; possession of the current token remains the trust
anchor — run clusters inside a private VPC. Unix sockets rely on the 0o700
runtime directory instead, like Ray's on-host sockets.
"""

from __future__ import annotations

import asyncio
import hmac
import os
import pickle
import socket
import struct
from typing import Any, Callable, List, Optional, Sequence, Tuple

# Fault-injection plane (ISSUE 14 gate-integrity): lazy proxy — the
# transport fault sites import the plane only when first exercised.
from ray_shuffling_data_loader_tpu._lazy import lazy_module

faults = lazy_module("ray_shuffling_data_loader_tpu.runtime.faults")

_LEN = struct.Struct("<Q")
_AUTH_MAGIC = b"RSDLAUTH"
_NONCE_LEN = 16

# Vectored-frame marker: the top bit of the length prefix. When set, the
# remaining 63 bits are the length of a pickled ``(obj, [payload sizes])``
# header and ``sum(sizes)`` raw payload bytes follow the header directly —
# bulk data never transits pickle, and the receiver lands it straight in a
# caller-provided buffer (``recv_into`` an mmapped cache segment). Plain
# frames are unchanged, so the two framings interleave on one connection.
_VEC_FLAG = 1 << 63
# sendmsg iov count stays far below any IOV_MAX (Linux: 1024).
_SENDMSG_MAX_VECS = 512

# Data-plane socket buffer size. Default kernel buffers autotune from
# ~128 KB, which turns a multi-MB window transfer into dozens of
# event-loop/epoll ping-pongs — measured as the dominant cost of a
# loopback window fetch (r7: 2.3 ms of a 2.85 ms 4 MB fetch was
# scheduling, not copying). One setsockopt per connection buys back
# most of it; the kernel clamps to net.core.{r,w}mem_max so an
# over-ask degrades gracefully.
_SOCK_BUF_BYTES = 4 << 20


def _tune_sock(sock: socket.socket) -> None:
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, _SOCK_BUF_BYTES)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, _SOCK_BUF_BYTES)
    except OSError:
        pass

ENV_ZEROCOPY = "RSDL_TCP_ZEROCOPY"
_zerocopy: Optional[bool] = None  # tri-state cache, like the telemetry gates

ENV_TCP_STREAMS = "RSDL_TCP_STREAMS"
_MAX_TCP_STREAMS = 16
_tcp_streams: Optional[int] = None


def zerocopy_enabled() -> bool:
    """Is the zero-copy vectored fetch plane on (``RSDL_TCP_ZEROCOPY``)?
    Off by default — the gated contract shared with the telemetry planes:
    when off, no vectored frame is ever requested and the legacy pickle
    path runs untouched. One cached boolean after the first read."""
    global _zerocopy
    if _zerocopy is None:
        _zerocopy = os.environ.get(ENV_ZEROCOPY, "").strip().lower() in (
            "1", "on", "true", "yes",
        )
    return _zerocopy


def refresh_zerocopy_from_env() -> None:
    """Forget the cached gate; next check re-reads the env (tests/bench)."""
    global _zerocopy
    _zerocopy = None


def tcp_streams() -> int:
    """Persistent connections per peer for striped zero-copy fetches
    (``RSDL_TCP_STREAMS``; default 1 = single-stream, the pre-striping
    wire behavior untouched). Clamped to [1, 16] — each stream costs a
    socket + HMAC handshake per peer, and recv parallelism past the
    core count buys nothing. Read once, like the zerocopy gate; only
    meaningful with ``RSDL_TCP_ZEROCOPY`` on (the legacy pickle path
    never stripes)."""
    global _tcp_streams
    if _tcp_streams is None:
        try:
            n = int(os.environ.get(ENV_TCP_STREAMS, "1").strip() or "1")
        except ValueError:
            n = 1
        _tcp_streams = max(1, min(_MAX_TCP_STREAMS, n))
    return _tcp_streams


def refresh_tcp_streams_from_env() -> None:
    """Forget the cached stream count; next check re-reads (tests/bench)."""
    global _tcp_streams
    _tcp_streams = None


class OutOfBand:
    """An actor-method result whose bulk payload rides outside the pickle
    frame: ``meta`` is pickled into the reply header, ``buffers`` are
    buffer-protocol objects (mmaps, numpy views) streamed verbatim after
    it. ``keepalive`` pins whatever owns the buffers' memory until the
    reply is written."""

    __slots__ = ("meta", "buffers", "keepalive")

    def __init__(self, meta: Any, buffers: Sequence, keepalive: Any = None):
        self.meta = meta
        self.buffers = list(buffers)
        self.keepalive = keepalive


# Address = ("unix", path) | ("tcp", host, port)
Address = Tuple


def cluster_token() -> Optional[bytes]:
    token = os.environ.get("RSDL_CLUSTER_TOKEN")
    return token.encode() if token else None


def _challenge() -> bytes:
    return _AUTH_MAGIC + os.urandom(_NONCE_LEN)


def _response(token: bytes, challenge: bytes) -> bytes:
    return hmac.new(token, challenge, "sha256").digest()


def _answer_challenge_sync(sock: socket.socket, token: bytes) -> None:
    """Client side, blocking socket: read the server's nonce, answer with
    the keyed digest."""
    challenge = _recv_exact_sock(sock, _LEN.size)
    (length,) = _LEN.unpack(challenge)
    if length != len(_AUTH_MAGIC) + _NONCE_LEN:
        raise ConnectionError("malformed auth challenge")
    blob = _recv_exact_sock(sock, length)
    if not blob.startswith(_AUTH_MAGIC):
        raise ConnectionError("malformed auth challenge")
    answer = _response(token, blob)
    sock.sendall(_LEN.pack(len(answer)) + answer)


def _recv_exact_sock(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("connection closed by peer")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def sendmsg_all(
    sock: socket.socket, views: Sequence, timeout_s: float = 120.0
) -> None:
    """``sendall`` over a scatter-gather list via ``sendmsg``, advancing
    across partial sends without coalescing buffers in user space. Works
    on blocking AND non-blocking sockets: on ``EAGAIN`` it waits for
    writability with ``select`` (bounded by ``timeout_s`` per wait) —
    the actor host calls this from an executor thread on a socket whose
    event loop owns the fd, so the socket's blocking mode must not be
    touched. ``sendmsg`` releases the GIL, so concurrent replies to
    different peers stream on different cores."""
    import select as _select

    # poll(), not select(): select raises ValueError for any fd >= 1024
    # (FD_SETSIZE) — easily exceeded on a serving host once striping
    # multiplies per-peer connections.
    poller = _select.poll()
    poller.register(sock.fileno(), _select.POLLOUT)
    queue = [memoryview(v).cast("B") for v in views if memoryview(v).nbytes]
    while queue:
        try:
            sent = sock.sendmsg(queue[:_SENDMSG_MAX_VECS])
        except InterruptedError:
            continue
        except BlockingIOError:
            if not poller.poll(timeout_s * 1000.0):
                raise ConnectionError(
                    f"peer stalled a vectored send > {timeout_s:.0f}s"
                ) from None
            continue
        while sent:
            head = queue[0]
            if sent >= head.nbytes:
                sent -= head.nbytes
                queue.pop(0)
            else:
                queue[0] = head[sent:]
                sent = 0


def dumps(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def vectored_frames(obj: Any, buffers: Sequence) -> List[memoryview]:
    """THE encoder of the vectored wire frame, as a scatter-gather list:
    ``[len|_VEC_FLAG][pickle((obj, sizes))][payload bytes...]``. Every
    sender (sync ``send_vectored``, asyncio ``write_frame_vectored``,
    the actor host's executor-thread reply) builds its frame here so the
    layout can never drift between them."""
    views = [memoryview(b).cast("B") for b in buffers]
    header = dumps((obj, [v.nbytes for v in views]))
    return [
        memoryview(_LEN.pack(_VEC_FLAG | len(header))),
        memoryview(header),
        *views,
    ]


loads = pickle.loads


# -- sync client side -------------------------------------------------------


class Connection:
    """A blocking framed connection (one per calling thread)."""

    def __init__(self, address: Address, timeout: float = None):
        self.address = address
        if address[0] == "unix":
            self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            # Timeout must cover connect() too: a half-dead peer (host up,
            # process wedged) hangs the connect, not just the recv.
            if timeout is not None:
                self.sock.settimeout(timeout)
            self.sock.connect(address[1])
        elif address[0] == "tcp":
            self.sock = socket.create_connection(
                (address[1], address[2]), timeout=timeout
            )
            try:
                self.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                _tune_sock(self.sock)
                token = cluster_token()
                if token is not None:
                    # Don't hang forever on a server that never challenges.
                    self.sock.settimeout(30.0)
                    _answer_challenge_sync(self.sock, token)
                    self.sock.settimeout(timeout)
            except BaseException:
                # Auth/handshake failed: a retry loop in the actor layer
                # must not accumulate leaked fds until EMFILE.
                self.sock.close()
                raise
        else:
            raise ValueError(f"unknown address scheme: {address!r}")
        if timeout is not None:
            self.sock.settimeout(timeout)

    def send(self, obj: Any) -> None:
        if faults.enabled():
            # PRE-send: a fault here models a reset before any bytes hit
            # the wire, which is the retry-safe class (the peer never saw
            # the frame) — the ActorHandle retry layer leans on that.
            faults.fire("transport.send")
        payload = dumps(obj)
        self.sock.sendall(_LEN.pack(len(payload)) + payload)

    def send_vectored(self, obj: Any, buffers: Sequence) -> None:
        """Send ``obj`` plus raw payload buffers as ONE vectored frame:
        header and payload hit the wire through a single ``sendmsg``
        scatter-gather call (no intermediate ``bytes`` join, no pickle of
        the payload). The receiver must use :meth:`recv_frame`.

        Today's production bulk flow is server->client (StoreServer
        replies via the asyncio :func:`write_frame_vectored`); this sync
        send side is the client->server half of the same framing —
        covered by the transport tests and reserved for a zero-copy put
        path."""
        if faults.enabled():
            faults.fire("transport.send")
        self._sendmsg_all(vectored_frames(obj, buffers))

    def _sendmsg_all(self, views: List[memoryview]) -> None:
        """sendall over a scatter-gather list, advancing across partial
        sends without ever coalescing the buffers in user space."""
        sendmsg_all(self.sock, views)

    def recv(self) -> Any:
        return self.recv_frame()[0]

    def recv_frame(
        self, into: Optional[Callable[[int], Any]] = None
    ) -> Tuple[Any, Optional[memoryview]]:
        """Read one frame. Plain frames return ``(obj, None)``. Vectored
        frames return ``(obj, payload_view)`` with the payload landed via
        ``recv_into`` in the buffer ``into(total_bytes)`` returns (an
        mmapped cache file on the fetch path) — or a throwaway bytearray
        when no allocator is given. An allocator carrying a truthy
        ``wants_meta`` attribute is called ``into(total_bytes, obj)``
        instead — the striped fetch plane needs the reply's stripe
        byte-range (carried in the header object) to hand back the right
        window of the shared destination mapping."""
        if faults.enabled():
            faults.fire("transport.recv")
        header = self._recv_exact(_LEN.size)
        (length,) = _LEN.unpack(header)
        if not length & _VEC_FLAG:
            return loads(self._recv_exact(length)), None
        obj, sizes = loads(self._recv_exact(length & ~_VEC_FLAG))
        total = int(sum(sizes))
        if into is None:
            raw = bytearray(total)
        elif getattr(into, "wants_meta", False):
            raw = into(total, obj)
        else:
            raw = into(total)
        # _recv_exact_into creates and RELEASES its own views: on a
        # mid-payload failure no memoryview over ``raw`` may survive
        # into the traceback — the fetch path's error cleanup closes the
        # underlying mmap, and a still-exported view would turn the
        # recoverable ConnectionError into BufferError at close().
        self._recv_exact_into(raw, total)
        return obj, memoryview(raw).cast("B")[:total]

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            chunk = self.sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionError("connection closed by peer")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def _recv_exact_into(self, buf, n: int) -> None:
        """Fill ``buf[:n]`` from the socket. The view over ``buf`` is
        released on EVERY exit path (the caller may need to close the
        buffer's mmap during exception cleanup — see recv_frame)."""
        view = memoryview(buf).cast("B")
        try:
            off = 0
            while off < n:
                got = self.sock.recv_into(view[off:n])
                if not got:
                    raise ConnectionError("connection closed by peer")
                off += got
        finally:
            view.release()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# -- asyncio side (used by actor servers and async clients) -----------------


async def read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length & _VEC_FLAG:
        # Vectored frames only flow server -> sync fetch client; an actor
        # server (or the async demux client) receiving one is a protocol
        # violation — fail the connection rather than unpickle garbage.
        raise ConnectionError("unexpected vectored frame")
    return loads(await reader.readexactly(length))


def write_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    payload = dumps(obj)
    writer.write(_LEN.pack(len(payload)) + payload)


def write_frame_vectored(
    writer: asyncio.StreamWriter, obj: Any, buffers: Sequence
) -> None:
    """Server side of a vectored reply: pickled header, then each payload
    buffer written as-is (the transport sends what it can immediately and
    buffers only the remainder — no payload pickle, no join). Sources may
    be released once this returns: asyncio copies unsent tails."""
    for v in vectored_frames(obj, buffers):
        if v.nbytes:
            writer.write(v)


async def open_connection(address: Address):
    if address[0] == "unix":
        return await asyncio.open_unix_connection(address[1])
    elif address[0] == "tcp":
        reader, writer = await asyncio.open_connection(
            address[1], address[2]
        )
        token = cluster_token()
        if token is not None:
            try:
                header = await asyncio.wait_for(
                    reader.readexactly(_LEN.size), 30.0
                )
                (length,) = _LEN.unpack(header)
                if length != len(_AUTH_MAGIC) + _NONCE_LEN:
                    raise ConnectionError("malformed auth challenge")
                blob = await reader.readexactly(length)
                if not blob.startswith(_AUTH_MAGIC):
                    raise ConnectionError("malformed auth challenge")
                answer = _response(token, blob)
                writer.write(_LEN.pack(len(answer)) + answer)
                await writer.drain()
            except BaseException:
                # Close the transport on auth failure so retry loops don't
                # leak fds / leave destroyed-task noise behind.
                writer.close()
                raise
        return reader, writer
    raise ValueError(f"unknown address scheme: {address!r}")


async def start_server(address: Address, handler):
    if address[0] == "unix":
        return await asyncio.start_unix_server(handler, path=address[1])
    elif address[0] == "tcp":
        token = cluster_token()

        async def tcp_handler(reader, writer):
            # Data-plane socket + write-buffer tuning: large socket
            # buffers (see _SOCK_BUF_BYTES) and a matching asyncio
            # write high-water mark, so a multi-MB vectored reply
            # drains in a few loop iterations instead of dozens.
            sock = writer.get_extra_info("socket")
            if sock is not None:
                _tune_sock(sock)
            try:
                writer.transport.set_write_buffer_limits(
                    high=_SOCK_BUF_BYTES
                )
            except (AttributeError, RuntimeError):
                pass
            # Gate BEFORE any pickle touches peer bytes: challenge the
            # peer with a nonce; the first frame back must be the keyed
            # digest. 10 s auth deadline so half-open peers can't pin
            # server tasks.
            if token is not None:
                try:
                    challenge = _challenge()
                    writer.write(_LEN.pack(len(challenge)) + challenge)
                    await writer.drain()
                    header = await asyncio.wait_for(
                        reader.readexactly(_LEN.size), 10.0
                    )
                    (length,) = _LEN.unpack(header)
                    if length > 4096:
                        raise ConnectionError("oversized auth frame")
                    blob = await asyncio.wait_for(
                        reader.readexactly(length), 10.0
                    )
                    expected = _response(token, challenge)
                    if not hmac.compare_digest(blob, expected):
                        raise ConnectionError("bad auth response")
                except (
                    asyncio.IncompleteReadError,
                    asyncio.TimeoutError,
                    ConnectionError,
                    OSError,
                ):
                    try:
                        writer.close()
                    except Exception:
                        pass
                    return
            await handler(reader, writer)

        return await asyncio.start_server(tcp_handler, address[1], address[2])
    raise ValueError(f"unknown address scheme: {address!r}")
