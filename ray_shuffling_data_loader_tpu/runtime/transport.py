"""Control-plane message transport.

Length-prefixed pickle frames over stream sockets. Addresses are tagged
tuples so the same protocol runs over unix-domain sockets on one host and
over TCP between TPU-VM hosts (the DCN control path) — replacing Ray's gRPC
control plane (reference depends on Ray core for all RPC, ``setup.py:14-20``).

TCP security: frames are pickles, so accepting them from arbitrary peers
would be remote code execution. Every TCP connection therefore starts with
an HMAC challenge-response on the cluster secret (``$RSDL_CLUSTER_TOKEN``,
minted by ``init_cluster`` and carried in the ``tcp://host:port/<token>``
join address): the server sends a random nonce, the client answers
``HMAC-SHA256(token, nonce)``, and non-matching peers are dropped before
any pickle is touched. The secret itself never crosses the wire, so a DCN
observer (or a copy of a logged join address *after* rotation) cannot
replay its way in; possession of the current token remains the trust
anchor — run clusters inside a private VPC. Unix sockets rely on the 0o700
runtime directory instead, like Ray's on-host sockets.
"""

from __future__ import annotations

import asyncio
import hmac
import os
import pickle
import socket
import struct
from typing import Any, Optional, Tuple

from . import faults

_LEN = struct.Struct("<Q")
_AUTH_MAGIC = b"RSDLAUTH"
_NONCE_LEN = 16

# Address = ("unix", path) | ("tcp", host, port)
Address = Tuple


def cluster_token() -> Optional[bytes]:
    token = os.environ.get("RSDL_CLUSTER_TOKEN")
    return token.encode() if token else None


def _challenge() -> bytes:
    return _AUTH_MAGIC + os.urandom(_NONCE_LEN)


def _response(token: bytes, challenge: bytes) -> bytes:
    return hmac.new(token, challenge, "sha256").digest()


def _answer_challenge_sync(sock: socket.socket, token: bytes) -> None:
    """Client side, blocking socket: read the server's nonce, answer with
    the keyed digest."""
    challenge = _recv_exact_sock(sock, _LEN.size)
    (length,) = _LEN.unpack(challenge)
    if length != len(_AUTH_MAGIC) + _NONCE_LEN:
        raise ConnectionError("malformed auth challenge")
    blob = _recv_exact_sock(sock, length)
    if not blob.startswith(_AUTH_MAGIC):
        raise ConnectionError("malformed auth challenge")
    answer = _response(token, blob)
    sock.sendall(_LEN.pack(len(answer)) + answer)


def _recv_exact_sock(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("connection closed by peer")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def dumps(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


loads = pickle.loads


# -- sync client side -------------------------------------------------------


class Connection:
    """A blocking framed connection (one per calling thread)."""

    def __init__(self, address: Address, timeout: float = None):
        self.address = address
        if address[0] == "unix":
            self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            # Timeout must cover connect() too: a half-dead peer (host up,
            # process wedged) hangs the connect, not just the recv.
            if timeout is not None:
                self.sock.settimeout(timeout)
            self.sock.connect(address[1])
        elif address[0] == "tcp":
            self.sock = socket.create_connection(
                (address[1], address[2]), timeout=timeout
            )
            try:
                self.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                token = cluster_token()
                if token is not None:
                    # Don't hang forever on a server that never challenges.
                    self.sock.settimeout(30.0)
                    _answer_challenge_sync(self.sock, token)
                    self.sock.settimeout(timeout)
            except BaseException:
                # Auth/handshake failed: a retry loop in the actor layer
                # must not accumulate leaked fds until EMFILE.
                self.sock.close()
                raise
        else:
            raise ValueError(f"unknown address scheme: {address!r}")
        if timeout is not None:
            self.sock.settimeout(timeout)

    def send(self, obj: Any) -> None:
        if faults.enabled():
            # PRE-send: a fault here models a reset before any bytes hit
            # the wire, which is the retry-safe class (the peer never saw
            # the frame) — the ActorHandle retry layer leans on that.
            faults.fire("transport.send")
        payload = dumps(obj)
        self.sock.sendall(_LEN.pack(len(payload)) + payload)

    def recv(self) -> Any:
        if faults.enabled():
            faults.fire("transport.recv")
        header = self._recv_exact(_LEN.size)
        (length,) = _LEN.unpack(header)
        return loads(self._recv_exact(length))

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            chunk = self.sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionError("connection closed by peer")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# -- asyncio side (used by actor servers and async clients) -----------------


async def read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    return loads(await reader.readexactly(length))


def write_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    payload = dumps(obj)
    writer.write(_LEN.pack(len(payload)) + payload)


async def open_connection(address: Address):
    if address[0] == "unix":
        return await asyncio.open_unix_connection(address[1])
    elif address[0] == "tcp":
        reader, writer = await asyncio.open_connection(
            address[1], address[2]
        )
        token = cluster_token()
        if token is not None:
            try:
                header = await asyncio.wait_for(
                    reader.readexactly(_LEN.size), 30.0
                )
                (length,) = _LEN.unpack(header)
                if length != len(_AUTH_MAGIC) + _NONCE_LEN:
                    raise ConnectionError("malformed auth challenge")
                blob = await reader.readexactly(length)
                if not blob.startswith(_AUTH_MAGIC):
                    raise ConnectionError("malformed auth challenge")
                answer = _response(token, blob)
                writer.write(_LEN.pack(len(answer)) + answer)
                await writer.drain()
            except BaseException:
                # Close the transport on auth failure so retry loops don't
                # leak fds / leave destroyed-task noise behind.
                writer.close()
                raise
        return reader, writer
    raise ValueError(f"unknown address scheme: {address!r}")


async def start_server(address: Address, handler):
    if address[0] == "unix":
        return await asyncio.start_unix_server(handler, path=address[1])
    elif address[0] == "tcp":
        token = cluster_token()

        async def tcp_handler(reader, writer):
            # Gate BEFORE any pickle touches peer bytes: challenge the
            # peer with a nonce; the first frame back must be the keyed
            # digest. 10 s auth deadline so half-open peers can't pin
            # server tasks.
            if token is not None:
                try:
                    challenge = _challenge()
                    writer.write(_LEN.pack(len(challenge)) + challenge)
                    await writer.drain()
                    header = await asyncio.wait_for(
                        reader.readexactly(_LEN.size), 10.0
                    )
                    (length,) = _LEN.unpack(header)
                    if length > 4096:
                        raise ConnectionError("oversized auth frame")
                    blob = await asyncio.wait_for(
                        reader.readexactly(length), 10.0
                    )
                    expected = _response(token, challenge)
                    if not hmac.compare_digest(blob, expected):
                        raise ConnectionError("bad auth response")
                except (
                    asyncio.IncompleteReadError,
                    asyncio.TimeoutError,
                    ConnectionError,
                    OSError,
                ):
                    try:
                        writer.close()
                    except Exception:
                        pass
                    return
            await handler(reader, writer)

        return await asyncio.start_server(tcp_handler, address[1], address[2])
    raise ValueError(f"unknown address scheme: {address!r}")
