"""Deterministic fault-injection plane for the runtime (chaos testing).

Production data loaders are only trustworthy when their failure handling
is *exercised*, not just written: tf.data-service-style disaggregated
input pipelines treat injectable, recoverable failures as part of the
service contract, and the PR-2 audit digests give this repo an oracle
that can prove recovery preserved exactly-once delivery. This module is
the injection half of that story: named fault sites threaded through the
runtime (transport send/recv, store get/put, task stage entry/exit,
actor dispatch, queue producer) fire scripted faults *deterministically*
so a chaos run is reproducible bit-for-bit.

Env contract (same zero-overhead-off pattern as ``telemetry/_env.py``):

* ``RSDL_FAULTS`` — comma-separated rules, each
  ``site[/role]:kind:prob[@epoch][xN]``:

  - ``site``: the injection-site name (``transport.send``, ``store.get``,
    ``task.map``, ``task.reduce``, ``actor.<Class>``, ``queue.producer``).
  - ``/role`` (optional): only fire in processes with that role —
    ``driver`` (default for any process), ``task`` (pool workers),
    ``actor`` (actor hosts). Without it the rule fires everywhere the
    site exists.
  - ``kind``: what happens — ``crash`` / ``crash-entry`` / ``crash-exit``
    (raise :class:`FaultInjected`), ``reset`` (ConnectionResetError),
    ``delay`` / ``stall`` (sleep ``RSDL_FAULTS_DELAY_S``), ``lost`` /
    ``corrupt`` (store sites raise Object{Lost,Corrupt}Error), ``fail``
    (OSError), ``kill`` (``os._exit``), ``wedge`` (sleep
    ``RSDL_FAULTS_WEDGE_S``).
  - ``prob``: per-invocation firing probability in (0, 1].
  - ``@epoch`` (optional): only fire for that epoch (sites that know it).
  - ``xN`` (optional): fire at most N times *per process*.

* ``RSDL_FAULTS_SEED`` — the determinism anchor: the fire/no-fire
  decision for invocation *i* of a site is a pure function of
  ``(seed, site, kind, i)`` (splitmix64), so a fixed seed replays the
  same schedule. Per-process invocation counters make the schedule
  deterministic per process; pipeline-level determinism follows when the
  task placement is (as in the tests' fixed-size pools).

With ``RSDL_FAULTS`` unset every site costs one cached boolean check —
the same no-op constant the telemetry gates pay.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

ENV_FAULTS = "RSDL_FAULTS"
ENV_SEED = "RSDL_FAULTS_SEED"
ENV_DELAY_S = "RSDL_FAULTS_DELAY_S"
ENV_WEDGE_S = "RSDL_FAULTS_WEDGE_S"

_KINDS = {
    "crash",
    "crash-entry",
    "crash-exit",
    "reset",
    "delay",
    "stall",
    "lost",
    "corrupt",
    "fail",
    "kill",
    "wedge",
}

_enabled: Optional[bool] = None  # tri-state: None = env not read yet
_lock = threading.Lock()
_rules: Optional[List["Rule"]] = None
_invocations: Dict[str, int] = {}  # site -> per-process invocation count
_fired: Dict[Tuple[str, str], int] = {}  # (site, kind) -> fire count
_role = "driver"


class FaultInjected(RuntimeError):
    """An injected crash fault — deliberately NOT a subclass of any
    domain error, so recovery paths that catch it are proving they
    tolerate arbitrary task/stage crashes."""

    def __init__(self, site: str, kind: str):
        super().__init__(f"injected fault at {site} ({kind})")
        self.site = site
        self.kind = kind


@dataclass
class Rule:
    site: str
    kind: str
    prob: float
    role: Optional[str] = None
    epoch: Optional[int] = None
    max_fires: Optional[int] = None
    fired: int = field(default=0)


def enabled() -> bool:
    """Is fault injection armed in this process? Cached after the first
    env read — the faults-off hot path pays one boolean check."""
    global _enabled
    if _enabled is None:
        _enabled = bool(os.environ.get(ENV_FAULTS, "").strip())
    return _enabled


def refresh_from_env() -> None:
    """Forget cached state; the next check re-reads the env (test hook)."""
    global _enabled, _rules
    with _lock:
        _enabled = None
        _rules = None
        _invocations.clear()
        _fired.clear()


def reset() -> None:
    """Disarm completely: drop the env spec and all cached state."""
    os.environ.pop(ENV_FAULTS, None)
    refresh_from_env()


def configure(spec: str, seed: Optional[int] = None) -> None:
    """Arm a fault schedule for this process AND (via the environment)
    every process spawned after this call — like ``telemetry.enable``,
    call before the worker pool first spawns. Parses eagerly so a typo'd
    schedule fails at the call site, not silently mid-run."""
    parse_spec(spec)  # validate
    os.environ[ENV_FAULTS] = spec
    if seed is not None:
        os.environ[ENV_SEED] = str(int(seed))
    refresh_from_env()


def set_role(role: str) -> None:
    """Tag this process's role (``driver``/``task``/``actor``) for rule
    ``/role`` filters. Called by the task-worker and actor entrypoints."""
    global _role
    _role = role


def role() -> str:
    return _role


def parse_spec(spec: str) -> List[Rule]:
    """``site[/role]:kind:prob[@epoch][xN],...`` -> rules (raises
    ValueError on malformed entries)."""
    rules: List[Rule] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"bad fault rule {entry!r}: want site[/role]:kind:prob"
                "[@epoch][xN]"
            )
        site, kind, tail = parts
        rule_role = None
        if "/" in site:
            site, rule_role = site.split("/", 1)
        if kind not in _KINDS:
            raise ValueError(
                f"bad fault kind {kind!r} in {entry!r}; known: "
                f"{sorted(_KINDS)}"
            )
        epoch = None
        max_fires = None
        if "x" in tail:
            tail, max_part = tail.rsplit("x", 1)
            max_fires = int(max_part)
        if "@" in tail:
            tail, epoch_part = tail.split("@", 1)
            epoch = int(epoch_part)
        prob = float(tail)
        if not (0.0 < prob <= 1.0):
            raise ValueError(f"bad fault prob {prob!r} in {entry!r}")
        rules.append(
            Rule(
                site=site,
                kind=kind,
                prob=prob,
                role=rule_role,
                epoch=epoch,
                max_fires=max_fires,
            )
        )
    return rules


def _get_rules() -> List[Rule]:
    global _rules
    with _lock:
        if _rules is None:
            spec = os.environ.get(ENV_FAULTS, "")
            try:
                _rules = parse_spec(spec)
            except ValueError:
                # A malformed schedule in a spawned worker must not sink
                # the data path; the driver's configure() already raised.
                logger.error("faults: unparseable %s=%r; injection off",
                             ENV_FAULTS, spec)
                _rules = []
        return _rules


def _seed() -> int:
    try:
        return int(os.environ.get(ENV_SEED, "0"))
    except ValueError:
        return 0


_MASK = (1 << 64) - 1


def _splitmix64(z: int) -> int:
    z = (z + 0x9E3779B97F4A7C15) & _MASK
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
    return (z ^ (z >> 31)) & _MASK


def _decision(site: str, kind: str, invocation: int) -> float:
    """Uniform [0, 1) drawn deterministically from (seed, site, kind,
    invocation) — the reproducibility contract of the whole plane."""
    h = _seed() & _MASK
    for token in (site, kind):
        for ch in token.encode():
            h = _splitmix64(h ^ ch)
    return _splitmix64(h ^ invocation) / float(1 << 64)


def _base_kind(kind: str) -> str:
    return kind.split("-", 1)[0]


def should_fire(
    site: str, epoch: Optional[int] = None, point: Optional[str] = None
) -> Optional[str]:
    """Decide whether a fault fires at this site invocation; returns the
    BASE kind to act on (``crash``, ``lost``, ...) or None. Sites with
    bespoke actions (the store's lost/corrupt) call this and act
    themselves; everything else goes through :func:`fire`."""
    if not enabled():
        return None
    rules = _get_rules()
    if not rules:
        return None
    with _lock:
        inv = _invocations.get(site, 0)
        _invocations[site] = inv + 1
    for rule in rules:
        if rule.site != site:
            continue
        if rule.role is not None and rule.role != _role:
            continue
        if rule.epoch is not None and rule.epoch != epoch:
            continue
        # entry/exit-suffixed kinds only fire at their matching point;
        # unsuffixed kinds fire at any point.
        suffix = (
            rule.kind.split("-", 1)[1] if "-" in rule.kind else None
        )
        if suffix is not None and suffix != point:
            continue
        if rule.max_fires is not None and rule.fired >= rule.max_fires:
            continue  # unlocked fast path; re-checked under the lock
        if rule.prob < 1.0 and _decision(
            site, rule.kind, inv
        ) >= rule.prob:
            continue
        with _lock:
            # Check-and-act atomically: concurrent threads racing an
            # unlocked cap check could both fire, overshooting xN — and
            # the CI chaos lane's no-flake argument depends on the caps
            # being exact.
            if rule.max_fires is not None and rule.fired >= rule.max_fires:
                continue
            rule.fired += 1
            key = (site, _base_kind(rule.kind))
            _fired[key] = _fired.get(key, 0) + 1
        _note_fired(site, _base_kind(rule.kind), epoch)
        return _base_kind(rule.kind)
    return None


def _note_fired(site: str, kind: str, epoch: Optional[int]) -> None:
    logger.warning(
        "faults: injecting %s at %s (epoch=%s, pid=%d, role=%s)",
        kind, site, epoch, os.getpid(), _role,
    )
    try:
        from ray_shuffling_data_loader_tpu.telemetry import metrics as _m

        _m.safe_inc("faults.injected", site=site, kind=kind)
    except Exception:
        pass


def _delay_s() -> float:
    try:
        return float(os.environ.get(ENV_DELAY_S, "0.05"))
    except ValueError:
        return 0.05


def _wedge_s() -> float:
    try:
        return float(os.environ.get(ENV_WEDGE_S, "30"))
    except ValueError:
        return 30.0


def fire(
    site: str, epoch: Optional[int] = None, point: Optional[str] = None
) -> None:
    """Decide AND act: raise/sleep/kill per the armed rule's kind.
    Call sites guard with ``if faults.enabled():`` so the disabled path
    never enters here."""
    kind = should_fire(site, epoch=epoch, point=point)
    if kind is None:
        return
    if kind == "crash":
        raise FaultInjected(site, kind)
    if kind == "reset":
        raise ConnectionResetError(f"injected connection reset at {site}")
    if kind == "fail":
        raise OSError(f"injected failure at {site}")
    if kind in ("delay", "stall"):
        time.sleep(_delay_s())
        return
    if kind == "wedge":
        time.sleep(_wedge_s())
        return
    if kind == "kill":
        # SIGKILL-equivalent: no atexit, no teardown — the supervision
        # paths must cope with an abrupt death, not a graceful exit.
        os._exit(17)
    if kind in ("lost", "corrupt"):
        # Store-specific kinds reaching the generic path (mis-sited
        # rule): treat as a crash so the mistake is loud.
        raise FaultInjected(site, kind)


def fired_counts() -> Dict[Tuple[str, str], int]:
    """Per-(site, kind) fire counts in THIS process (tests assert the
    schedule actually fired, not just that the run survived)."""
    with _lock:
        return dict(_fired)
