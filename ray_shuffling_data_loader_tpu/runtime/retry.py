"""One retry policy for the whole runtime.

Before this module the runtime had four hand-rolled retry loops
(``connect_actor``'s uncapped exponential sleep, ``wait_ready``'s ping
loop, the cluster scheduler's ping ladder, the shuffle driver's none at
all) that disagreed about backoff, caps, and jitter — and the uncapped
one thundering-herded N trainers in lockstep after a queue-actor
restart. :class:`RetryPolicy` is the single definition: bounded
attempts, exponential backoff with a cap, decorrelating jitter, and an
optional overall deadline. Every retry increments the
``recovery.retries{site=...}`` counter (metrics registry, when enabled)
and drops a ``recovery:retry`` instant on the trace timeline, so a chaos
run's recovery work is observable with the same tooling as its schedule.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass
from typing import Iterator, Optional, Tuple


def _observe_retry(site: str, attempt: int, error: str) -> None:
    """Recovery observability: counter + trace instant, both no-ops when
    the respective telemetry half is off. Never raises into the retry
    loop (a broken metrics source must not break recovery itself)."""
    try:
        from ray_shuffling_data_loader_tpu import telemetry

        telemetry.metrics.safe_inc("recovery.retries", site=site)
        if telemetry.enabled():
            telemetry.instant(
                "recovery:retry", cat="recovery", site=site,
                attempt=attempt, error=error[:200],
            )
    except Exception:
        pass


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with jitter.

    ``max_attempts`` counts the first try: 3 means one try plus two
    retries. ``jitter`` is the randomized fraction of each delay —
    ``delay * (1 - jitter) + U[0, jitter) * delay`` — so N clients
    retrying after one shared event (a queue-actor restart) decorrelate
    instead of stampeding in lockstep. ``deadline_s`` bounds the total
    time across attempts (sleeps are clipped to it)."""

    max_attempts: int = 5
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    deadline_s: Optional[float] = None

    def delay(self, attempt: int) -> float:
        """The backoff before retry ``attempt`` (1-based: the delay
        after the ``attempt``-th failure)."""
        d = min(
            self.max_delay_s,
            self.base_delay_s * (self.multiplier ** max(0, attempt - 1)),
        )
        if self.jitter > 0:
            d = d * (1.0 - self.jitter) + random.random() * self.jitter * d
        return d

    def attempts(self, site: str = "") -> Iterator[Tuple[int, "_Attempt"]]:
        """Iterate ``(attempt_number, handle)``; call
        ``handle.backoff(error)`` after a failure to sleep (and record
        the retry) before the next attempt. Stops after ``max_attempts``
        or when the deadline would be exceeded."""
        deadline = (
            None
            if self.deadline_s is None
            else time.monotonic() + self.deadline_s
        )
        for attempt in range(1, self.max_attempts + 1):
            yield attempt, _Attempt(self, site, attempt, deadline)
            if deadline is not None and time.monotonic() >= deadline:
                return

class _Attempt:
    __slots__ = ("_policy", "_site", "_attempt", "_deadline")

    def __init__(self, policy, site, attempt, deadline):
        self._policy = policy
        self._site = site
        self._attempt = attempt
        self._deadline = deadline

    def backoff(self, error: str = "") -> None:
        _observe_retry(self._site, self._attempt, error)
        d = self._policy.delay(self._attempt)
        if self._deadline is not None:
            d = min(d, max(0.0, self._deadline - time.monotonic()))
        if d > 0:
            time.sleep(d)


# Shared default policies, overridable via env for operators tuning a
# deployment (and for tests that want fast failure).
def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def connect_policy(num_retries: int) -> RetryPolicy:
    """Discovery backoff (``connect_actor``): capped at
    ``RSDL_CONNECT_MAX_BACKOFF_S`` (default 5 s) with 50% jitter — N
    trainers reconnecting after a queue-actor restart spread out instead
    of re-dialing in lockstep (the old loop doubled 1 s unbounded with
    zero jitter)."""
    try:
        cap = float(os.environ.get("RSDL_CONNECT_MAX_BACKOFF_S", "5"))
    except ValueError:
        cap = 5.0
    return RetryPolicy(
        max_attempts=max(1, num_retries),
        base_delay_s=0.5,
        max_delay_s=cap,
        multiplier=2.0,
        jitter=0.5,
    )


_call_policy_cache: Optional[RetryPolicy] = None


def call_policy() -> RetryPolicy:
    """Pre-send transport retry (``ActorHandle.call``): small and fast —
    its job is riding out one connection reset, not masking a dead
    actor (death still surfaces as ``ActorDiedError`` within ~0.3 s).
    The deadline bounds the TOTAL pre-send window even when the OS-level
    connect timeouts are long (a wedged-but-listening peer).

    Cached: this sits on the hottest control-plane path (every queue
    ack, every stats oneway), so the env reads happen once per process
    — :func:`refresh_policies` forgets the cache (test hook)."""
    global _call_policy_cache
    if _call_policy_cache is None:
        _call_policy_cache = RetryPolicy(
            max_attempts=_env_int("RSDL_CALL_RETRIES", 3),
            base_delay_s=0.05,
            max_delay_s=0.5,
            multiplier=2.0,
            jitter=0.5,
            deadline_s=_env_float("RSDL_CALL_DEADLINE_S", 10.0),
        )
    return _call_policy_cache


def refresh_policies() -> None:
    """Forget cached policies; the next use re-reads the env."""
    global _call_policy_cache
    _call_policy_cache = None


def stage_policy() -> RetryPolicy:
    """Shuffle stage (map/reduce task) re-execution budget: a poison
    task exhausts this and fails the epoch with ``StageFailedError``
    instead of retrying forever across hosts."""
    return RetryPolicy(
        max_attempts=_env_int("RSDL_STAGE_MAX_ATTEMPTS", 3),
        base_delay_s=0.05,
        max_delay_s=1.0,
        multiplier=2.0,
        jitter=0.5,
    )
