"""Disaggregated shuffle service: one shuffle plane, many jobs (ISSUE 15).

Today one driver owns one shuffle for one trainer group. This module
turns the shuffle/store/queue plane into a long-lived multi-tenant
*service*: concurrent :func:`~.shuffle.shuffle` calls — distinct
datasets, seeds, epoch windows, possibly distinct processes joined to
one runtime session — register **jobs** against the shared worker pool
and get

* **job-scoped namespaces** — named actors (batch queue, stats
  collectors), the live trial status, audit digest records, journal run
  identities, and the capacity ledger all carry the job id, so two
  same-shaped jobs can never clobber each other's resources or fold
  into each other's verdicts;
* **fair-share scheduling** — :class:`FairShareScheduler` interleaves
  stage tasks across jobs by weighted share (release the next task from
  the job with the smallest in-flight/weight ratio), so one straggling
  or flooding job cannot starve another out of the pool;
* **per-job epoch-window admission** — :func:`admit_epoch` holds a new
  epoch window back while the capacity ledger reports the shm budget
  over the admission watermark and other jobs are in flight, so
  concurrent windows never thrash the evictor;
* **cross-job hot-dataset sharing** — the shared decode-cache registry
  is re-keyed from session identity to *content identity*
  (:func:`cache_key`: file fingerprint + projection + narrowing) with
  refcounted per-job claims, so a second job over the same Parquet set
  rides the first job's decoded segments from its first epoch and the
  evictor never drops a segment a live job claims.

Env-gated ``RSDL_SERVICE=auto|off`` with the repo's zero-overhead-off
contract: unset means this module is never imported, no thread starts,
and the single-job code path is byte-for-byte unchanged (enforced by
the gate-integrity lint plane — every core-module import of this plane
is function-level behind an env check).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_shuffling_data_loader_tpu import telemetry
from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics

_ENV_MODE = "RSDL_SERVICE"
_ENV_JOB_ID = "RSDL_JOB_ID"
_ENV_JOB_NAME = "RSDL_JOB_NAME"
_ENV_JOB_WEIGHT = "RSDL_JOB_WEIGHT"
_ENV_ADMIT_FRAC = "RSDL_SERVICE_ADMIT_FRAC"
_ENV_ADMIT_TIMEOUT = "RSDL_SERVICE_ADMIT_TIMEOUT_S"

_OFF_VALUES = ("", "off", "0", "false", "no")


def mode() -> str:
    """The parsed ``RSDL_SERVICE`` value (``off`` when unset/disabled).
    Read per call — the plane is only ever consulted from call sites
    that already saw the env var set, so this is never on a hot path."""
    raw = os.environ.get(_ENV_MODE, "").strip().lower()
    if raw in _OFF_VALUES:
        return "off"
    return raw if raw else "off"


def enabled() -> bool:
    return mode() != "off"


# ---------------------------------------------------------------------------
# Jobs
# ---------------------------------------------------------------------------


class Job:
    """One tenant of the shuffle service: identity + scheduling weight.

    ``job_id`` is globally unique (name-pid-counter) and suffixes every
    job-scoped resource name; ``name`` is the stable human identity
    (journal run identity, default metrics label)."""

    __slots__ = (
        "job_id", "name", "weight", "pid", "created_ts", "ended_ts",
    )

    def __init__(self, job_id: str, name: str, weight: float):
        self.job_id = job_id
        self.name = name
        self.weight = float(weight)
        self.pid = os.getpid()
        self.created_ts = time.time()
        self.ended_ts: Optional[float] = None

    @property
    def running(self) -> bool:
        return self.ended_ts is None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "job_id": self.job_id,
            "name": self.name,
            "weight": self.weight,
            "pid": self.pid,
            "created_ts": self.created_ts,
            "ended_ts": self.ended_ts,
            "running": self.running,
        }


_jobs_lock = threading.Lock()
_jobs: Dict[str, Job] = {}
_job_counter = itertools.count()
_tls = threading.local()


def _default_weight() -> float:
    try:
        w = float(os.environ.get(_ENV_JOB_WEIGHT, "1.0"))
    except ValueError:
        w = 1.0
    return max(w, 0.001)  # zero/negative would starve the job forever


def _service_dir() -> Optional[str]:
    """``<runtime_dir>/service`` when a session is live, else None.
    Job records and the cache registry live here so every process
    joined to the session (distinct drivers, the obs endpoint owner)
    sees one consistent view."""
    from ray_shuffling_data_loader_tpu import runtime

    if not runtime.is_initialized():
        return None
    try:
        return os.path.join(runtime.get_context().runtime_dir, "service")
    except Exception:
        return None


def _write_job_record(job: Job) -> None:
    base = _service_dir()
    if base is None:
        return
    try:
        jobs_dir = os.path.join(base, "jobs")
        os.makedirs(jobs_dir, exist_ok=True)
        path = os.path.join(jobs_dir, f"{job.job_id}.json")
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(job.to_dict(), f)
        os.replace(tmp, path)
    except OSError:
        pass


def register_job(
    name: Optional[str] = None, weight: Optional[float] = None
) -> Job:
    """Register one tenant. ``name`` defaults to ``RSDL_JOB_NAME`` (or
    ``"job"``); ``weight`` to ``RSDL_JOB_WEIGHT`` (1.0). Also registers
    the service's ``/status`` section provider on first use when the
    obs endpoint is configured."""
    name = (name or os.environ.get(_ENV_JOB_NAME) or "job").strip()
    weight = _default_weight() if weight is None else max(float(weight), 0.001)
    with _jobs_lock:
        job_id = f"{name}-{os.getpid()}-{next(_job_counter)}"
        job = Job(job_id, name, weight)
        _jobs[job_id] = job
    _write_job_record(job)
    _maybe_register_status_provider()
    _metrics.safe_inc("service.jobs_registered")
    telemetry.emit_event(
        "job.registered", job=job_id, name=name, weight=weight
    )
    _set_active_gauge()
    return job


def end_job(job: Job) -> None:
    """Mark a job ended: release its decode-cache claims and drop its
    pending fair-share queue (in-flight tasks complete normally)."""
    if job is None or job.ended_ts is not None:
        return
    job.ended_ts = time.time()
    _write_job_record(job)
    try:
        release_claims(job.job_id)
    except Exception:
        pass
    sched = _scheduler_singleton()
    if sched is not None:
        sched.forget_job(job.job_id)
    telemetry.emit_event("job.ended", job=job.job_id, name=job.name)
    _set_active_gauge()


def _set_active_gauge() -> None:
    try:
        if _metrics.enabled():
            _metrics.registry.gauge("service.jobs_active").set(
                float(len(active_jobs()))
            )
    except Exception:
        pass


def active_jobs() -> List[Job]:
    with _jobs_lock:
        return [j for j in _jobs.values() if j.running]


def _record_live(rec: Dict[str, Any]) -> bool:
    """Is an on-disk job record genuinely live? ``running`` alone is
    not enough: a SIGKILLed driver never ran ``end_job``, and treating
    its record as live forever would pin its cache claims against the
    evictor and keep admission in multi-tenant mode. The pid-liveness
    probe is sound here — job records live in the session's runtime
    dir, and every process that can write one is on this host."""
    if not rec.get("running"):
        return False
    pid = rec.get("pid")
    if not pid:
        return False
    if int(pid) == os.getpid():
        return True
    try:
        os.kill(int(pid), 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True  # exists but not ours (EPERM)


def live_jobs_count() -> int:
    """Running jobs across EVERY process of the session (in-process
    registry + liveness-checked on-disk records) — the multi-tenancy
    signal admission keys on; the in-process count alone would leave
    cross-process tenants without admission control."""
    seen = {j.job_id for j in active_jobs()}
    for rec in jobs_snapshot():
        jid = rec.get("job_id")
        if jid in seen:
            continue
        if _record_live(rec):
            seen.add(jid)
    return len(seen)


def jobs_snapshot() -> List[Dict[str, Any]]:
    """Every job this session knows about: this process's registry
    merged with the on-disk records other drivers wrote (theirs win
    nothing — same job ids never collide across processes)."""
    with _jobs_lock:
        out = {j.job_id: j.to_dict() for j in _jobs.values()}
    base = _service_dir()
    if base is not None:
        jobs_dir = os.path.join(base, "jobs")
        try:
            names = os.listdir(jobs_dir)
        except OSError:
            names = []
        for fname in names:
            if not fname.endswith(".json"):
                continue
            try:
                with open(os.path.join(jobs_dir, fname)) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            out.setdefault(str(rec.get("job_id")), rec)
    return sorted(out.values(), key=lambda r: r.get("created_ts") or 0.0)


def current_job() -> Optional[Job]:
    """The ambient job: the :func:`job_context` threadlocal, else a
    process-wide job derived from ``RSDL_JOB_ID`` (spawned trainer
    ranks of a job-scoped driver inherit the id via env)."""
    job = getattr(_tls, "job", None)
    if job is not None:
        return job
    env_id = os.environ.get(_ENV_JOB_ID)
    if env_id:
        with _jobs_lock:
            job = _jobs.get(env_id)
            if job is None:
                job = Job(
                    env_id,
                    os.environ.get(_ENV_JOB_NAME) or env_id,
                    _default_weight(),
                )
                _jobs[env_id] = job
        return job
    return None


def set_current_job(job: Optional[Job]) -> None:
    _tls.job = job


@contextlib.contextmanager
def job_context(job: Optional[Job]):
    """Make ``job`` ambient for the block: resource names created
    inside are job-scoped and the telemetry context carries
    ``job=<id>`` (so spans, events, audit digests, and ledger ops —
    local and propagated to workers — attribute to the job)."""
    if job is None:
        yield
        return
    prev = getattr(_tls, "job", None)
    _tls.job = job
    try:
        with telemetry.context(job=job.job_id):
            yield
    finally:
        _tls.job = prev


def scoped_name(base: str, job: Optional[Job] = None) -> str:
    """Job-scope a session-wide resource name (named actors): two
    concurrent jobs using the same logical name get distinct resources
    instead of racing on one (the ISSUE 15 latent-collision fix)."""
    job = job if job is not None else current_job()
    if not enabled() or job is None or not base:
        return base
    suffix = f"--{job.job_id}"
    return base if base.endswith(suffix) else f"{base}{suffix}"


# ---------------------------------------------------------------------------
# Fair-share scheduling
# ---------------------------------------------------------------------------


class _ProxyFuture:
    """Task-future stand-in handed out while the fair-share dispatcher
    holds the task back. Duck-types :class:`~.tasks.TaskFuture` (done /
    result / waiter hooks) so ``runtime.wait`` and the shuffle driver's
    retry loops work unchanged; once dispatched it delegates to the
    real future."""

    __slots__ = ("_event", "_inner", "_waiters", "_lock")

    def __init__(self):
        self._event = threading.Event()
        self._inner = None
        self._waiters: List[threading.Event] = []
        self._lock = threading.Lock()

    def _resolve(self, inner) -> None:
        """Called by the dispatcher once the INNER future completed."""
        with self._lock:
            self._inner = inner
            self._event.set()
            waiters, self._waiters = self._waiters, []
        for w in waiters:
            w.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"fair-share task not done after {timeout}s"
            )
        return self._inner.result(0)

    def _add_waiter(self, event: threading.Event) -> None:
        with self._lock:
            if self._event.is_set():
                event.set()
            else:
                self._waiters.append(event)

    def _remove_waiter(self, event: threading.Event) -> None:
        with self._lock:
            try:
                self._waiters.remove(event)
            except ValueError:
                pass


class FairShareScheduler:
    """Weighted max-min interleaving of stage tasks across jobs.

    Wraps the session scheduler (local :class:`~.tasks.WorkerPool` or
    the cluster scheduler — both expose ``submit``/``submit_local_to``
    and a ``width``). Tasks submitted with NO ambient job pass straight
    through; job tasks queue per job and a dispatcher releases the next
    task from the backlogged job with the smallest *virtual time*
    (start-time fair queuing: each release advances the job's clock by
    ``1/weight``, and a newly backlogged job starts at the active
    minimum rather than replaying history) whenever the
    released-but-unfinished count is under the pool width. Weighted
    max-min by construction: under contention a ``weight=2`` job is
    released twice per a ``weight=1`` job's once, and a flooding job
    cannot starve a neighbor — the neighbor's clock is behind, so it
    wins the next free slot. With a single active job the release cap
    is waived — the sole tenant floods the pool exactly like the
    service-off path.
    """

    def __init__(self, inner):
        self.inner = inner
        self._lock = threading.Lock()
        self._pending: Dict[str, deque] = {}
        self._weights: Dict[str, float] = {}
        self._inflight: Dict[str, int] = {}
        self._vtime: Dict[str, float] = {}
        self._released: List[tuple] = []  # (inner_fut, job_id, proxy)
        self._notify = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._closed = False
        self._lag_published: set = set()

    # The scheduler duck-type surface shuffle_epoch sees.
    @property
    def width(self) -> int:
        return max(1, int(getattr(self.inner, "width", 1)))

    def submit(self, fn: Callable, *args, **kwargs):
        return self._enqueue(
            lambda: self.inner.submit(fn, *args, **kwargs)
        )

    def submit_local_to(self, refs, fn: Callable, *args, **kwargs):
        return self._enqueue(
            lambda: self.inner.submit_local_to(refs, fn, *args, **kwargs)
        )

    def _enqueue(self, thunk: Callable[[], Any]):
        job = current_job()
        if job is None or not job.running:
            return thunk()
        proxy = _ProxyFuture()
        # Snapshot the SUBMITTER'S telemetry context now: a deferred
        # task may be released later from the watcher thread, and the
        # inner submit captures its outbound (job/epoch) context at
        # release time — without the snapshot, every throttled task
        # would lose its attribution (worker-side audit digests would
        # fold jobless and fail a correct multi-job reconcile).
        try:
            ctx = telemetry.outbound_context() or {}
        except Exception:
            ctx = {}
        inner_thunk = thunk
        if ctx:
            def thunk(_run=inner_thunk, _ctx=ctx):
                with telemetry.context(**_ctx):
                    return _run()
        with self._lock:
            self._weights[job.job_id] = job.weight
            queue = self._pending.setdefault(job.job_id, deque())
            if not queue and not self._inflight.get(job.job_id):
                # Newly backlogged: start at the active minimum so an
                # idle spell never becomes banked credit (and a
                # latecomer never replays the incumbents' history).
                others = [
                    self._vtime.get(j, 0.0)
                    for j in (
                        set(self._inflight)
                        | {
                            k
                            for k, q in self._pending.items()
                            if q and k != job.job_id
                        }
                    )
                ]
                self._vtime[job.job_id] = max(
                    self._vtime.get(job.job_id, 0.0),
                    min(others) if others else 0.0,
                )
            queue.append((thunk, proxy))
            self._ensure_watcher_locked()
        self._pump()
        return proxy

    def forget_job(self, job_id: str) -> None:
        """Drop a finished job's pending queue and clock (its in-flight
        tasks complete and decrement normally)."""
        with self._lock:
            dropped = self._pending.pop(job_id, None)
            self._vtime.pop(job_id, None)
        if dropped:
            # An ended job should have drained its own queue; anything
            # left would hang its proxy waiters forever, so fail them.
            for _thunk, proxy in dropped:
                try:
                    proxy._resolve(_FailedInner("job ended"))
                except Exception:
                    pass

    def _multi_tenant_locked(self) -> bool:
        """More than one tenant is in play — by scheduler state (tasks
        pending or in flight from two jobs) or by registration (two
        running jobs exist, so the very first submissions must already
        shape to the share instead of flooding). Deliberately
        process-LOCAL (unlike admission's session-wide count): a job in
        another driver process submits to ITS OWN worker pool, never to
        this scheduler, so counting it here would throttle a sole
        tenant for a neighbor that cannot contend for these slots."""
        jobs = set(self._inflight) | {
            j for j, q in self._pending.items() if q
        }
        if len(jobs) > 1:
            return True
        return len(active_jobs()) > 1

    def _pump(self) -> None:
        """Release queued tasks while capacity allows, picking the
        backlogged job with the smallest virtual time (ties: fewest
        in-flight, then id). Runs the thunks OUTSIDE the lock — a
        submit can block on the mp queue."""
        while True:
            with self._lock:
                if self._closed:
                    return
                queues = {
                    j: q for j, q in self._pending.items() if q
                }
                if not queues:
                    self._publish_vtime_lag_locked()
                    return
                total = sum(self._inflight.values())
                if self._multi_tenant_locked() and total >= self.width:
                    _metrics.safe_inc("service.tasks_throttled")
                    self._publish_vtime_lag_locked()
                    return
                job_id = min(
                    queues,
                    key=lambda j: (
                        self._vtime.get(j, 0.0),
                        self._inflight.get(j, 0),
                        j,
                    ),
                )
                self._vtime[job_id] = self._vtime.get(
                    job_id, 0.0
                ) + 1.0 / self._weights.get(job_id, 1.0)
                thunk, proxy = queues[job_id].popleft()
                self._inflight[job_id] = self._inflight.get(job_id, 0) + 1
                self._publish_vtime_lag_locked()
            try:
                inner_fut = thunk()
            except BaseException as exc:
                with self._lock:
                    self._dec_inflight_locked(job_id)
                # The proxy was already handed to the submitter: fail
                # it loudly — left unresolved, a deliver thread blocked
                # in proxy.result() (no timeout) would hang forever.
                try:
                    proxy._resolve(
                        _FailedInner(
                            f"submit failed: "
                            f"{type(exc).__name__}: {exc}"[:200]
                        )
                    )
                except Exception:
                    pass
                raise
            add = getattr(inner_fut, "_add_waiter", None)
            if add is not None:
                add(self._notify)
            with self._lock:
                self._released.append((inner_fut, job_id, proxy))
            if inner_fut.done():
                self._notify.set()

    def _publish_vtime_lag_locked(self) -> None:
        """Per-job dispatch-lag gauges: how far each active job's
        virtual clock trails the most-advanced active clock,
        ``service.dispatch_vtime_lag{job=}``. A job with no queued
        tasks publishes 0 (it is not waiting on dispatch, whatever its
        clock says); departed jobs' gauges are zeroed so a stale series
        cannot hold the fair_share_starved alert open. Caller holds
        ``self._lock``; metrics-gated, never raises."""
        if not _metrics.enabled():
            return
        try:
            reg = _metrics.registry
            active = set(self._inflight) | {
                j for j, q in self._pending.items() if q
            }
            lead = max(
                (self._vtime.get(j, 0.0) for j in active), default=0.0
            )
            for job_id in active:
                lag = (
                    lead - self._vtime.get(job_id, 0.0)
                    if self._pending.get(job_id)
                    else 0.0
                )
                reg.gauge(
                    "service.dispatch_vtime_lag", job=job_id
                ).set(round(lag, 4))
            for job_id in self._lag_published - active:
                reg.gauge("service.dispatch_vtime_lag", job=job_id).set(0.0)
            self._lag_published = active
        except Exception:
            pass

    def _dec_inflight_locked(self, job_id: str) -> None:
        n = self._inflight.get(job_id, 0) - 1
        if n <= 0:
            self._inflight.pop(job_id, None)
        else:
            self._inflight[job_id] = n

    def _ensure_watcher_locked(self) -> None:
        if self._watcher is None or not self._watcher.is_alive():
            self._watcher = threading.Thread(
                target=self._watch, name="rsdl-fair-share", daemon=True
            )
            self._watcher.start()

    def _watch(self) -> None:
        while not self._closed:
            self._notify.wait(timeout=0.5)
            self._notify.clear()
            finished: List[tuple] = []
            with self._lock:
                still: List[tuple] = []
                for entry in self._released:
                    if entry[0].done():
                        finished.append(entry)
                        self._dec_inflight_locked(entry[1])
                    else:
                        still.append(entry)
                self._released = still
                if finished:
                    self._publish_vtime_lag_locked()
                idle = (
                    not self._released
                    and not any(q for q in self._pending.values())
                )
            for inner_fut, _job_id, proxy in finished:
                rm = getattr(inner_fut, "_remove_waiter", None)
                if rm is not None:
                    rm(self._notify)
                proxy._resolve(inner_fut)
            if finished:
                # A raising submit (pool shutting down, dead cluster
                # host) must not kill the dispatcher thread: its proxy
                # was failed in _pump, but OTHER jobs' queued tasks
                # still need this loop alive.
                try:
                    self._pump()
                except Exception:
                    pass
            if idle:
                # Park cheaply between bursts; a new enqueue restarts
                # the loop via _notify after _pump releases.
                self._notify.wait(timeout=5.0)

    def stop(self) -> None:
        self._closed = True
        self._notify.set()

    def queue_depths(self) -> Dict[str, int]:
        with self._lock:
            return {
                j: len(q) for j, q in self._pending.items() if q
            }

    def inflight(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._inflight)


class _FailedInner:
    """Inner-future stand-in whose result always raises (an ended
    job's still-queued tasks must fail loudly, not hang)."""

    def __init__(self, why: str):
        self._why = why

    def done(self) -> bool:
        return True

    def result(self, timeout: Optional[float] = None):
        raise RuntimeError(f"fair-share task dropped: {self._why}")


_sched_lock = threading.Lock()
_schedulers: Dict[int, FairShareScheduler] = {}


def wrap_scheduler(inner):
    """The session scheduler wrapped for fair share (cached per inner
    scheduler object; returns ``inner`` unchanged when the plane is
    off)."""
    if not enabled():
        return inner
    if isinstance(inner, FairShareScheduler):
        return inner
    with _sched_lock:
        sched = _schedulers.get(id(inner))
        if sched is None or sched.inner is not inner:
            sched = FairShareScheduler(inner)
            _schedulers[id(inner)] = sched
        return sched


def _scheduler_singleton() -> Optional[FairShareScheduler]:
    with _sched_lock:
        for sched in _schedulers.values():
            return sched
    return None


def stop() -> None:
    """Session teardown: stop dispatcher threads and forget state
    (called by ``runtime.shutdown`` via the loaded-modules sweep)."""
    with _sched_lock:
        scheds = list(_schedulers.values())
        _schedulers.clear()
    for sched in scheds:
        try:
            sched.stop()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Per-job epoch-window admission
# ---------------------------------------------------------------------------


def _admit_frac() -> float:
    try:
        return float(os.environ.get(_ENV_ADMIT_FRAC, "0.85"))
    except ValueError:
        return 0.85


def _admit_timeout_s() -> float:
    try:
        return float(os.environ.get(_ENV_ADMIT_TIMEOUT, "30"))
    except ValueError:
        return 30.0


def admit_epoch(job: Job, epoch: int, in_flight: int) -> float:
    """Hold a new epoch window back while the shm budget is over the
    admission watermark AND other jobs are active. Returns the seconds
    waited. Progress is guaranteed by construction: a job with no
    window in flight is always admitted (its oldest window is what
    frees memory), the sole tenant is always admitted, and the wait is
    bounded by ``RSDL_SERVICE_ADMIT_TIMEOUT_S`` — admission shapes
    concurrency, it never deadlocks it. Multi-tenancy is judged across
    every process of the session (on-disk job records, pid-alive) —
    the shm budget is shared session-wide, so a tenant in another
    driver process must count."""
    if job is None or in_flight <= 0 or live_jobs_count() <= 1:
        return 0.0
    if not _metrics.enabled():
        return 0.0  # no ledger -> no headroom signal to key on
    from ray_shuffling_data_loader_tpu.telemetry import capacity

    watermark = _admit_frac()
    deadline = time.monotonic() + _admit_timeout_s()
    t0 = time.monotonic()
    waited_event = False
    while True:
        try:
            frac = capacity.view().get("shm_used_frac")
        except Exception:
            frac = None
        if frac is None or float(frac) < watermark:
            break
        if time.monotonic() >= deadline:
            _metrics.safe_inc(
                "service.admission_timeouts", job=job.job_id
            )
            break
        if not waited_event:
            waited_event = True
            telemetry.emit_event(
                "service.admission_wait", job=job.job_id, epoch=epoch,
                shm_used_frac=float(frac),
            )
        time.sleep(0.2)
    waited = time.monotonic() - t0
    if waited > 0.05:
        try:
            if _metrics.enabled():
                # Histogram (ISSUE 16): the SLO plane's
                # admission_wait_long rule keys on the windowed MEAN
                # wait per tenant, which a bare counter cannot give it;
                # count/sum/min/max also feed the /jobs rollup.
                _metrics.registry.histogram(
                    "service.admission_wait_seconds", job=job.job_id
                ).observe(waited)
        except Exception:
            pass
    return waited


# ---------------------------------------------------------------------------
# Cross-job hot-dataset sharing (content-identity decode-cache registry)
# ---------------------------------------------------------------------------


def cache_key(
    filename: str,
    columns: Optional[Sequence[str]],
    narrow: bool,
) -> str:
    """Content identity of one file's decoded columns: the file
    fingerprint (path + size + mtime — a rewritten file can never
    serve a stale cache), the projection, and the narrowing flag.
    Unlike the PR 11 session key, two JOBS with the same content
    identity share one segment."""
    path = filename if "://" in filename else os.path.abspath(filename)
    try:
        st = os.stat(path)
        fp = f"{st.st_size}:{st.st_mtime_ns}"
    except OSError:
        fp = "?"
    proj = "*" if columns is None else ",".join(str(c) for c in columns)
    return f"{path}|{fp}|{proj}|{int(bool(narrow))}"


_cache_lock = threading.Lock()
_cache_mem: Dict[str, Dict[str, Any]] = {}  # in-process fast path


def _registry_paths() -> Optional[tuple]:
    base = _service_dir()
    if base is None:
        return None
    return (
        os.path.join(base, "cache-registry.json"),
        os.path.join(base, "cache-registry.lock"),
    )


@contextlib.contextmanager
def _registry_locked():
    """The cross-process registry dict under an flock'd lockfile;
    mutations inside the block are persisted on exit. Yields None when
    no session is live (in-process registry only)."""
    paths = _registry_paths()
    if paths is None:
        yield None
        return
    reg_path, lock_path = paths
    os.makedirs(os.path.dirname(reg_path), exist_ok=True)
    import fcntl

    with open(lock_path, "a+") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            try:
                with open(reg_path) as f:
                    data = json.load(f)
            except (OSError, ValueError):
                data = {}
            yield data
            tmp = f"{reg_path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, reg_path)
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)


def _ref_to_dict(ref) -> Dict[str, Any]:
    return {
        "id": ref.object_id,
        "nbytes": int(ref.nbytes),
        "session": ref.session,
        "owner": list(ref.owner) if ref.owner is not None else None,
        "rows": (
            [int(ref.rows[0]), int(ref.rows[1])]
            if ref.rows is not None
            else None
        ),
    }


def _ref_from_dict(d: Dict[str, Any]):
    from ray_shuffling_data_loader_tpu.runtime.store import ObjectRef

    return ObjectRef(
        object_id=str(d["id"]),
        nbytes=int(d.get("nbytes", 0)),
        session=str(d.get("session", "")),
        owner=tuple(d["owner"]) if d.get("owner") else None,
        rows=tuple(d["rows"]) if d.get("rows") else None,
    )


def cache_publish(key: str, ref, job: Optional[Job] = None) -> None:
    """Publish one decoded-file segment under its content key, claimed
    by the publishing job. Never raises into the data path."""
    job = job if job is not None else current_job()
    try:
        entry = _ref_to_dict(ref)
        entry["claims"] = {job.job_id: time.time()} if job else {}
        with _cache_lock:
            _cache_mem[key] = entry
        with _registry_locked() as data:
            if data is not None:
                cur = data.get(key)
                if cur is not None and cur.get("id") != entry["id"]:
                    # Keep the incumbent (first publisher wins) but
                    # carry our claim onto it so it stays fenced.
                    if job is not None:
                        cur.setdefault("claims", {})[
                            job.job_id
                        ] = time.time()
                    with _cache_lock:
                        _cache_mem[key] = dict(cur)
                else:
                    prev_claims = (cur or {}).get("claims") or {}
                    entry["claims"] = {**prev_claims, **entry["claims"]}
                    data[key] = entry
    except Exception:
        pass


def cache_lookup(key: str, job: Optional[Job] = None):
    """A still-live shared segment for ``key`` (session-validated and
    ``store.exists``-checked), with a claim added for ``job`` — or
    None, dropping any stale entry so the caller re-decodes."""
    from ray_shuffling_data_loader_tpu import runtime

    job = job if job is not None else current_job()
    with _cache_lock:
        entry = _cache_mem.get(key)
    if entry is None:
        try:
            with _registry_locked() as data:
                entry = dict(data[key]) if data and key in data else None
        except Exception:
            entry = None
        if entry is not None:
            with _cache_lock:
                _cache_mem[key] = entry
    if entry is None:
        return None
    try:
        ctx = runtime.get_context()
        ref = _ref_from_dict(entry)
        if ref.session == ctx.store.session and ctx.store.exists(ref):
            if job is not None:
                claim_cache(key, job)
            _metrics.safe_inc(
                "service.cache_hits",
                job=job.job_id if job else "none",
            )
            return ref
    except Exception:
        pass
    _drop_cache_entry(key)
    return None


def claim_cache(key: str, job: Job) -> None:
    try:
        with _cache_lock:
            entry = _cache_mem.get(key)
            if entry is not None:
                claims = entry.setdefault("claims", {})
                if job.job_id in claims:
                    # Already claimed: claims never age out while the
                    # job is pid-live, so skip the flock'd full-file
                    # registry rewrite — a hot per-epoch lookup loop
                    # must cost one write per (job, key), not one per
                    # hit.
                    return
                claims[job.job_id] = time.time()
        with _registry_locked() as data:
            if data is not None and key in data:
                data[key].setdefault("claims", {})[
                    job.job_id
                ] = time.time()
    except Exception:
        pass


def release_claims(job_id: str) -> None:
    """Release every cache claim ``job_id`` holds (job end): unclaimed
    segments become ordinary evictor candidates again."""
    try:
        with _cache_lock:
            for entry in _cache_mem.values():
                (entry.get("claims") or {}).pop(job_id, None)
        with _registry_locked() as data:
            if data is not None:
                for entry in data.values():
                    (entry.get("claims") or {}).pop(job_id, None)
    except Exception:
        pass


def claimed_cache_ids() -> set:
    """Object ids of shared-cache segments a LIVE job still claims —
    the evictor's do-not-drop set (:mod:`.elastic`). Liveness is
    record-``running`` AND pid-alive: a SIGKILLed driver's claims must
    not fence segments forever (its record stays ``running`` — only
    the liveness probe can retire it)."""
    live = {
        rec.get("job_id")
        for rec in jobs_snapshot()
        if _record_live(rec)
    }
    out = set()
    try:
        with _registry_locked() as data:
            entries = list((data or {}).values())
    except Exception:
        entries = []
    with _cache_lock:
        entries += list(_cache_mem.values())
    for entry in entries:
        claims = entry.get("claims") or {}
        if any(j in live for j in claims):
            oid = entry.get("id")
            if oid:
                out.add(str(oid))
    return out


def job_cache_claims() -> Dict[str, int]:
    """``{job_id: shared-cache entries claimed}`` across the on-disk
    registry and the in-process view — the ``/jobs`` fleet view's
    cache-claims column."""
    seen = set()
    out: Dict[str, int] = {}
    try:
        with _registry_locked() as data:
            entries = list((data or {}).values())
    except Exception:
        entries = []
    with _cache_lock:
        entries += list(_cache_mem.values())
    for entry in entries:
        oid = entry.get("id")
        if oid in seen:
            continue  # the same entry, seen via both views
        seen.add(oid)
        for job_id in entry.get("claims") or {}:
            out[job_id] = out.get(job_id, 0) + 1
    return out


def _drop_cache_entry(key: str) -> None:
    try:
        with _cache_lock:
            _cache_mem.pop(key, None)
        with _registry_locked() as data:
            if data is not None:
                data.pop(key, None)
    except Exception:
        pass


def cache_registry_clear() -> None:
    """Drop every registry entry (tests / operators). Segments are not
    freed — the session cleanup / evictor own their lifetime."""
    with _cache_lock:
        _cache_mem.clear()
    try:
        with _registry_locked() as data:
            if data is not None:
                data.clear()
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Observability
# ---------------------------------------------------------------------------


_provider_registered = False


def _maybe_register_status_provider() -> None:
    global _provider_registered
    if _provider_registered or not os.environ.get("RSDL_OBS_PORT"):
        return
    try:
        from ray_shuffling_data_loader_tpu.telemetry import obs_server

        obs_server.register_status_provider("service", status_section)
        _provider_registered = True
    except Exception:
        pass


def status_section() -> Dict[str, Any]:
    """The ``service`` section of ``/status``: registered jobs, the
    fair-share queues, and the shared-cache registry size."""
    sched = _scheduler_singleton()
    try:
        with _registry_locked() as data:
            cache_entries = len(data or {})
    except Exception:
        cache_entries = len(_cache_mem)
    return {
        "mode": mode(),
        "jobs": jobs_snapshot(),
        "fair_share": {
            "queued": sched.queue_depths() if sched else {},
            "in_flight": sched.inflight() if sched else {},
        },
        "cache_entries": cache_entries,
    }


def reset_state() -> None:
    """Tests only: forget jobs, schedulers, and the in-process cache
    view (the on-disk registry belongs to the session)."""
    stop()
    with _jobs_lock:
        _jobs.clear()
    with _cache_lock:
        _cache_mem.clear()
    _tls.job = None
    global _provider_registered
    _provider_registered = False
