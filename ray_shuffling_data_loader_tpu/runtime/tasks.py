"""Task execution: a process pool with futures and a ``wait`` primitive.

Replaces Ray's ``@ray.remote`` task layer that the reference uses for its
shuffle map/reduce stages (``shuffle.py:129,171``) and data generation
(``data_generation.py:30``). Tasks are plain importable functions; arguments
and results that are bulk data travel through the shared-memory
:mod:`.store` as :class:`~.store.ObjectRef` — the worker pool only moves
pickled control messages.

Workers are **spawned** (fresh interpreters): they never inherit JAX/TPU
state from the driver, so shuffle CPU work cannot corrupt the TPU client.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_shuffling_data_loader_tpu import telemetry
from ray_shuffling_data_loader_tpu._lazy import lazy_module

# Fault-injection plane (ISSUE 14 gate-integrity): lazy proxy — the
# plane's module body runs only when a worker actually starts, never
# when this module is imported.
faults = lazy_module("ray_shuffling_data_loader_tpu.runtime.faults")


class TaskError(Exception):
    """A task raised; carries the remote traceback plus structured
    fields the recovery layer keys on: ``error_type`` (the remote
    exception class name) and ``lost_object_id`` (set when the task died
    on an :class:`~.store.ObjectLostError`, so the shuffle driver can
    re-materialize that exact object from lineage instead of guessing
    from traceback text)."""

    def __init__(
        self,
        message: str,
        error_type: Optional[str] = None,
        lost_object_id: Optional[str] = None,
    ):
        super().__init__(message)
        self.error_type = error_type
        self.lost_object_id = lost_object_id

    def __reduce__(self):
        # Crosses the actor wire (HostAgent.submit re-raises it to the
        # remote driver); the default reduce would drop the structured
        # fields.
        return (
            TaskError,
            (self.args[0] if self.args else "", self.error_type,
             self.lost_object_id),
        )


class TaskFuture:
    def __init__(self, task_id: int):
        self.task_id = task_id
        self._event = threading.Event()
        self._result = None
        self._error: Optional[str] = None
        self._waiters_lock = threading.Lock()
        self._waiters: List[threading.Event] = []

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(f"task {self.task_id} not done after {timeout}s")
        if self._error is not None:
            # Workers report structured {"tb", "type", "lost"} errors;
            # pool-level failures (worker died, pool shut down) remain
            # plain strings.
            if isinstance(self._error, dict):
                raise TaskError(
                    self._error.get("tb", ""),
                    error_type=self._error.get("type"),
                    lost_object_id=self._error.get("lost"),
                )
            raise TaskError(self._error)
        return self._result

    def _add_waiter(self, event: threading.Event) -> None:
        with self._waiters_lock:
            if self._event.is_set():
                event.set()
            else:
                self._waiters.append(event)

    def _remove_waiter(self, event: threading.Event) -> None:
        with self._waiters_lock:
            try:
                self._waiters.remove(event)
            except ValueError:
                pass

    def _fulfill(self, result, error):
        self._result = result
        self._error = error
        self._event.set()
        with self._waiters_lock:
            waiters, self._waiters = self._waiters, []
        for w in waiters:
            w.set()


def wait(
    futures: Sequence[TaskFuture],
    num_returns: int = 1,
    timeout: Optional[float] = None,
) -> Tuple[List[TaskFuture], List[TaskFuture]]:
    """``ray.wait`` analog: block until ``num_returns`` futures complete;
    return (done, pending) preserving submission order.

    Event-driven: completions notify a shared event, so waiting burns no
    CPU (futures without waiter support — e.g. bare concurrent futures —
    fall back to a coarse poll).
    """
    import time as _time

    deadline = None if timeout is None else _time.monotonic() + timeout
    notify = threading.Event()
    subscribed = []
    for f in futures:
        add = getattr(f, "_add_waiter", None)
        if add is not None:
            add(notify)
            subscribed.append(f)
    pollable = len(subscribed) < len(futures)
    try:
        while True:
            # Clear BEFORE checking: a completion racing this loop either
            # lands before the check (seen via done()) or after (re-sets
            # the event, so the next wait() returns immediately).
            notify.clear()
            done = [f for f in futures if f.done()]
            if len(done) >= num_returns:
                break
            if deadline is not None and _time.monotonic() > deadline:
                break
            remaining = (
                None if deadline is None else deadline - _time.monotonic()
            )
            if pollable:
                remaining = 0.01 if remaining is None else min(remaining, 0.01)
            if remaining is not None and remaining <= 0:
                continue
            notify.wait(remaining)
    finally:
        for f in subscribed:
            f._remove_waiter(notify)
    # One snapshot, done first: a future completing between two separate
    # scans would otherwise land in BOTH lists.
    done_set = {id(f) for f in futures if f.done()}
    done = [f for f in futures if id(f) in done_set]
    pending = [f for f in futures if id(f) not in done_set]
    return done, pending


def _record_task_done(fn, duration_s: float, trace_ctx) -> None:
    """Feed the straggler detector one completed-task record
    (ISSUE 7). Metrics-gated BEFORE the import so the disabled path
    never loads the stragglers module; never raises."""
    if not telemetry.metrics.enabled():
        return
    try:
        from ray_shuffling_data_loader_tpu.telemetry import stragglers

        ctx = trace_ctx or {}
        stragglers.record_task(
            getattr(fn, "__name__", "task"), duration_s,
            epoch=ctx.get("epoch"), job=ctx.get("job"),
        )
    except Exception:
        pass


def _outbound_ctx():
    """The submitter's trace context to pickle next to the task, or
    None with no facade touch when nothing can have produced one —
    context lives in telemetry.trace (never imported ⇒ empty) and the
    metrics half ships identity through the same path only when
    enabled. The service plane's job identity (ISSUE 15) also rides
    this context, but a job can only be ambient after the shuffle
    driver entered telemetry.context — which loads the trace module —
    so the sys.modules check below already covers it. Mirrors
    runtime/actor.py's _trace_ctx (ISSUE 14: the disabled submit path
    stays import-free)."""
    import sys as _sys

    if (
        _sys.modules.get("ray_shuffling_data_loader_tpu.telemetry.trace")
        is None
        and not telemetry.metrics.enabled()
    ):
        return None
    return telemetry.outbound_context()


def _flush_telemetry_spools() -> None:
    """The task-done spool barrier: trace, audit, metrics registry,
    plus (metrics-gated, lazily imported) the event log and straggler
    task records. Trace/audit flush via ``sys.modules`` — a module
    never imported has nothing buffered, and touching the facade
    attribute instead would import it just to no-op (ISSUE 14: the
    disabled path stays import-free, not merely cheap)."""
    import sys as _sys

    for _name in ("trace", "audit", "profiler"):
        _mod = _sys.modules.get(
            f"ray_shuffling_data_loader_tpu.telemetry.{_name}"
        )
        if _mod is not None:
            _mod.safe_flush()
    if telemetry.metrics.enabled():
        telemetry.export.safe_flush()
        try:
            from ray_shuffling_data_loader_tpu.telemetry import (
                capacity,
                events,
                stragglers,
            )

            events.safe_flush()
            stragglers.safe_flush()
            capacity.safe_flush()
        except Exception:
            pass
    # Flush-then-SHIP (ISSUE 19): with the federation plane armed, wake
    # this host's relay shipper so a remote worker's records are durable
    # at the driver at the same task-done barrier local ones are.
    # Env-gated BEFORE the import — relay off stays import-free.
    _mode = os.environ.get("RSDL_RELAY", "").strip().lower()
    if _mode and _mode not in ("off", "0", "false"):
        try:
            from ray_shuffling_data_loader_tpu.telemetry import relay

            relay.kick()
        except Exception:
            pass


def _worker_main(task_q, result_q, env: Dict[str, str]):
    import pickle

    os.environ.update(env)
    pid = os.getpid()
    # Unconditional: the role tag is process IDENTITY — the telemetry
    # spools (events/metrics source records) stamp it, not just
    # /task-filtered fault rules — so it must be set even with the
    # fault plane unarmed. (One cheap stdlib import per worker, at
    # worker start, never at module import — the gate invariant.)
    faults.set_role("task")
    # Entrypoint-equivalent of telemetry.enabled(): a freshly spawned
    # worker can only have tracing on via env, and the flag read skips
    # importing the trace module when off (ISSUE 14: the disabled path
    # stays import-free at runtime, not just at import time).
    from ray_shuffling_data_loader_tpu.telemetry import _env

    trace_on = _env.read_flag("RSDL_TRACE")
    if trace_on:
        telemetry.set_process_name(f"task-worker-{pid}")
    instrumented = trace_on or telemetry.metrics.enabled()
    # The continuous profiler (ISSUE 17) samples THIS worker too — env-
    # gated before the import, same contract as the trace flag above.
    if _env.read_flag("RSDL_PROFILE"):
        try:
            from ray_shuffling_data_loader_tpu.telemetry import profiler

            profiler.start()
        except Exception:
            pass
    # Orphan self-destruct: if the pool owner dies without shutdown (e.g.
    # SIGKILL), exit rather than linger holding inherited pipes/fds.
    parent = os.getppid()

    def _watch_parent():
        import time

        while True:
            time.sleep(1.0)
            if os.getppid() != parent:
                os._exit(0)

    threading.Thread(target=_watch_parent, daemon=True).start()
    import time as _time

    while True:
        item = task_q.get()
        if item is None:
            break
        # Announce task start so the driver can attribute in-flight tasks
        # to this worker if it dies mid-task.
        task_id, blob = item
        result_q.put(("start", task_id, pid))
        try:
            # Blob carries the submitter's trace context; the span + the
            # re-entered context give every task a runtime-layer span and
            # make in-task spans inherit (trial, epoch, ...).
            fn, args, kwargs, trace_ctx = pickle.loads(blob)
            t0 = _time.perf_counter()
            if instrumented or trace_ctx is not None:
                with telemetry.propagated_span(
                    f"task:{getattr(fn, '__name__', 'task')}", trace_ctx
                ):
                    result = fn(*args, **kwargs)
            else:
                # Fully disabled: don't resolve the facade span (it
                # would import telemetry.trace just to no-op).
                result = fn(*args, **kwargs)
            _record_task_done(fn, _time.perf_counter() - t0, trace_ctx)
            # Flush BEFORE reporting done: by the time the caller can
            # observe the result, this task's spans, audit digest
            # records, event-log + task-duration records, AND
            # metrics-registry snapshot are on their spools (the
            # driver's reconciler, the cluster metrics aggregator, and
            # the straggler detector all rely on this ordering — all
            # futures resolved implies all worker-side records visible;
            # without the metrics flush, worker counters died with the
            # pool).
            _flush_telemetry_spools()
            result_q.put(("done", task_id, result, None))
        except Exception as exc:
            _flush_telemetry_spools()
            result_q.put(
                (
                    "done",
                    task_id,
                    None,
                    {
                        "tb": traceback.format_exc(),
                        "type": type(exc).__name__,
                        # ObjectLostError carries the id of the missing
                        # segment; the driver's lineage recovery needs it
                        # structured, not buried in traceback text.
                        "lost": getattr(exc, "object_id", None),
                    },
                )
            )


class WorkerPool:
    """Pool of spawned worker processes with a shared task queue.

    Sized at construction, but elastic (ISSUE 10): :meth:`add_workers`
    spawns more processes onto the shared queue mid-run, and
    :meth:`retire_workers` retires workers *gracefully* — a retiring
    worker finishes its current task, takes no more (the pill is just
    the next queue item it dequeues), and exits cleanly; the watchdog
    reaps clean exits without failing anyone's futures.
    """

    def __init__(self, num_workers: int, env: Optional[Dict[str, str]] = None):
        self.num_workers = num_workers
        self.width = num_workers  # scheduler-duck-typed capacity surface
        ctx = mp.get_context("spawn")
        self._mp_ctx = ctx
        self._task_q = ctx.Queue()
        self._result_q = ctx.Queue()
        env = dict(env or {})
        # Workers are CPU-side shuffle executors; keep them off the TPU.
        env.setdefault("JAX_PLATFORMS", "cpu")
        self._env = env
        self._procs_lock = threading.Lock()
        self._procs = [
            ctx.Process(
                target=_worker_main,
                args=(self._task_q, self._result_q, env),
                daemon=True,
            )
            for _ in range(num_workers)
        ]
        for p in self._procs:
            p.start()
        self._futures: Dict[int, TaskFuture] = {}
        self._futures_lock = threading.Lock()
        self._running_on: Dict[int, int] = {}  # task_id -> worker pid
        self._task_names: Dict[int, str] = {}  # task_id -> fn name
        self._started: Dict[int, float] = {}  # task_id -> start monotonic
        self._next_id = 0
        self._closed = False
        self._collector = threading.Thread(target=self._collect, daemon=True)
        self._collector.start()
        self._watchdog = threading.Thread(target=self._watch, daemon=True)
        self._watchdog.start()
        # Publish the live in-flight view to the straggler detector
        # (ISSUE 7): which task functions started when, on which worker
        # pid — the feed the wedged-worker flag needs. Metrics-gated
        # before the import, like every temporal-plane touchpoint.
        self._inflight_name = f"pool-{id(self)}"
        if telemetry.metrics.enabled():
            try:
                from ray_shuffling_data_loader_tpu.telemetry import (
                    stragglers,
                )

                stragglers.register_inflight_provider(
                    self._inflight_name, self.in_flight
                )
            except Exception:
                pass

    def _collect(self):
        while True:
            try:
                item = self._result_q.get()
            except (EOFError, OSError):
                break
            if item is None:
                break
            if item[0] == "start":
                _, task_id, pid = item
                with self._futures_lock:
                    self._running_on[task_id] = pid
                    self._started[task_id] = time.monotonic()
                continue
            _, task_id, result, error = item
            with self._futures_lock:
                fut = self._futures.pop(task_id, None)
                self._running_on.pop(task_id, None)
                self._started.pop(task_id, None)
                self._task_names.pop(task_id, None)
            if fut is not None:
                fut._fulfill(result, error)

    def _watch(self):
        # Fail in-flight tasks whose worker died (e.g. OOM-killed) so
        # callers get a TaskError instead of hanging forever.
        import time as _time

        while not self._closed:
            _time.sleep(0.5)
            with self._procs_lock:
                procs = list(self._procs)
            # Reap gracefully-retired workers (clean exit after a retire
            # pill): membership shrinks without failing any futures.
            clean = [
                p for p in procs if not p.is_alive() and not p.exitcode
            ]
            if clean and not self._closed:
                with self._procs_lock:
                    for p in clean:
                        if p in self._procs:
                            p.join(timeout=0.1)
                            self._procs.remove(p)
                    self.num_workers = self.width = len(self._procs)
            dead = [
                p.pid for p in procs if not p.is_alive() and p.exitcode
            ]
            if not dead:
                continue
            with self._futures_lock:
                lost = [
                    (tid, pid)
                    for tid, pid in self._running_on.items()
                    if pid in dead
                ]
                futs = []
                for tid, pid in lost:
                    fut = self._futures.pop(tid, None)
                    self._running_on.pop(tid, None)
                    self._started.pop(tid, None)
                    self._task_names.pop(tid, None)
                    if fut is not None:
                        futs.append((fut, pid))
            for fut, pid in futs:
                fut._fulfill(
                    None, f"worker process {pid} died while running this task"
                )

    # -- elastic membership (ISSUE 10) ---------------------------------------

    def add_workers(self, n: int) -> int:
        """Spawn ``n`` more workers onto the shared task queue (the
        single-host scale-up actuator). Returns the new pool size."""
        if self._closed or n <= 0:
            return self.num_workers
        procs = [
            self._mp_ctx.Process(
                target=_worker_main,
                args=(self._task_q, self._result_q, self._env),
                daemon=True,
            )
            for _ in range(int(n))
        ]
        for p in procs:
            p.start()
        with self._procs_lock:
            self._procs.extend(procs)
            self.num_workers = self.width = len(self._procs)
            return self.num_workers

    def retire_workers(
        self, n: int, deadline_s: float = 10.0
    ) -> List[int]:
        """Gracefully retire ``n`` workers (never below one): each pill
        is consumed by SOME worker as its next queue item — it finishes
        its current task, drains nothing further, and exits cleanly.
        Pills queue behind already-submitted tasks, so retirement is
        drain-aware by construction: capacity drops only after the
        backlog ahead of the pill is done. Waits up to ``deadline_s``
        for the exits; stragglers are reaped later by the watchdog (a
        busy worker holding a long task is exactly who we must not
        kill). Returns the pids that exited within the deadline."""
        with self._procs_lock:
            before = {p.pid for p in self._procs}
            n = min(int(n), len(before) - 1)
        if self._closed or n <= 0:
            return []
        for _ in range(n):
            self._task_q.put(None)
        # Membership shrink is the truth, not who reaped: the watchdog's
        # clean-exit reaper races this loop, and a retiree it collects
        # first must still count toward n (pid-set difference), or the
        # call would spin out its whole deadline on a success.
        deadline = time.monotonic() + max(0.0, deadline_s)
        while True:
            with self._procs_lock:
                done = [
                    p
                    for p in self._procs
                    if not p.is_alive() and not p.exitcode
                ]
                for p in done:
                    p.join(timeout=0.1)
                    self._procs.remove(p)
                self.num_workers = self.width = len(self._procs)
                current = {p.pid for p in self._procs}
            retired = sorted(before - current)
            if len(retired) >= n or time.monotonic() >= deadline:
                return retired
            time.sleep(0.05)

    def in_flight(self) -> List[Dict[str, Any]]:
        """The live in-flight task view the straggler detector folds:
        one entry per started-but-unfinished task with its function
        name, worker pid, and age."""
        now = time.monotonic()
        with self._futures_lock:
            return [
                {
                    "stage": self._task_names.get(tid, "task"),
                    "pid": pid,
                    "age_s": now - self._started[tid],
                }
                for tid, pid in self._running_on.items()
                if tid in self._started
            ]

    def submit_local_to(self, refs, fn: Callable, *args, **kwargs):
        """Locality-aware submit surface shared with the cluster scheduler;
        a single-host pool has exactly one locality, so the hint is moot."""
        return self.submit(fn, *args, **kwargs)

    def submit(self, fn: Callable, *args, **kwargs) -> TaskFuture:
        import pickle

        if self._closed:
            raise RuntimeError("worker pool is shut down")
        # Pickle eagerly: mp.Queue pickles in a background feeder thread
        # where a PicklingError would be swallowed and the future never
        # fulfilled; raising here puts the error in the caller's lap.
        # The submitter's trace context rides along so the worker-side
        # span carries (trial, epoch, ...) without changing task args.
        blob = pickle.dumps(
            (fn, args, kwargs, _outbound_ctx())
        )
        with self._futures_lock:
            task_id = self._next_id
            self._next_id += 1
            fut = TaskFuture(task_id)
            self._futures[task_id] = fut
            self._task_names[task_id] = getattr(fn, "__name__", "task")
        self._task_q.put((task_id, blob))
        return fut

    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        # Unregister only if the module was ever loaded — shutdown on a
        # telemetry-off run must not import the temporal plane.
        import sys as _sys

        stragglers = _sys.modules.get(
            "ray_shuffling_data_loader_tpu.telemetry.stragglers"
        )
        if stragglers is not None:
            try:
                stragglers.unregister_inflight_provider(self._inflight_name)
            except Exception:
                pass
        with self._procs_lock:
            procs = list(self._procs)
        for _ in procs:
            try:
                self._task_q.put(None)
            except Exception:
                pass
        for p in procs:
            p.join(timeout=2)
            if p.is_alive():
                p.terminate()
        for p in procs:
            # SIGKILL stragglers: a worker that survives SIGTERM (e.g. one
            # wedged mid-syscall) would otherwise hang the interpreter's
            # multiprocessing atexit join forever.
            p.join(timeout=2)
            if p.is_alive():
                p.kill()
                p.join()
        try:
            self._result_q.put(None)
        except Exception:
            pass
        # Fail any outstanding futures so waiters don't hang forever.
        with self._futures_lock:
            for fut in self._futures.values():
                fut._fulfill(None, "worker pool shut down")
            self._futures.clear()
