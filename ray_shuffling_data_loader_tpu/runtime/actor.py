"""Actor runtime: named async endpoints in their own processes.

The TPU-native replacement for Ray's named-actor machinery that the reference
builds its delivery layer on: ``ray.remote(_QueueActor).options(name=...)``
(reference ``batch_queue.py:63-65``) and ``ray.get_actor(name)`` discovery
with exponential-backoff retry (``batch_queue.py:358-380``).

Model:

* ``spawn_actor(cls, *args, name=..)`` starts a **spawned** process hosting one
  instance of ``cls`` behind an asyncio socket server. ``async def`` methods
  run as event-loop tasks, so a blocked ``get`` never stalls a concurrent
  ``put`` — the same single-threaded-asyncio concurrency model as a Ray async
  actor (reference ``batch_queue.py:383-509``).
* Named actors register a JSON record (address + pid) in the session registry
  directory; ``connect_actor(name)`` resolves it with exponential backoff.
* Clients hold one blocking connection per calling thread. Fire-and-forget
  calls (``oneway=True``) get no reply — the analog of not ``ray.get``-ing a
  Ray call (reference ``batch_queue.py:94,108``).

The wire protocol is scheme-agnostic (unix socket on-host, TCP across hosts),
so the same actor code serves as the multi-host control plane over DCN.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import multiprocessing as mp
import os
import secrets
import signal
import sys
import threading
import time
import traceback
import weakref
from typing import Any, Dict, Optional

from ray_shuffling_data_loader_tpu import telemetry

from . import transport

# Fault-injection plane (ISSUE 14 gate-integrity): lazy proxy — never
# imported by merely importing the actor layer.
from ray_shuffling_data_loader_tpu._lazy import lazy_module
from ray_shuffling_data_loader_tpu.telemetry import _env

faults = lazy_module("ray_shuffling_data_loader_tpu.runtime.faults")
from .retry import call_policy, connect_policy
from .transport import Address


# The caller's trace context to ship with a request frame, or None when
# tracing is off (the common case). A def, not a module-level
# ``telemetry.outbound_context`` binding: binding the facade attribute
# at import time would eagerly pull telemetry.trace into every process
# that imports the actor layer (gate-integrity, ISSUE 14). The
# sys.modules gate keeps the disabled path import-free at CALL time
# too: context can only be non-empty if something already imported
# trace (set_context/context/enable live there), and the metrics half
# ships identity through the same outbound path only when enabled.
def _trace_ctx():
    if (
        sys.modules.get("ray_shuffling_data_loader_tpu.telemetry.trace")
        is None
        and not telemetry.metrics.enabled()
    ):
        return None
    return telemetry.outbound_context()


def _flush_telemetry_spools(maybe: bool = False) -> None:
    """Actor-host spool barrier (quiescence + exit): flush trace only
    if its module is already loaded (never imported ⇒ nothing buffered
    ⇒ nothing to import just to no-op), export only when metrics are on
    (its spool is metrics-gated). Keeps the disabled path import-free
    at runtime, matching the structural gate (ISSUE 14)."""
    for _name in ("trace", "profiler"):
        mod = sys.modules.get(
            f"ray_shuffling_data_loader_tpu.telemetry.{_name}"
        )
        if mod is not None:
            mod.safe_flush()
    if telemetry.metrics.enabled():
        if maybe:
            telemetry.export.maybe_flush()
        else:
            telemetry.export.safe_flush()
    # Flush-then-SHIP (ISSUE 19): with the federation plane armed, wake
    # this host's relay shipper so the records just flushed reach the
    # driver at the same barrier. Env-gated BEFORE the import — relay
    # off means the module is never loaded here.
    _mode = os.environ.get("RSDL_RELAY", "").strip().lower()
    if _mode and _mode not in ("off", "0", "false"):
        try:
            from ray_shuffling_data_loader_tpu.telemetry import relay

            relay.kick()
        except Exception:
            pass


# Virtual thread ids for traced dispatches: concurrent dispatches all run
# on the one event-loop thread, so their spans can overlap WITHOUT
# nesting — which a single Chrome-trace thread track cannot render. Each
# in-flight traced dispatch borrows a virtual tid from a free list (ids
# are reused, keeping the track count = peak concurrency, not dispatch
# count).
_VTID_BASE = 1 << 20
_vtid_lock = threading.Lock()
_vtid_free: list = []
_vtid_high = 0


def _acquire_vtid() -> int:
    global _vtid_high
    with _vtid_lock:
        if _vtid_free:
            return _vtid_free.pop()
        _vtid_high += 1
        tid = _VTID_BASE + _vtid_high
    telemetry.name_thread_track(tid, f"dispatch-{tid - _VTID_BASE}")
    return tid


def _release_vtid(tid: int) -> None:
    with _vtid_lock:
        _vtid_free.append(tid)


class ActorDiedError(Exception):
    """Raised when calling an actor whose process has exited."""


class RemoteError(Exception):
    """An exception raised inside an actor method, re-raised at the caller.

    Picklable exceptions are re-raised directly (so callers can except
    concrete types); ``RemoteError`` is the fallback carrying the remote
    traceback text when the original instance could not cross the wire."""


def _registry_dir(runtime_dir: str) -> str:
    return os.path.join(runtime_dir, "actors")


def _registry_path(runtime_dir: str, name: str) -> str:
    return os.path.join(_registry_dir(runtime_dir), f"{name}.json")


# ---------------------------------------------------------------------------
# Server side
# ---------------------------------------------------------------------------


class _ActorHost:
    """Runs inside the actor process: serves method calls on an asyncio loop."""

    def __init__(self, instance, address: Address):
        self.instance = instance
        self.address = address
        self._shutdown = None  # asyncio.Event, created on the loop
        self._inflight = 0  # dispatches in flight (loop-thread only)
        # Per-connection reply locks: OutOfBand payloads are written by
        # an executor thread on the RAW socket (see _send_out_of_band),
        # so every reply on that connection must serialize against it —
        # and so must the connection CLOSE (writer.close() while an
        # executor send is mid-flight would free the fd under it; a
        # reused fd number would then receive another connection's
        # bytes). Weak-keyed: entries vanish with their writer, so a
        # dispatch outliving its connection can't leak a lock entry.
        self._write_locks: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )

    def _writer_lock(self, writer) -> asyncio.Lock:
        lock = self._write_locks.get(writer)
        if lock is None:
            lock = self._write_locks[writer] = asyncio.Lock()
        return lock

    async def _send_out_of_band(self, writer, req_id, oob) -> None:
        """Write a vectored reply with the bulk payload sent from an
        EXECUTOR thread straight on the raw socket (``sendmsg`` releases
        the GIL). The asyncio loop single-threads every transport write;
        with striped fetches (``RSDL_TCP_STREAMS``) serving N concurrent
        window stripes, that one thread was the measured server-side
        bottleneck — per-core sends are the point of striping. The
        per-connection lock plus a drained transport buffer guarantee
        the raw-socket bytes cannot interleave with loop-side writes."""
        sock = writer.get_extra_info("socket")
        if sock is None:
            transport.write_frame_vectored(
                writer, (req_id, "okv", oob.meta), oob.buffers
            )
            await writer.drain()
            return
        frames = transport.vectored_frames(
            (req_id, "okv", oob.meta), oob.buffers
        )
        tr = writer.transport
        # The transport buffer must be EMPTY (not merely below the high
        # water mark, which is all drain() guarantees) before raw-socket
        # bytes go out, or they would overtake loop-buffered ones. A
        # yield-first spin keeps the common case (already empty) free;
        # a stalled peer backs off to millisecond sleeps, a closed
        # transport aborts, and a half-open peer that simply stops
        # reading hits the same 120 s bound as the raw send path —
        # without it this loop would hold the connection's reply lock
        # forever.
        spins = 0
        deadline = time.monotonic() + 120.0
        while tr.get_write_buffer_size() > 0:
            if tr.is_closing():
                raise ConnectionError("connection closed mid-reply")
            if time.monotonic() > deadline:
                raise ConnectionError(
                    "peer stalled a buffered reply > 120s"
                )
            await asyncio.sleep(0 if spins < 16 else 0.001)
            spins += 1
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, transport.sendmsg_all, sock, frames
        )

    async def _handle_client(self, reader, writer):
        try:
            while True:
                try:
                    frame = await transport.read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                # Frames are 5-tuples, or 6 with the caller's trace context
                # appended (tracing enabled caller-side; see _trace_ctx).
                req_id, method, args, kwargs, oneway = frame[:5]
                trace_ctx = frame[5] if len(frame) > 5 else None
                # Dispatch as a task: requests on one connection must not
                # head-of-line-block each other (a blocked queue.get would
                # otherwise deadlock the producer's puts).
                asyncio.get_running_loop().create_task(
                    self._dispatch(
                        writer, req_id, method, args, kwargs, oneway,
                        trace_ctx,
                    )
                )
        finally:
            # Close UNDER the reply lock: an executor-thread OutOfBand
            # send still writing this fd must finish (or fail on its
            # own) before the fd is released for reuse.
            async with self._writer_lock(writer):
                try:
                    writer.close()
                except Exception:
                    pass

    async def _dispatch(self, writer, req_id, method, args, kwargs, oneway,
                        trace_ctx=None):
        self._inflight += 1
        try:
            if method == "__ping__":
                result = "pong"
            elif method == "__terminate__":
                result = None
                self._shutdown.set()
            else:
                if faults.enabled():
                    # Liveness faults: `kill` exits the process abruptly
                    # (no teardown — supervision must cope with SIGKILL
                    # semantics); `wedge` blocks the EVENT LOOP (a
                    # time.sleep on the loop thread), so the actor stops
                    # answering pings — the alive-but-unresponsive case.
                    faults.fire(f"actor.{type(self.instance).__name__}")
                # With a propagated trace context, re-enter it and span
                # the whole dispatch, awaits included — for the queue
                # actor that IS the interesting number (e.g. how long
                # new_epoch blocked on the admission window). Dispatches
                # interleave on this one event-loop thread, but each runs
                # as its own asyncio task with its own contextvars
                # Context, so a context held across an await cannot leak
                # into other dispatches' spans; the virtual tid gives
                # each concurrent dispatch its own renderable track (see
                # _acquire_vtid).
                fn = getattr(self.instance, method)
                vtid = _acquire_vtid() if trace_ctx is not None else None
                try:
                    with telemetry.propagated_span(
                        f"actor:{method}", trace_ctx, cat="actor", tid=vtid
                    ) if vtid is not None else contextlib.nullcontext():
                        result = fn(*args, **kwargs)
                        if asyncio.iscoroutine(result):
                            result = await result
                finally:
                    if vtid is not None:
                        _release_vtid(vtid)
            if not oneway:
                if isinstance(result, transport.OutOfBand):
                    # Zero-copy reply: meta in the pickle header, bulk
                    # payload streamed verbatim after it (StoreServer
                    # fetch_vec path) by an executor thread — concurrent
                    # stripe replies ride different cores. The sync
                    # caller reads it with call_vectored/recv_frame.
                    try:
                        async with self._writer_lock(writer):
                            await self._send_out_of_band(
                                writer, req_id, result
                            )
                    except Exception:
                        # The vectored frame may have PARTIALLY hit the
                        # wire: the connection's framing is gone, and an
                        # err reply on it would be consumed as payload
                        # bytes by a blocked reader. Tear the connection
                        # down so the client fails into its
                        # ActorDiedError ladder instead of hanging.
                        try:
                            writer.close()
                        except Exception:
                            pass
                        return
                    result = None  # release buffer keepalives promptly
                else:
                    async with self._writer_lock(writer):
                        transport.write_frame(writer, (req_id, "ok", result))
                        await writer.drain()
        except Exception as exc:  # noqa: BLE001 — propagate to caller
            if not oneway:
                tb = traceback.format_exc()
                try:
                    async with self._writer_lock(writer):
                        transport.write_frame(writer, (req_id, "err", (exc, tb)))
                        await writer.drain()
                except Exception:
                    # The exception itself didn't pickle; the caller still
                    # needs a reply frame or it blocks forever. Send just
                    # the traceback text.
                    try:
                        async with self._writer_lock(writer):
                            transport.write_frame(
                                writer, (req_id, "err", (None, tb))
                            )
                            await writer.drain()
                    except Exception:
                        pass
        finally:
            # Quiescence flush: when the last in-flight dispatch ends,
            # drain buffered spans to the spool. Async actors can run for
            # whole epochs without a depth-0 moment on the loop thread,
            # so relying on the span-close heuristic alone leaves their
            # spans invisible to a concurrent trace_export until process
            # exit. Event-driven and cheap: no-ops when telemetry is off
            # or the buffer is empty. The metrics-registry snapshot
            # spools on the same trigger (rate-limited inside
            # maybe_flush) so this actor's counters/gauges stay visible
            # to the driver's live aggregation mid-run.
            self._inflight -= 1
            if self._inflight == 0:
                _flush_telemetry_spools(maybe=True)

    async def start(self):
        """Bind the server socket; returns once the actor is reachable.
        TCP with port 0 binds an OS-chosen port and rewrites ``address`` —
        the child owns port selection, so there is no bind-race with other
        spawners."""
        self._shutdown = asyncio.Event()
        self._server = await transport.start_server(
            self.address, self._handle_client
        )
        if self.address[0] == "tcp" and self.address[2] == 0:
            port = self._server.sockets[0].getsockname()[1]
            self.address = ("tcp", self.address[1], port)
        setup = getattr(self.instance, "setup", None)
        if setup is not None:
            result = setup()
            if asyncio.iscoroutine(result):
                await result

    async def wait_shutdown(self):
        async with self._server:
            await self._shutdown.wait()
        # Graceful resource teardown before process exit (e.g. the cluster
        # HostAgent reaping its worker pool — a SIGKILLed agent would orphan
        # the pool, and orphans holding the spawner's resource-tracker pipe
        # hang that process's interpreter exit).
        teardown = getattr(self.instance, "teardown", None)
        if teardown is not None:
            result = teardown()
            if asyncio.iscoroutine(result):
                await result


def _actor_main(
    cls, args, kwargs, address: Address, registry_path, ready_q,
    watch_parent: Optional[int] = None,
):
    # Child process entrypoint (spawned: fresh interpreter, no inherited
    # TPU/JAX state).
    if watch_parent is not None:
        # Daemonic children die with a cleanly-exiting parent but NOT with
        # a SIGKILLed one (preemption), and non-daemon actors (those that
        # spawn their own children, e.g. the HostAgent's worker pool)
        # never do; poll the parent pid and exit when orphaned.
        def _watch():
            while True:
                time.sleep(1.0)
                if not _pid_alive(watch_parent):
                    os._exit(0)

        threading.Thread(target=_watch, daemon=True).start()
    # Unconditional: the role tag is process IDENTITY (telemetry spool
    # source records stamp it), not just /actor-filtered fault rules.
    faults.set_role("actor")
    # The continuous profiler (ISSUE 17) samples this host too — env-
    # gated before the import, same contract as the trace flag below.
    if _env.read_flag("RSDL_PROFILE"):
        try:
            from ray_shuffling_data_loader_tpu.telemetry import profiler

            profiler.start()
        except Exception:
            pass
    if _env.read_flag("RSDL_TRACE"):
        # Entrypoint-equivalent of telemetry.enabled(): a freshly
        # spawned process can only have been enabled via env, and the
        # flag read skips importing the trace module when off.
        telemetry.set_process_name(f"actor:{cls.__name__}-{os.getpid()}")
    try:
        instance = cls(*args, **kwargs)
        host = _ActorHost(instance, address)
    except Exception:
        ready_q.put(("err", traceback.format_exc()))
        return

    async def run():
        # Bind strictly before announcing readiness: callers may issue a
        # method call the moment spawn_actor returns.
        await host.start()
        if registry_path is not None:
            tmp = registry_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {"address": list(host.address), "pid": os.getpid()}, f
                )
            os.replace(tmp, registry_path)
        # The bound address travels back (it differs from the requested one
        # for tcp port 0).
        ready_q.put(("ok", list(host.address)))
        await host.wait_shutdown()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    finally:
        # Graceful terminate reaches here; drain this actor's spans and
        # final metrics snapshot to their spools before the process
        # exits (atexit also fires on clean exits, but not on the
        # SIGKILL escalation path).
        _flush_telemetry_spools()
        if registry_path is not None:
            try:
                os.unlink(registry_path)
            except FileNotFoundError:
                pass
        if address[0] == "unix":
            try:
                os.unlink(address[1])
            except FileNotFoundError:
                pass


# ---------------------------------------------------------------------------
# Client side
# ---------------------------------------------------------------------------


class ActorHandle:
    """Client-side proxy. ``handle.call("method", ...)`` blocks for the
    result; ``call_oneway`` is fire-and-forget; ``call_async`` awaits on an
    asyncio loop."""

    def __init__(self, address: Address, pid: Optional[int] = None, name=None):
        self.address = tuple(address)
        self.pid = pid
        self.name = name
        self._local = threading.local()
        self._async_clients: Dict[Any, "_AsyncActorClient"] = {}
        self._req_counter = 0
        self._counter_lock = threading.Lock()

    # pickling: handles travel inside task args across processes
    def __getstate__(self):
        return {"address": self.address, "pid": self.pid, "name": self.name}

    def __setstate__(self, state):
        self.__init__(state["address"], state["pid"], state["name"])

    def _conn(self) -> transport.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            try:
                conn = transport.Connection(self.address)
            except (ConnectionError, FileNotFoundError, OSError) as e:
                raise ActorDiedError(
                    f"cannot connect to actor {self.name or self.address}: {e}"
                ) from e
            self._local.conn = conn
        return conn

    def _next_id(self) -> int:
        with self._counter_lock:
            self._req_counter += 1
            return self._req_counter

    def _send_with_retry(self, req_id, method, args, kwargs, oneway):
        """Connect + send one request frame, retrying transient
        connection failures with bounded backoff (``call_policy``).

        Only the PRE-response window retries: a connect refusal or a
        send-time reset means the request never dispatched (a partial
        frame is dropped by the server's framing loop without
        executing), so a retry cannot double-execute. Failures after the
        frame is fully sent — recv errors — are ambiguous (the method
        may have run) and are NOT retried here; those stay
        ``ActorDiedError`` for callers' existing death handling."""
        policy = call_policy()
        last: Optional[Exception] = None
        for attempt, handle in policy.attempts(site="actor.send"):
            try:
                conn = self._conn()
                conn.send(
                    (req_id, method, args, kwargs, oneway, _trace_ctx())
                )
                return conn
            except (ActorDiedError, ConnectionError, OSError) as e:
                self._local.conn = None
                last = e
                if attempt >= policy.max_attempts:
                    break
                handle.backoff(str(e))
        raise ActorDiedError(
            f"cannot reach actor {self.name or self.address} "
            f"after {policy.max_attempts} attempts: {last}"
        ) from last

    def call(self, method: str, *args, **kwargs):
        # One response tail for plain AND vectored calls (a vectored
        # reply to a plain call is consumed into a throwaway buffer —
        # methods that return OutOfBand are only ever invoked through
        # call_vectored, which hands the payload back). ``into`` is a
        # RESERVED kwarg name on this client (the vectored allocator);
        # passing explicit into=None here makes a remote-method kwarg
        # named ``into`` fail loudly (duplicate keyword) instead of
        # being silently consumed as the allocator.
        return self.call_vectored(method, *args, into=None, **kwargs)[0]

    def call_oneway(self, method: str, *args, **kwargs) -> None:
        self._send_with_retry(
            self._next_id(), method, args, kwargs, True
        )

    def call_vectored(self, method: str, *args, into=None, **kwargs):
        """Call a method whose reply may be a :class:`transport.OutOfBand`
        vectored frame. Returns ``(meta, payload_view)``; the payload is
        landed via ``recv_into`` in the buffer ``into(total_bytes)``
        returns (the zero-copy fetch path mmaps the destination cache
        file), or ``(result, None)`` when the method replied plainly.

        An allocator with a truthy ``wants_meta`` attribute is called
        ``into(total_bytes, reply_meta)`` — the striped fetch needs the
        reply's stripe range before it can hand out the destination
        window (see :meth:`transport.Connection.recv_frame`)."""
        req_id = self._next_id()
        if into is not None and getattr(into, "wants_meta", False):
            user_into = into

            def _shim(total, frame):
                # frame is the raw (req_id, status, meta) reply tuple at
                # the transport layer; hand the caller just the meta.
                return user_into(total, frame[2])

            _shim.wants_meta = True
            into = _shim
        conn = self._send_with_retry(req_id, method, args, kwargs, False)
        try:
            while True:
                frame, payload = conn.recv_frame(into=into)
                resp_id, status, meta = frame
                if resp_id == req_id:
                    break
        except (ConnectionError, OSError) as e:
            self._local.conn = None
            raise ActorDiedError(
                f"actor {self.name or self.address} died mid-call: {e}"
            ) from e
        if status == "okv":
            return meta, payload
        if status == "ok":
            return meta, None
        exc, tb = meta
        if isinstance(exc, Exception):
            raise exc
        raise RemoteError(f"remote call {method} failed:\n{tb}")

    async def call_async(self, method: str, *args, **kwargs):
        loop = asyncio.get_running_loop()
        client = self._async_clients.get(loop)
        if client is None or client.closed:
            client = _AsyncActorClient(self.address)
            await client.connect()
            self._async_clients[loop] = client
        return await client.call(method, *args, **kwargs)

    def call_with_timeout(self, method: str, *args, timeout: float = 30.0,
                          **kwargs):
        """One-shot call on a dedicated timed connection.

        The per-thread connection deliberately has no socket timeout
        (streaming gets block indefinitely by design); control-plane calls
        that must not wedge on a half-dead host — placement, remote spawn —
        use this instead. Raises :class:`ActorDiedError` on timeout or
        connection failure, so callers' existing died-actor fallbacks fire.
        """
        try:
            conn = transport.Connection(self.address, timeout=timeout)
        except (ConnectionError, FileNotFoundError, OSError) as e:
            raise ActorDiedError(
                f"actor {self.name or self.address} unreachable: {e}"
            ) from e
        try:
            conn.send((0, method, args, kwargs, False, _trace_ctx()))
            while True:
                resp_id, status, payload = conn.recv()
                if resp_id == 0:
                    break
        except (ConnectionError, OSError) as e:
            raise ActorDiedError(
                f"actor {self.name or self.address} did not answer "
                f"{method} within {timeout}s: {e}"
            ) from e
        finally:
            conn.close()
        if status == "ok":
            return payload
        exc, tb = payload
        if isinstance(exc, Exception):
            raise exc
        raise RemoteError(f"remote call {method} failed:\n{tb}")

    def ping(self, timeout: float = None) -> bool:
        # A dedicated short-lived connection with a socket timeout: the
        # regular per-thread connection has no timeout, and a wedged (alive
        # but non-responsive) actor must not hang wait_ready's deadline.
        try:
            conn = transport.Connection(self.address, timeout=timeout)
        except (ConnectionError, FileNotFoundError, OSError):
            return False
        try:
            conn.send((0, "__ping__", (), {}, False))
            _, status, payload = conn.recv()
            return status == "ok" and payload == "pong"
        except Exception:
            return False
        finally:
            conn.close()

    def wait_ready(self, timeout: float = 30.0) -> None:
        """Block until the actor answers a ping (reference
        ``BatchQueue.ready``, ``batch_queue.py:67-71``)."""
        deadline = time.monotonic() + timeout
        delay = 0.005
        while True:
            if self.ping(timeout=min(2.0, timeout)):
                return
            if time.monotonic() > deadline:
                raise ActorDiedError(
                    f"actor {self.name or self.address} not ready "
                    f"after {timeout}s"
                )
            time.sleep(delay)
            delay = min(delay * 2, 0.25)

    def terminate(self, force: bool = False, grace_period_s: float = 5.0):
        """Graceful-then-forceful shutdown (reference
        ``BatchQueue.shutdown``, ``batch_queue.py:333-355``)."""
        if not force:
            try:
                self.call("__terminate__")
            except (ActorDiedError, RemoteError, ConnectionError):
                pass
            deadline = time.monotonic() + grace_period_s
            while time.monotonic() < deadline:
                if self.pid is None or not _pid_alive(self.pid):
                    return
                time.sleep(0.02)
        if self.pid is not None and _pid_alive(self.pid):
            try:
                os.kill(self.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass


class _AsyncActorClient:
    """Asyncio client with request/response demultiplexing."""

    def __init__(self, address: Address):
        self.address = address
        self._pending: Dict[int, asyncio.Future] = {}
        self._req = 0
        self.closed = False
        self._reader = self._writer = self._reader_task = None

    async def connect(self):
        self._reader, self._writer = await transport.open_connection(
            self.address
        )
        self._reader_task = asyncio.get_running_loop().create_task(
            self._read_loop()
        )

    async def _read_loop(self):
        try:
            while True:
                resp_id, status, payload = await transport.read_frame(
                    self._reader
                )
                fut = self._pending.pop(resp_id, None)
                if fut is None or fut.done():
                    continue
                if status == "ok":
                    fut.set_result(payload)
                else:
                    exc, tb = payload
                    fut.set_exception(
                        exc
                        if isinstance(exc, Exception)
                        else RemoteError(f"remote failure:\n{tb}")
                    )
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            self.closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ActorDiedError(f"actor died: {e}"))
            self._pending.clear()

    async def call(self, method, *args, **kwargs):
        self._req += 1
        req_id = self._req
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        transport.write_frame(
            self._writer, (req_id, method, args, kwargs, False, _trace_ctx())
        )
        await self._writer.drain()
        return await fut


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


# ---------------------------------------------------------------------------
# Spawning and discovery
# ---------------------------------------------------------------------------


def spawn_actor(
    cls,
    *args,
    name: Optional[str] = None,
    runtime_dir: str,
    host: Optional[str] = None,
    port: int = 0,
    daemon: bool = True,
    **kwargs,
) -> ActorHandle:
    """Start an actor process and return a connected handle.

    With ``host`` set, the actor listens on TCP (multi-host control plane);
    otherwise on a unix socket under ``runtime_dir``. ``daemon=False`` is
    for actors that must spawn child processes themselves (multiprocessing
    forbids daemonic parents); they get a parent-death watchdog instead.
    """
    os.makedirs(_registry_dir(runtime_dir), exist_ok=True)
    token = secrets.token_hex(4)
    if host is not None:
        # port 0: the child binds an OS-chosen port and reports it back.
        address: Address = ("tcp", host, port)
    else:
        address = ("unix", os.path.join(runtime_dir, f"a-{token}.sock"))
    registry_path = (
        _registry_path(runtime_dir, name) if name is not None else None
    )
    if registry_path is not None and os.path.exists(registry_path):
        # A SIGKILLed actor never unlinks its record; a live holder is a
        # real conflict, a dead one is evicted and the name reclaimed
        # (same policy as the cluster registry's register_named_actor).
        # Liveness is judged by the record's PID first — local records
        # always carry one, and a pid probe cannot false-negative on a
        # loaded host the way a short ping can (evicting a live-but-busy
        # actor would spawn a same-name duplicate: split-brain). Only a
        # pid-less record falls back to pings, escalating like the
        # cluster scheduler's ladder before concluding death.
        stale = resolve_actor(name, runtime_dir)
        holder_alive = False
        if stale is not None:
            if stale.pid is not None:
                holder_alive = _pid_alive(stale.pid)
            else:
                holder_alive = any(
                    stale.ping(timeout=t) for t in (2.0, 5.0, 10.0)
                )
        if holder_alive:
            raise ValueError(f"actor name {name!r} already registered")
        try:
            # rsdl-lint: disable=barrier-order -- evicting a DEAD
            # foreign actor's stale record, not self-deregistration:
            # the dead holder's spools were flushed (or lost) with it,
            # this process has nothing to flush on its behalf
            os.unlink(registry_path)
        except FileNotFoundError:
            pass

    ctx = mp.get_context("spawn")
    ready_q = ctx.Queue()
    proc = ctx.Process(
        target=_actor_main,
        args=(
            cls, args, kwargs, address, registry_path, ready_q,
            os.getpid(),
        ),
        daemon=daemon,
    )
    proc.start()
    # Readiness handshake with two escapes beyond the mp.Queue message:
    # (a) the registry file the child atomically writes just before its
    #     ready_q.put — observed once (2026-07-31): the child was up and
    #     serving while the queue's feeder thread wedged on a futex, so
    #     the message never arrived and the old loop polled forever;
    # (b) an overall deadline (generous: the actor ctor runs before
    #     readiness and a first-touch jax init can legitimately take
    #     minutes) that kills the child and fails cleanly instead of
    #     wedging the spawner.
    ready_timeout = float(
        os.environ.get("RSDL_SPAWN_READY_TIMEOUT_S", "600")
    )
    deadline = time.monotonic() + ready_timeout
    status = payload = None
    while True:
        try:
            status, payload = ready_q.get(timeout=0.2)
            break
        except Exception:  # queue.Empty
            if not proc.is_alive():
                raise RuntimeError(
                    f"actor {cls.__name__} process exited during startup "
                    f"(exitcode={proc.exitcode})"
                ) from None
            if registry_path is not None and os.path.exists(registry_path):
                try:
                    with open(registry_path) as f:
                        record = json.load(f)
                    status, payload = "ok", record["address"]
                    break
                except (json.JSONDecodeError, KeyError, OSError):
                    pass  # mid-replace; next poll sees it whole
            if time.monotonic() > deadline:
                proc.terminate()
                proc.join(5)
                raise RuntimeError(
                    f"actor {cls.__name__} did not announce readiness "
                    f"within {ready_timeout:.0f}s (child alive; ready-"
                    "queue handshake lost?)"
                )
    if status != "ok":
        raise RuntimeError(f"actor {cls.__name__} failed to start:\n{payload}")
    handle = ActorHandle(tuple(payload), pid=proc.pid, name=name)
    handle._process = proc  # keep a reference for join/cleanup by the owner
    return handle


def resolve_actor(name: str, runtime_dir: str) -> Optional[ActorHandle]:
    path = _registry_path(runtime_dir, name)
    try:
        with open(path) as f:
            record = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None
    return ActorHandle(
        tuple(record["address"]), pid=record.get("pid"), name=name
    )


def connect_actor(
    name: str,
    runtime_dir: str,
    num_retries: int = 5,
    fallback_resolver=None,
) -> ActorHandle:
    """Discover a named actor, retrying with capped, jittered
    exponential backoff via the shared :class:`~.retry.RetryPolicy`
    (parity with reference ``connect_queue_actor``,
    ``batch_queue.py:358-380``; the old loop doubled its sleep without a
    cap or jitter, so N trainers reconnecting after a queue-actor
    restart thundering-herded in lockstep).

    ``fallback_resolver(name) -> Optional[ActorHandle]`` is consulted when
    the local session registry misses (cluster mode: the head's registry).
    """
    policy = connect_policy(num_retries)
    last_exc: Optional[Exception] = None
    for attempt, backoff in policy.attempts(site="connect_actor"):
        handle = resolve_actor(name, runtime_dir)
        if handle is None and fallback_resolver is not None:
            handle = fallback_resolver(name)
        if handle is not None and handle.ping():
            return handle
        last_exc = ActorDiedError(f"no live actor registered as {name!r}")
        if attempt < policy.max_attempts:
            backoff.backoff(str(last_exc))
    raise ValueError(
        f"Unable to connect to actor {name} after {num_retries} retries. "
        f"Last error: {last_exc!s}"
    )
