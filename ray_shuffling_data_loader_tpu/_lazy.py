"""Lazy module proxies for env-gated planes (ISSUE 14).

The repo's gate-integrity invariant (enforced by
``tools/rsdl_lint.py``, checker ``gate-integrity``) is that env-gated
planes — the telemetry planes and ``runtime/{journal,faults,elastic}``
— are never *module-level* imports of the core data-path modules:
importing ``shuffle`` or ``runtime.store`` must not execute a gated
plane's module body. Hot call sites still want module-attribute syntax
(``_audit.enabled()``), so this shim gives them a proxy whose first
attribute access performs the real (function-level, hence allowed)
import and then delegates forever after.

Cost: one ``__getattr__`` + ``getattr`` per attribute access after the
first (the import itself happens once). Every site this proxies is
per-task / per-batch / per-frame, never per-row, so the overhead is
noise next to the work the call does.
"""

from __future__ import annotations


class _LazyModule:
    """Attribute-forwarding proxy that imports ``name`` on first use."""

    __slots__ = ("_rsdl_lazy_name", "_rsdl_lazy_mod")

    def __init__(self, name: str):
        object.__setattr__(self, "_rsdl_lazy_name", name)
        object.__setattr__(self, "_rsdl_lazy_mod", None)

    def _rsdl_resolve(self):
        mod = self._rsdl_lazy_mod
        if mod is None:
            import importlib

            mod = importlib.import_module(self._rsdl_lazy_name)
            object.__setattr__(self, "_rsdl_lazy_mod", mod)
        return mod

    def __getattr__(self, attr: str):
        return getattr(self._rsdl_resolve(), attr)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        state = "loaded" if self._rsdl_lazy_mod is not None else "unloaded"
        return f"<lazy module {self._rsdl_lazy_name!r} ({state})>"


def lazy_module(name: str) -> _LazyModule:
    """Return a proxy for module ``name`` that imports it on first
    attribute access. The returned object is NOT the module (identity
    checks and ``sys.modules`` lookups see the real module only after
    first use); call sites that need the module object itself should do
    a function-level import instead."""
    return _LazyModule(name)
