"""Wall-clock timing helper shared by the stats hooks and benchmarks."""

from __future__ import annotations

import timeit
from contextlib import contextmanager


@contextmanager
def timer():
    """``with timer() as t: ...; t()`` -> elapsed seconds (callable stays
    live after the block; matches the reference's ``timeit.default_timer``
    deltas, reference ``shuffle.py:149-167``)."""
    start = timeit.default_timer()
    end = None

    def elapsed() -> float:
        return (end if end is not None else timeit.default_timer()) - start

    yield elapsed
    end = timeit.default_timer()
