"""JAX platform pinning.

Some TPU plugins override ``JAX_PLATFORMS`` from the environment during
their registration; the config API takes precedence, so code that must
honor the user's platform choice (CPU smoke runs, virtual-device sharding
validation) re-asserts it through the config. Used by the examples, the
test conftest, and the driver entry points.
"""

from __future__ import annotations

import os
from typing import Optional


def pin_platform(name: str) -> None:
    """Force JAX onto ``name`` (e.g. ``"cpu"``), overriding any plugin's
    default. Must run before the first computation; safe after ``import
    jax`` (backends initialize lazily)."""
    import jax

    jax.config.update("jax_platforms", name)


def force_platform_from_env(var: str = "JAX_PLATFORMS") -> Optional[str]:
    """Re-assert ``$JAX_PLATFORMS`` via the config API; returns the pinned
    name (or None if the variable is unset)."""
    name = os.environ.get(var)
    if name:
        pin_platform(name)
    return name
