"""Shared utilities: platform pinning, wall-clock timing."""

from ray_shuffling_data_loader_tpu.utils.platform import (  # noqa: F401
    force_platform_from_env,
    pin_platform,
)
from ray_shuffling_data_loader_tpu.utils.timing import timer  # noqa: F401

__all__ = ["force_platform_from_env", "pin_platform", "timer"]
