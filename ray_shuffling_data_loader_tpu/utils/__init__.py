"""Shared utilities: platform pinning, wall-clock timing, path kinds."""

import os

from ray_shuffling_data_loader_tpu.utils.platform import (  # noqa: F401
    force_platform_from_env,
    pin_platform,
)
from ray_shuffling_data_loader_tpu.utils.timing import timer  # noqa: F401


def decode_use_threads(num_concurrent_tasks: int) -> bool:
    """Should one Parquet decode task use Arrow's internal thread pool?

    Parallelism normally comes from the worker POOL (one decode task per
    file); per-task Arrow threads only help when the host has idle cores
    beyond the concurrently-decoding tasks — e.g. a ~120-core TPU-VM
    host decoding a 16-file dataset leaves >100 cores idle without them.
    On a saturated host they oversubscribe instead (measured 5x slower,
    see ``shuffle.read_parquet_columns``). Heuristic: engage when the
    host has at least twice as many cores as concurrent decode tasks.
    ``RSDL_DECODE_THREADS=on|off`` overrides.
    """
    env = os.environ.get("RSDL_DECODE_THREADS", "").lower()
    if env in ("on", "1", "true"):
        return True
    if env in ("off", "0", "false"):
        return False
    return (os.cpu_count() or 1) >= 2 * max(1, num_concurrent_tasks)


def arrow_decode_threads(stage_tasks: int) -> bool:
    """Worker-side decision + pool cap for one decode task.

    Called INSIDE the pool worker that is about to decode (so the core
    count consulted is the core count of the host actually doing the
    work — the driver that submitted the stage may have a different
    shape). ``stage_tasks`` is how many decode tasks the stage submitted
    cluster-wide; concurrency on THIS host can't exceed
    ``min(stage_tasks, local cores)``.

    When threads engage, Arrow's process-global thread pool is CAPPED to
    this task's fair share of the host (``cores // concurrent``) —
    Arrow's default pool is cpu_count-sized PER PROCESS, so N concurrent
    uncapped readers would run N x cores threads, re-creating the
    oversubscription the pool-parallel design avoids. A pool worker runs
    one task at a time, so setting the cap here is race-free.
    """
    cores = os.cpu_count() or 1
    concurrent = min(max(1, stage_tasks), cores)
    if not decode_use_threads(concurrent):
        return False
    try:
        import pyarrow as pa

        pa.set_cpu_count(max(2, cores // concurrent))
    except Exception:
        return False
    return True


def decode_rowgroup_threads(stage_tasks: int) -> int:
    """Row-group decode parallelism for ONE Parquet decode task — the
    ``RSDL_DECODE_ROWGROUPS`` gate plus the same fair-share logic as
    :func:`arrow_decode_threads`, returning a thread COUNT instead of
    arming Arrow's pool (the row-group plan owns its threads and reads
    each range with ``use_threads=False``, so the two parallelism
    sources never stack).

    * unset / ``off`` — 1 (single-shot decode; the zero-overhead
      default: no decode pool thread ever exists);
    * ``auto`` — the task's fair share of the host
      (``cores // concurrent``) when idle cores exist, else 1 — the
      exact condition :func:`decode_use_threads` applies to Arrow's
      pool, so ``auto`` can never oversubscribe a saturated host;
    * ``on`` — fair share, floored at 2 (engage even on a host with no
      idle cores — the operator asked);
    * an integer — that many threads, verbatim (CI forces ``2`` on the
      2-core host so the parallel assembly path is exercised).
    """
    env = os.environ.get("RSDL_DECODE_ROWGROUPS", "").strip().lower()
    if env in ("", "off", "0", "false"):
        return 1
    cores = os.cpu_count() or 1
    concurrent = min(max(1, stage_tasks), cores)
    fair = max(1, cores // concurrent)
    if env == "auto":
        return fair if cores >= 2 * concurrent else 1
    if env in ("on", "true"):
        return max(2, fair)
    try:
        return max(1, int(env))
    except ValueError:
        return fair if cores >= 2 * concurrent else 1


def shuffle_plan_spec():
    """The ONE parser of ``RSDL_SHUFFLE_PLAN`` — the seeded plan FAMILY
    every schedule partitions with (ISSUE 12): ``("rowwise", 0)`` or
    ``("block", G)``.

    * unset / ``rowwise`` — the per-row uniform assignment (every row
      draws its reducer independently). Maximal dispersion, but every
      row group holds rows for every reducer, so per-reducer row-group
      pruning can never engage (BENCHLOG r11's honest limit).
    * ``block`` / ``block:G`` — row-group-aligned blocks of ``G``
      consecutive row groups (default 1) are assigned to reducers by a
      seeded permutation; rows inside a block travel together and the
      reduce-side full permutation supplies within-reducer randomness
      (RINAS, PAPERS.md). Per-reducer selections become DISJOINT by
      construction, so the selective schedule decodes each group
      exactly once per epoch.

    A malformed value raises: the plan family determines the delivered
    stream, and silently falling back to a different family would be a
    reproducibility bug, not a tolerable default. Parsed driver-side
    before any task is submitted, so the raise is early and loud."""
    env = os.environ.get("RSDL_SHUFFLE_PLAN", "").strip().lower()
    if env in ("", "rowwise", "row", "off"):
        return ("rowwise", 0)
    if env == "block":
        return ("block", 1)
    if env.startswith("block:"):
        try:
            g = int(env.split(":", 1)[1])
        except ValueError:
            g = 0
        if g >= 1:
            return ("block", g)
    raise ValueError(
        f"RSDL_SHUFFLE_PLAN={env!r}: expected 'rowwise', 'block', or "
        "'block:<G>' with integer G >= 1 (row groups per block)"
    )


def shuffle_plan_label() -> str:
    """The plan family as a metric-label value (``rowwise`` or
    ``block:G``) — the vocabulary the ``{schedule,plan}``-labeled decode
    counters and the audit quality gauges share."""
    family, g = shuffle_plan_spec()
    return family if family == "rowwise" else f"block:{g}"


def is_remote_path(path: str) -> bool:
    """True for URI-style paths (gs://, s3://, ...) that route through a
    non-local filesystem — one definition, shared by Parquet decode and
    the fsspec stats writers."""
    return "://" in path


# Schemes pyarrow's native C++ filesystems resolve directly — preferred
# over fsspec (no extra python deps, zero-copy reads). Everything else
# with a scheme goes through fsspec (file://, memory://, http://, ...).
_PYARROW_NATIVE_SCHEMES = ("s3", "gs", "gcs", "hdfs", "viewfs")


def parquet_filesystem(path: str):
    """Resolve a dataset path to ``(filesystem, relative_path)`` for
    pyarrow readers (``pq.read_table(..., filesystem=fs)`` /
    ``pq.ParquetFile(..., filesystem=fs)``).

    Local paths return ``(None, path)`` (pyarrow mmap-reads them
    directly). The reference only ever reads local NVMe
    (``/root/reference/ray_shuffling_data_loader/shuffle.py:151`` via
    ``pd.read_parquet`` of plain paths); TPU-VM pods routinely read
    training data from object storage instead, so every Parquet input
    site here routes through this resolver.
    """
    if not is_remote_path(path):
        return None, path
    from pyarrow import fs as pafs

    scheme = path.split("://", 1)[0]
    if scheme in _PYARROW_NATIVE_SCHEMES:
        return pafs.FileSystem.from_uri(path)
    import fsspec

    fs, rel = fsspec.core.url_to_fs(path)
    return pafs.PyFileSystem(pafs.FSSpecHandler(fs)), rel


__all__ = [
    "arrow_decode_threads",
    "decode_rowgroup_threads",
    "decode_use_threads",
    "force_platform_from_env",
    "is_remote_path",
    "parquet_filesystem",
    "pin_platform",
    "shuffle_plan_label",
    "shuffle_plan_spec",
    "timer",
]
