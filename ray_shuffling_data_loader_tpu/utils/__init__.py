"""Shared utilities: platform pinning, wall-clock timing, path kinds."""

from ray_shuffling_data_loader_tpu.utils.platform import (  # noqa: F401
    force_platform_from_env,
    pin_platform,
)
from ray_shuffling_data_loader_tpu.utils.timing import timer  # noqa: F401


def is_remote_path(path: str) -> bool:
    """True for URI-style paths (gs://, s3://, ...) that route through a
    non-local filesystem — one definition, shared by Parquet decode and
    the fsspec stats writers."""
    return "://" in path


# Schemes pyarrow's native C++ filesystems resolve directly — preferred
# over fsspec (no extra python deps, zero-copy reads). Everything else
# with a scheme goes through fsspec (file://, memory://, http://, ...).
_PYARROW_NATIVE_SCHEMES = ("s3", "gs", "gcs", "hdfs", "viewfs")


def parquet_filesystem(path: str):
    """Resolve a dataset path to ``(filesystem, relative_path)`` for
    pyarrow readers (``pq.read_table(..., filesystem=fs)`` /
    ``pq.ParquetFile(..., filesystem=fs)``).

    Local paths return ``(None, path)`` (pyarrow mmap-reads them
    directly). The reference only ever reads local NVMe
    (``/root/reference/ray_shuffling_data_loader/shuffle.py:151`` via
    ``pd.read_parquet`` of plain paths); TPU-VM pods routinely read
    training data from object storage instead, so every Parquet input
    site here routes through this resolver.
    """
    if not is_remote_path(path):
        return None, path
    from pyarrow import fs as pafs

    scheme = path.split("://", 1)[0]
    if scheme in _PYARROW_NATIVE_SCHEMES:
        return pafs.FileSystem.from_uri(path)
    import fsspec

    fs, rel = fsspec.core.url_to_fs(path)
    return pafs.PyFileSystem(pafs.FSSpecHandler(fs)), rel


__all__ = [
    "force_platform_from_env",
    "is_remote_path",
    "parquet_filesystem",
    "pin_platform",
    "timer",
]
