"""utils subpackage."""
