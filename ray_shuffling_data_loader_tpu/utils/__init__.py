"""Shared utilities: platform pinning, wall-clock timing, path kinds."""

from ray_shuffling_data_loader_tpu.utils.platform import (  # noqa: F401
    force_platform_from_env,
    pin_platform,
)
from ray_shuffling_data_loader_tpu.utils.timing import timer  # noqa: F401


def is_remote_path(path: str) -> bool:
    """True for URI-style paths (gs://, s3://, ...) that route through a
    non-local filesystem — one definition, shared by Parquet decode and
    the fsspec stats writers."""
    return "://" in path


__all__ = [
    "force_platform_from_env",
    "is_remote_path",
    "pin_platform",
    "timer",
]
