"""Framework-agnostic shuffling dataset API.

Parity with the reference ``ShufflingDataset`` (``dataset.py:15-188``):
rank 0 creates the named batch queue and kicks off the multi-epoch shuffle;
every rank iterates exact-``batch_size`` batches re-cut from streamed
reducer outputs with a carry-over buffer, and acks consumption back to the
queue to drive the epoch-window backpressure.

Differences from the reference (TPU-first, not a port):

* Batches are :class:`~.runtime.ColumnBatch` (named contiguous numpy
  columns, zero-copy views over shared memory) instead of pandas
  DataFrames — the layout the JAX/HBM staging path consumes directly.
  Use ``batch.to_pandas()`` where a DataFrame is wanted.
* The shuffle driver runs on a daemon thread in the rank-0 process,
  submitting stage tasks to the runtime's worker pool (the reference runs it
  as a detached Ray task, ``dataset.py:68-74``).
* Reducer-output segments are freed as soon as they have been sliced into
  training batches; on Linux the pages live until the last view drops.
"""

from __future__ import annotations

import os
import threading
from typing import Iterator, List, Optional

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.batch_queue import (
    BatchQueue,
    DEFAULT_QUEUE_NAME,
)
from ray_shuffling_data_loader_tpu.runtime import ColumnBatch, ObjectRef
from ray_shuffling_data_loader_tpu.runtime.store import (
    device_batch_rows,
    is_device_batch,
    iter_packed_batches,
    logical_columns,
)
from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle
# Gated planes (ISSUE 14 gate-integrity): lazy proxies, resolved on
# first attribute access — importing the dataset layer must not execute
# a telemetry-plane module body.
from ray_shuffling_data_loader_tpu._lazy import lazy_module

_audit = lazy_module("ray_shuffling_data_loader_tpu.telemetry.audit")
_phases = lazy_module("ray_shuffling_data_loader_tpu.telemetry.phases")

# Default reducer share of cluster cores (reference ``dataset.py:12``).
REDUCER_CLUSTER_CORE_SHARE = 0.6


def default_num_reducers(num_trainers: int) -> int:
    return max(
        1,
        int(num_trainers * (os.cpu_count() or 1) * REDUCER_CLUSTER_CORE_SHARE),
    )


class _ShuffleResult:
    """Holds the background shuffle driver's outcome (the analog of the
    detached-task ref the reference ``ray.get``s at ``dataset.py:186-188``)."""

    def __init__(self):
        self.duration: Optional[float] = None
        self.error: Optional[BaseException] = None
        self.thread: Optional[threading.Thread] = None

    def join(self):
        self.thread.join()
        if self.error is not None:
            raise self.error


class CarryRebatcher:
    """The exact-``batch_size`` re-batching algebra, isolated.

    Reducer outputs arrive in arbitrary sizes; training wants exact
    batches with a carry buffer spanning output boundaries (reference
    ``dataset.py:118-182``, minus its dropped-tail bug at ``:160-168``).
    Kept free of queue/store machinery so the hypothesis property suite
    (``tests/test_rebatch_property.py``) drives the PRODUCTION algebra
    with in-memory outputs — the iterator below feeds it the real
    stream. ``skip_batches`` counts suppressed batches in yield order
    (the final partial counts as one batch).
    """

    def __init__(self, batch_size: int, skip_batches: int = 0):
        self.batch_size = batch_size
        self.to_skip = skip_batches
        self.buf: Optional[ColumnBatch] = None

    def feed(self, cb: ColumnBatch) -> Iterator[ColumnBatch]:
        """Yield every full batch completed by this reducer output."""
        batch_size = self.batch_size
        offset = batch_size - (self.buf.num_rows if self.buf else 0)
        # Top up the carry buffer with a front slice.
        self.buf = ColumnBatch.concat([self.buf, cb.slice(0, offset)])
        if self.buf.num_rows == batch_size:
            if self.to_skip > 0:
                self.to_skip -= 1
            else:
                yield self.buf
            self.buf = None
        # Whole batches straight from this output, then the short tail
        # into the carry buffer.
        start = min(offset, cb.num_rows)
        num_full = (cb.num_rows - start) // batch_size
        num_skipped = min(self.to_skip, num_full)
        self.to_skip -= num_skipped
        for i in range(num_skipped, num_full):
            lo = start + i * batch_size
            yield cb.slice(lo, lo + batch_size)
        tail = start + num_full * batch_size
        if tail < cb.num_rows:
            self.buf = cb.slice(tail, cb.num_rows)

    def finish(self, drop_last: bool) -> Optional[ColumnBatch]:
        """The final partial batch, unless dropped/skipped/empty."""
        buf, self.buf = self.buf, None
        if buf is not None and buf.num_rows > 0 and not drop_last:
            if self.to_skip > 0:
                self.to_skip -= 1
                return None
            return buf
        return None


class ShufflingDataset:
    """A shuffling dataset that yields batches upon iteration.

    Constructing this on rank 0 kicks off shuffling for up to
    ``max_concurrent_epochs`` epochs. Constructor signature matches the
    reference (``dataset.py:37-48``) plus a deterministic ``seed``.

    Args:
        filenames: Paths to input Parquet files.
        num_epochs: Number of training epochs.
        num_trainers: Number of trainer workers.
        batch_size: Rows per yielded batch.
        rank: This trainer's rank.
        drop_last: Drop the final incomplete batch. Default False.
        num_reducers: Shuffler reducer count. Default
            ``num_trainers × cores × 0.6`` (reference ``dataset.py:46-48``).
        max_concurrent_epochs: Epoch pipelining window. Default 2.
        seed: Root seed for the per-epoch shuffle permutations.
        queue_name: Name of the shared batch-queue endpoint.
        start_epoch: First epoch to shuffle/consume (checkpoint resume;
            epoch indices stay absolute so permutations match the
            original run).
    """

    def __init__(
        self,
        filenames: List[str],
        num_epochs: int,
        num_trainers: int,
        batch_size: int,
        rank: int,
        drop_last: bool = False,
        num_reducers: Optional[int] = None,
        max_concurrent_epochs: int = 2,
        seed: int = 0,
        queue_name: str = DEFAULT_QUEUE_NAME,
        start_epoch: int = 0,
        narrow_to_32: bool = False,
        cache_decoded: Optional[bool] = None,
        stats_collector=None,
        device_layout: Optional[dict] = None,
    ):
        """``narrow_to_32``: cast 64-bit columns to 32-bit at Parquet
        decode time, inside the map tasks. Every downstream pass
        (partition scatter, concat+permute, shared-memory residency,
        cross-host fetch) then moves half the bytes. Only safe when
        values fit (int32 ids / float32 labels) — the device path
        (:class:`~.jax_dataset.JaxShufflingDataset`) turns it on because
        it narrows to 32-bit at staging anyway.

        ``device_layout``: device-direct delivery (ROADMAP 3) — the
        staging consumer's ``{"batch": B, "columns": [...]}`` layout.
        Reducers then emit batch-aligned packed segments; this iterator
        yields each packed batch as zero-copy logical column views (with
        ``.packed`` exposing the raw ``[n_cols, B]`` staging block) and
        routes only the boundary remainders through the carry rebatcher.
        The yielded row stream is bit-identical to the layout-off path."""
        runtime.ensure_initialized()
        if num_reducers is None:
            num_reducers = default_num_reducers(num_trainers)
        self._batch_size = batch_size

        # Service plane (ISSUE 15): capture the caller's ambient job so
        # the shuffle-driver THREAD below runs inside it (threadlocals
        # do not cross threads) — the queue name created here and the
        # driver's job-scoped resources must agree. NO auto-registration
        # here: trainer ranks in other threads/processes could never
        # learn an implicit job's id and would connect to an unscoped
        # name the producer never spawned — job-scoped queues require
        # the caller's job_context (or RSDL_JOB_ID), docs/service.md
        # "Boundary". Env-guarded before the import: service off means
        # no plane load, no behavior change.
        service_job = None
        if os.environ.get("RSDL_SERVICE"):
            try:
                from ray_shuffling_data_loader_tpu.runtime import service

                if service.enabled():
                    service_job = service.current_job()
            except Exception:
                service_job = None

        if rank == 0:
            # Master: create the queue, then kick off the shuffle driver.
            self._batch_queue = BatchQueue(
                num_epochs,
                num_trainers,
                max_concurrent_epochs,
                name=queue_name,
                connect=False,
            )
            self._consumer = BatchConsumerQueue(self._batch_queue)
            self._batch_queue.ready()
            self._shuffle_result = _ShuffleResult()

            def _drive(result=self._shuffle_result):
                try:
                    if service_job is not None:
                        from ray_shuffling_data_loader_tpu.runtime import (
                            service,
                        )

                        service.set_current_job(service_job)
                    result.duration = shuffle(
                        filenames,
                        self._consumer,
                        num_epochs,
                        num_reducers,
                        num_trainers,
                        seed=seed,
                        start_epoch=start_epoch,
                        narrow_to_32=narrow_to_32,
                        cache_decoded=cache_decoded,
                        stats_collector=stats_collector,
                        device_layout=device_layout,
                    )
                except BaseException as exc:  # surfaced at iterator end
                    result.error = exc

            self._shuffle_result.thread = threading.Thread(
                target=_drive, name="shuffle-driver", daemon=True
            )
            self._shuffle_result.thread.start()
        else:
            # Worker: connect to the named queue with retry.
            self._batch_queue = BatchQueue(
                num_epochs,
                num_trainers,
                max_concurrent_epochs,
                name=queue_name,
                connect=True,
            )
            self._shuffle_result = None

        self._num_epochs = num_epochs
        self._num_trainers = num_trainers
        self._rank = rank
        self._epoch: Optional[int] = None
        self._last_epoch: Optional[int] = None
        self._drop_last = drop_last
        self._skip_batches = 0

    @property
    def batch_size(self) -> int:
        return self._batch_size

    def set_epoch(self, epoch: int, skip_batches: int = 0) -> None:
        """Must be called before each epoch's iteration (reference
        ``dataset.py:96-106``).

        ``skip_batches`` resumes mid-epoch after a preemption: the shuffle
        is deterministic per ``(seed, epoch)`` (``shuffle.py:87-95``), so
        regenerating the epoch and suppressing the first ``skip_batches``
        yields exactly the stream an uninterrupted run would have produced
        from that point (the reference has no resume at all, SURVEY §5).
        Skipped batches still flow through the carry-buffer bookkeeping and
        ``task_done`` acks — only the yields are suppressed.
        """
        self._epoch = epoch
        self._skip_batches = skip_batches

    def __iter__(self) -> Iterator[ColumnBatch]:
        if self._epoch is None or self._epoch == self._last_epoch:
            raise ValueError(
                "You must set the epoch on this dataset via set_epoch() at "
                "the beginning of each epoch, before iterating over this "
                "dataset."
            )
        store = runtime.get_context().store
        rebatch = CarryRebatcher(self._batch_size, self._skip_batches)
        # Staging sub-phase attribution (ISSUE 8 satellite): the carry
        # re-cut used to hide inside the monolithic "staging" stall; its
        # host-copy cost is now its own series. The profiler is the
        # shared no-op when telemetry is off.
        prof = _phases.stage_profiler(
            "staging", epoch=self._epoch, rank=self._rank
        )

        def _recut(cb):
            """Drive ``rebatch.feed`` so only the rebatcher's own slicing
            work is timed — the consumer runs between ``next()`` calls,
            outside the phase."""
            feed = rebatch.feed(cb)
            while True:
                with prof.phase("rebatch"):
                    try:
                        out = next(feed)
                    except StopIteration:
                        return
                yield out

        is_done = False
        consumed_rows = 0  # audit: this rank's consumed-stream offset
        while not is_done:
            pending = self._batch_queue.get_batch(self._rank, self._epoch)
            if pending and pending[-1] is None:
                # Trailing producer-done sentinel; drain the rest first.
                is_done = True
                pending.pop()
            num_outstanding = len(pending)
            # Pull every foreign ref's bytes over DCN in parallel while the
            # first is being consumed (the ``ray.wait(fetch_local=True)``
            # analog, reference ``dataset.py:132-137``); local refs no-op.
            store.prefetch(pending)

            for ref in pending:
                cb = store.get_columns(ref)
                # Segment pages outlive the unlink until views drop.
                store.free(ref)
                if _audit.enabled():
                    # Consumed-side digest BEFORE rebatching: what this
                    # rank actually read back through queue + store. A
                    # row lost (or duplicated) anywhere between the
                    # delivery thread and here breaks delivered==consumed
                    # at reconcile.
                    _audit.record_consume(
                        self._epoch, self._rank, logical_columns(cb),
                        consumed_rows,
                    )
                    consumed_rows += (
                        device_batch_rows(cb)
                        if is_device_batch(cb)
                        else cb.num_rows
                    )
                if (
                    is_device_batch(cb)
                    and cb.layout.get("batch") == self._batch_size
                    and rebatch.buf is None
                ):
                    # Device-direct body: batches already cut at this
                    # rank stream's grid (the producer proved alignment
                    # by construction — the carry is empty exactly when
                    # a body arrives). Yield zero-copy per-batch views;
                    # the carry rebatcher never touches these bytes.
                    for pb in iter_packed_batches(cb):
                        if rebatch.to_skip > 0:
                            rebatch.to_skip -= 1
                            continue
                        yield pb
                elif is_device_batch(cb):
                    # Alignment broken (e.g. an injected delivery fault
                    # upstream shifted the stream): correctness first —
                    # re-cut the logical batches through the carry
                    # buffer like any columnar output.
                    for pb in iter_packed_batches(cb):
                        yield from _recut(pb)
                else:
                    yield from _recut(cb)
                del cb

            if num_outstanding > 0:
                self._batch_queue.task_done(
                    self._rank, self._epoch, num_outstanding
                )

        final = rebatch.finish(self._drop_last)
        if final is not None:
            yield final
        # Ack the producer-done sentinel itself (reference dataset.py:184).
        self._batch_queue.task_done(self._rank, self._epoch, 1)
        self._last_epoch = self._epoch
        if (
            self._epoch == self._num_epochs - 1
            and self._shuffle_result is not None
        ):
            self._shuffle_result.join()


class BatchConsumerQueue(BatchConsumer):
    """Adapts the shuffle engine's consumer interface onto a BatchQueue
    (reference ``dataset.py:191-205``)."""

    def __init__(self, batch_queue: BatchQueue):
        self._batch_queue = batch_queue

    def consume(
        self,
        rank: int,
        epoch: int,
        batches: List[ObjectRef],
        seq: Optional[int] = None,
    ):
        accepted = self._batch_queue.put_batch(
            rank, epoch, batches, seq=seq
        )
        if accepted is False:
            # Idempotency drop (a resumed driver re-published a reducer
            # the surviving queue actor already delivered): nothing will
            # ever consume these refs, so free them here — or the
            # re-executed reducer's segments pin shm for the whole run.
            store = runtime.get_context().store
            for ref in batches:
                try:
                    store.free(ref)
                except Exception:
                    pass

    def producer_done(self, rank: int, epoch: int):
        self._batch_queue.producer_done(rank, epoch)

    def restore_delivery_cursors(self, cursors) -> None:
        # Journal resume (runtime/journal.py): seed the queue actor's
        # idempotency cursors from the journaled delivery state.
        self._batch_queue.restore_delivery_cursors(cursors)

    def wait_until_ready(self, epoch: int):
        self._batch_queue.new_epoch(epoch)

    def wait_until_all_epochs_done(self):
        self._batch_queue.wait_until_all_epochs_done()


if __name__ == "__main__":
    # Smoke run (reference dataset.py:208-252 runs the same shape in CI):
    # generate a small dataset, iterate every epoch, assert exactly-once.
    import numpy as np

    from ray_shuffling_data_loader_tpu.data_generation import generate_data

    num_rows, num_files, num_epochs, batch_size = 10**5, 10, 4, 20_000
    runtime.init()
    filenames, _ = generate_data(
        num_rows, num_files, 2, 0.0, "smoke_data"
    )
    ds = ShufflingDataset(
        filenames,
        num_epochs=num_epochs,
        num_trainers=1,
        batch_size=batch_size,
        rank=0,
        num_reducers=8,
    )
    for epoch in range(num_epochs):
        ds.set_epoch(epoch)
        keys = [k for b in ds for k in b["key"].tolist()]
        assert sorted(keys) == list(range(num_rows)), len(keys)
        print(f"epoch {epoch}: {num_rows} rows exactly once")
    runtime.shutdown()
    print("smoke OK")
