"""TPU-native shuffling data loader.

A brand-new framework with the capabilities of
``ray-project/ray_shuffling_data_loader`` (reference exports:
``__init__.py:1-7``): per-epoch distributed map/reduce shuffle over Parquet,
epoch pipelining with consumer-driven backpressure, and delivery of
exact-size training batches to data-parallel trainers — built TPU-first:

* shuffle stages run on TPU-VM host CPUs over a shared-memory columnar
  object store (:mod:`.runtime`);
* batches are staged into HBM through an async double-buffered
  ``jax.device_put`` prefetch ring and yielded as pod-sharded ``jax.Array``
  batches (:class:`JaxShufflingDataset`);
* gradient exchange is ``jax.lax.psum`` over ICI inside ``pjit``/``shard_map``
  (:mod:`.parallel`), not NCCL.

Heavy adapters (jax / torch) are imported lazily so that CPU-side worker
processes never pay for them.
"""

from ray_shuffling_data_loader_tpu.checkpoint import (
    BatchCursor,
    CheckpointManager,
)
from ray_shuffling_data_loader_tpu.dataset import ShufflingDataset
from ray_shuffling_data_loader_tpu.shuffle import shuffle

__version__ = "0.1.0"

__all__ = [
    "ShufflingDataset",
    "shuffle",
    "JaxShufflingDataset",
    "DeviceResidentShufflingDataset",
    "TorchShufflingDataset",
    "BatchCursor",
    "CheckpointManager",
]


def __getattr__(name):
    # Lazy: keep jax/torch imports out of CPU-side worker processes.
    if name == "JaxShufflingDataset":
        from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset

        return JaxShufflingDataset
    if name == "DeviceResidentShufflingDataset":
        from ray_shuffling_data_loader_tpu.resident import (
            DeviceResidentShufflingDataset,
        )

        return DeviceResidentShufflingDataset
    if name == "TorchShufflingDataset":
        from ray_shuffling_data_loader_tpu.torch_dataset import (
            TorchShufflingDataset,
        )

        return TorchShufflingDataset
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
