"""Model zoo. Flagship: DLRM-style tabular recommender matching the
synthetic DATA_SPEC workload the loader feeds (reference trains a mocked
ConvNet instead — ``examples/horovod/ray_torch_shuffle.py:124-140,214``)."""

from ray_shuffling_data_loader_tpu.models.dlrm import (  # noqa: F401
    TabularDLRM,
    dlrm_for_data_spec,
    example_features,
)
from ray_shuffling_data_loader_tpu.models.lm import (  # noqa: F401
    CausalLM,
    next_token_loss,
    synthetic_tokens,
)
from ray_shuffling_data_loader_tpu.models.transformer import (  # noqa: F401
    TabTransformer,
    transformer_for_data_spec,
)
