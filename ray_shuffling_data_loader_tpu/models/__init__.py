"""models subpackage."""
