"""Causal transformer LM: the long-context model family.

Completes the model zoo's coverage of the framework's parallelism
surface: the DLRM exercises dp×tp (vocab-sharded tables), the
TabTransformer exercises attention over column tokens, and this family
exercises **sequence parallelism** — a causal LM whose attention runs
ring- or Ulysses-scheduled over a mesh axis, so the sequence dimension
scales past one chip's memory (the task's long-context requirement; the
reference repo has no model compute at all).

Blocks are shared with the TabTransformer (:class:`~.transformer
.EncoderBlock` with a causal ``attention_fn``); bfloat16 compute /
float32 params as everywhere in the zoo.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn
import numpy as np

from ray_shuffling_data_loader_tpu.models.transformer import EncoderBlock
from ray_shuffling_data_loader_tpu.ops.flash_attention import (
    flash_attention,
)


class CausalLM(nn.Module):
    """Next-token transformer over int32 token ids.

    ``__call__(tokens [batch, seq]) -> logits [batch, seq, vocab]``.

    ``attention_fn`` must apply a CAUSAL mask (default: causal
    ``flash_attention`` — fused Pallas on TPU backends (batch/head-
partitioned on pod meshes), dense XLA
    elsewhere; pass ``make_ring_attention(mesh, axis, causal=True)`` or
    the Ulysses equivalent to shard the sequence axis).
    """

    vocab_size: int
    max_seq_len: int
    embed_dim: int = 64
    num_layers: int = 2
    num_heads: int = 4
    mlp_ratio: int = 4
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        b, t = tokens.shape
        embed = self.param(
            "token_embed",
            nn.initializers.normal(stddev=0.02),
            (self.vocab_size, self.embed_dim),
            jnp.float32,
        )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (self.max_seq_len, self.embed_dim),
            jnp.float32,
        )
        x = jnp.take(embed, tokens % self.vocab_size, axis=0)
        x = (x + pos[None, :t]).astype(self.compute_dtype)
        # Default: the flash lowering with causal masking (Pallas on a
        # TPU backends incl. pod meshes, dense XLA elsewhere — see
        # flash_attention).
        attention = self.attention_fn or functools.partial(
            flash_attention, causal=True
        )
        for i in range(self.num_layers):
            x = EncoderBlock(
                num_heads=self.num_heads,
                mlp_ratio=self.mlp_ratio,
                compute_dtype=self.compute_dtype,
                attention_fn=attention,
                name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.compute_dtype, name="ln_out")(x)
        # Weight-tied readout: logits against the embedding table (keeps
        # the params lean and the matmul on the MXU).
        logits = jnp.einsum(
            "btd,vd->btv", x.astype(jnp.float32), embed
        )
        return logits


def next_token_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean cross-entropy of predicting token ``t+1`` from position ``t``.

    Targets fold into the vocab exactly like the model's input hashing
    (``tokens % vocab`` in ``__call__``) — without it, an out-of-range id
    would be silently CLAMPED by ``take_along_axis`` under jit and train
    toward the wrong class."""
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    targets = tokens[:, 1:] % logits.shape[-1]
    picked = jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return -jnp.mean(picked)


def synthetic_tokens(
    batch: int, seq_len: int, vocab: int, seed: int = 0
) -> np.ndarray:
    """Learnable synthetic stream: a periodic pattern with per-sample
    phase plus light noise — next-token loss genuinely falls."""
    rng = np.random.default_rng(seed)
    period = min(vocab, 17)
    phase = rng.integers(0, period, (batch, 1))
    base = (np.arange(seq_len)[None, :] + phase) % period
    noise = rng.integers(0, vocab, (batch, seq_len))
    use_noise = rng.random((batch, seq_len)) < 0.05
    return np.where(use_noise, noise, base).astype(np.int32)
