"""Flagship model: DLRM-style tabular recommender over the DATA_SPEC schema.

The reference ships only a toy ConvNet whose train step is mocked by
``time.sleep`` (``examples/horovod/ray_torch_shuffle.py:124-140,214``); the
actual workload its loader feeds is a DLRM-like tabular embedding model —
17 categorical embedding columns + 2 one-hot columns + a float label
(``data_generation.py:56-77``). This module implements that model properly,
TPU-first:

* per-column embedding tables, looked up with ``take`` (gather);
* dot-interaction of embedding vectors (batched matmul → MXU) as in the
  DLRM architecture, upper-triangle extracted with a static mask;
* top MLP in **bfloat16 compute / float32 params** so the matmuls hit the
  MXU at full rate; logits return in float32 for a stable loss.

Sharding intent (consumed by :mod:`..parallel`): large embedding tables
shard their vocab dimension across the ``model`` mesh axis; MLP layers and
small tables replicate; activations shard along ``data``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn
import numpy as np


class TabularDLRM(nn.Module):
    """DLRM-style model over named categorical columns.

    Attributes:
        vocab_sizes: column name -> cardinality.
        embed_dim: embedding width (shared across tables, as in DLRM).
        top_mlp: hidden widths of the top MLP.
        compute_dtype: activation/matmul dtype (bfloat16 for MXU).
    """

    vocab_sizes: Dict[str, int]
    embed_dim: int = 32
    top_mlp: Sequence[int] = (256, 128, 64)
    compute_dtype: jnp.dtype = jnp.bfloat16
    # Dot-interaction lowering: None = auto (fused Pallas kernel on TPU,
    # XLA reference elsewhere); True/False forces it (ops/interaction.py).
    use_pallas_interaction: Optional[bool] = None

    @nn.compact
    def __call__(self, features: Dict[str, jax.Array]) -> jax.Array:
        """features: column name -> int32 [batch] index array. Returns
        float32 [batch] logits."""
        embeds: List[jax.Array] = []
        for col in sorted(self.vocab_sizes):
            table = self.param(
                f"embed_{col}",
                nn.initializers.normal(stddev=1.0 / np.sqrt(self.embed_dim)),
                (self.vocab_sizes[col], self.embed_dim),
                jnp.float32,
            )
            # Hashing trick: fold ids into the table (a no-op when ids are
            # in range). Without it, a capped vocab (``vocab_cap`` in
            # tests/smoke runs) feeds out-of-range ids to ``jnp.take``,
            # whose default OOB mode FILLS WITH NaN — poisoning the loss.
            idx = features[col].reshape(-1) % self.vocab_sizes[col]
            embeds.append(
                jnp.take(table, idx, axis=0).astype(self.compute_dtype)
            )

        # [batch, num_cols, dim]
        stacked = jnp.stack(embeds, axis=1)
        # Dot interaction (batched Gram on the MXU + upper-triangle
        # compaction), fused in VMEM by the Pallas kernel on TPU.
        from ray_shuffling_data_loader_tpu.ops import dot_interaction

        inter_flat = dot_interaction(
            stacked, use_pallas=self.use_pallas_interaction
        )  # [batch, n*(n-1)/2]

        x = jnp.concatenate(
            [stacked.reshape(stacked.shape[0], -1), inter_flat], axis=-1
        )
        for width in self.top_mlp:
            x = nn.Dense(
                width,
                dtype=self.compute_dtype,
                param_dtype=jnp.float32,
            )(x)
            x = nn.relu(x)
        logit = nn.Dense(1, dtype=self.compute_dtype, param_dtype=jnp.float32)(x)
        return logit.reshape(-1).astype(jnp.float32)


def dlrm_for_data_spec(
    embed_dim: int = 32,
    top_mlp: Sequence[int] = (256, 128, 64),
    vocab_cap: Optional[int] = None,
    use_pallas_interaction: Optional[bool] = None,
) -> TabularDLRM:
    """Build the flagship model for the synthetic DATA_SPEC schema
    (``data_generation.py:56-77`` cardinalities). ``vocab_cap`` shrinks
    tables for tests/dry-runs."""
    from ray_shuffling_data_loader_tpu.data_generation import (
        DATA_SPEC,
        LABEL_COLUMN,
    )

    vocab_sizes = {
        col: int(min(high, vocab_cap) if vocab_cap else high)
        for col, (low, high, dtype) in DATA_SPEC.items()
        if col != LABEL_COLUMN
    }
    return TabularDLRM(
        vocab_sizes=vocab_sizes,
        embed_dim=embed_dim,
        top_mlp=tuple(top_mlp),
        use_pallas_interaction=use_pallas_interaction,
    )


def example_features(
    model: TabularDLRM, batch_size: int, seed: int = 0
) -> Dict[str, jax.Array]:
    """A host-side example batch matching the model's schema."""
    rng = np.random.default_rng(seed)
    return {
        col: jnp.asarray(
            rng.integers(0, size, batch_size, dtype=np.int32)
        )
        for col, size in model.vocab_sizes.items()
    }
