"""Second model family: transformer encoder over tabular embedding tokens.

A TabTransformer-style classifier for the same DATA_SPEC workload the
loader feeds: each categorical column embeds to one token, a transformer
encoder attends across the column-token sequence, and a pooled head emits
the binary logit. The reference repo ships only a mocked ConvNet
(``examples/horovod/ray_torch_shuffle.py:124-140,214``); this family
exists so the framework exercises an attention-bearing model end to end
— including the sequence-parallel path.

TPU-first choices mirror the flagship DLRM (``models/dlrm.py``):
float32 params with bfloat16 compute (MXU-rate matmuls), embedding
lookups as gathers, and no data-dependent control flow. Attention is
pluggable: the default is :func:`~.ops.flash_attention.flash_attention`
(auto: fused Pallas kernel on TPU backends incl. pod meshes, dense XLA reference
elsewhere); pass ``attention_fn=make_ring_attention(mesh, axis)`` to run
the encoder with sequence-parallel ring attention when the token
sequence is sharded across the mesh (long-context configurations — see
``tests/test_transformer.py`` for the wiring).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn
import numpy as np

from ray_shuffling_data_loader_tpu.ops.flash_attention import (
    flash_attention,
)


class EncoderBlock(nn.Module):
    """Pre-norm transformer block; ``attention_fn(q, k, v) -> out`` over
    ``[batch, seq, heads, head_dim]``."""

    num_heads: int
    mlp_ratio: int = 4
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        head_dim = d // self.num_heads
        assert head_dim * self.num_heads == d, (
            f"embed_dim {d} not divisible by num_heads {self.num_heads}"
        )
        dense = lambda feats, name: nn.Dense(
            feats,
            dtype=self.compute_dtype,
            param_dtype=jnp.float32,
            name=name,
        )

        h = nn.LayerNorm(dtype=self.compute_dtype, name="ln_attn")(x)
        qkv = dense(3 * d, "qkv")(h).reshape(b, t, 3, self.num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        # Default lowering mirrors the DLRM interaction auto-policy: the
        # fused Pallas flash kernel on TPU backends (pods included), the
        # dense XLA reference everywhere else (flash_attention resolves
        # this internally).
        attn = (self.attention_fn or flash_attention)(q, k, v)
        x = x + dense(d, "proj")(attn.reshape(b, t, d))

        h = nn.LayerNorm(dtype=self.compute_dtype, name="ln_mlp")(x)
        h = dense(self.mlp_ratio * d, "mlp_up")(h)
        h = nn.gelu(h)
        x = x + dense(d, "mlp_down")(h)
        return x


class TabTransformer(nn.Module):
    """Transformer encoder over one token per categorical column.

    Same input/output contract as :class:`~.models.dlrm.TabularDLRM`
    (features dict of int32 ``[batch]`` arrays -> float32 ``[batch]``
    logits), so it drops into ``parallel.make_train_step`` and every
    loader unchanged.
    """

    vocab_sizes: Dict[str, int]
    embed_dim: int = 32
    num_layers: int = 2
    num_heads: int = 4
    mlp_ratio: int = 4
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, features: Dict[str, jax.Array]) -> jax.Array:
        cols = sorted(self.vocab_sizes)
        tokens = []
        for col in cols:
            table = self.param(
                f"embed_{col}",
                nn.initializers.normal(stddev=1.0 / np.sqrt(self.embed_dim)),
                (self.vocab_sizes[col], self.embed_dim),
                jnp.float32,
            )
            # Same hashing trick as the DLRM: capped vocabs must not feed
            # out-of-range ids to the gather (OOB fills with NaN).
            idx = features[col].reshape(-1) % self.vocab_sizes[col]
            tokens.append(jnp.take(table, idx, axis=0))
        x = jnp.stack(tokens, axis=1)  # [batch, n_cols, dim]
        col_embed = self.param(
            "col_embed",
            nn.initializers.normal(stddev=0.02),
            (len(cols), self.embed_dim),
            jnp.float32,
        )
        x = (x + col_embed[None]).astype(self.compute_dtype)
        for i in range(self.num_layers):
            x = EncoderBlock(
                num_heads=self.num_heads,
                mlp_ratio=self.mlp_ratio,
                compute_dtype=self.compute_dtype,
                attention_fn=self.attention_fn,
                name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.compute_dtype, name="ln_out")(x)
        pooled = x.mean(axis=1)
        logit = nn.Dense(
            1, dtype=self.compute_dtype, param_dtype=jnp.float32, name="head"
        )(pooled)
        return logit.reshape(-1).astype(jnp.float32)


def transformer_for_data_spec(
    embed_dim: int = 32,
    num_layers: int = 2,
    num_heads: int = 4,
    vocab_cap: Optional[int] = None,
    attention_fn: Optional[Callable] = None,
) -> TabTransformer:
    """Build the tabular transformer for the synthetic DATA_SPEC schema
    (cardinalities from ``data_generation.py:56-77`` parity)."""
    from ray_shuffling_data_loader_tpu.data_generation import (
        DATA_SPEC,
        LABEL_COLUMN,
    )

    vocab_sizes = {
        col: int(min(high, vocab_cap) if vocab_cap else high)
        for col, (low, high, dtype) in DATA_SPEC.items()
        if col != LABEL_COLUMN
    }
    return TabTransformer(
        vocab_sizes=vocab_sizes,
        embed_dim=embed_dim,
        num_layers=num_layers,
        num_heads=num_heads,
        attention_fn=attention_fn,
    )
