"""rsdl-lint: the invariant-enforcing static-analysis plane (ISSUE 14).

Thirteen PRs of cross-cutting invariants — zero-overhead-off lazy-import
gating, flush-before-task-done spool barriers, seeded determinism on
every plan/digest path, a documented knob and metric vocabulary — were
until now re-proven by hand-written tests and re-discovered in review.
This package checks them *structurally*, on every commit, from the AST:

========================  ===================================================
checker                   invariant
========================  ===================================================
``gate-integrity``        env-gated planes (telemetry planes,
                          ``runtime/{journal,faults,elastic}``) are reachable
                          from core data-path modules only through
                          function-level lazy imports / ``sys.modules``
                          lookups, never module-level ones
``knob-registry``         every ``RSDL_*`` env read is declared in the
                          central registry (:mod:`.knob_registry`) and every
                          public knob is documented in ``docs/TUNING.md``
``vocabulary-drift``      metric names, ``rsdl_`` Prometheus aliases, and
                          event kinds emitted by code appear in
                          ``docs/observability.md``
``determinism-hygiene``   no unseeded ``random``/``np.random``/time-derived
                          seeding in plan- or digest-affecting modules
``lock-discipline``       module-level mutable state mutated off-lock in
                          threaded modules; inconsistent lock-acquisition
                          order across ``with`` statements
``barrier-order``         spool flushes precede task-done / quiesce
                          signaling in ``runtime/tasks.py`` and
                          ``runtime/actor.py``
========================  ===================================================

Entry point: ``tools/rsdl_lint.py`` (human + ``--json`` output,
``--explain CHECK``, per-line ``# rsdl-lint: disable=CHECK -- reason``
suppressions). Policy and the how-to for registering a new knob or
metric: ``docs/static-analysis.md``.
"""

from ray_shuffling_data_loader_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintCrash,
)
from ray_shuffling_data_loader_tpu.analysis.project import Project  # noqa: F401
from ray_shuffling_data_loader_tpu.analysis.checkers import (  # noqa: F401
    all_checkers,
    get_checker,
    run_checks,
)
