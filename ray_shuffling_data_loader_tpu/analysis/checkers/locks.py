"""lock-discipline: heuristics over module-level mutable state and lock
acquisition order.

Two sub-checks, both scoped to *threaded package modules* (a module
that imports ``threading``; pure-sequential helpers are exempt):

1. **off-lock mutation** — a module-level mutable container (``X = {}``
   / ``[]`` / ``set()`` / ``deque()``) mutated from inside a function
   (``X[k] = v``, ``X.append(...)``, ``global X`` reassignment) with no
   enclosing ``with <lock>`` and no lock ``.acquire()`` in the same
   function. Registration tables touched only at import time are the
   classic false positive — that is what the suppression-with-reason
   mechanism is for, and the reason documents the threading argument.
2. **inconsistent acquisition order** — nested ``with``-acquisitions of
   two named locks observed in both orders across one module is the
   textbook deadlock precondition; the second order is flagged.

Lock-ish names: any name/attribute whose final component contains
``lock``, ``mutex``, or ``cond`` (case-insensitive).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_shuffling_data_loader_tpu.analysis.core import (
    Finding,
    dotted_name,
)
from ray_shuffling_data_loader_tpu.analysis.project import Project

EXPLAIN = """\
lock-discipline: shared state in threaded modules is lock-protected and
locks are ordered.

(1) module-level mutable containers mutated from functions in modules
that run threads must hold a lock at the mutation site (a `with
<lock>:` ancestor or an `.acquire()` in the same function). If the
mutation is provably single-threaded (import time, process entrypoint
before threads start), suppress with that reason — the reason IS the
documentation.
(2) two locks entered in nested `with` blocks in both orders in one
module can deadlock; pick one order and stick to it.

Conventions the checker honors: a function named `*_locked` is called
with the module lock held (the name is the contract), and a function
containing an explicit `.acquire()` manages its lock by hand.

Heuristic by design: it cannot see cross-module locking protocols.
Keep module-level mutable state behind small accessor functions that
own one lock — the pattern the telemetry registries use."""

MUTABLE_CTORS = {"dict", "list", "set", "deque", "defaultdict", "OrderedDict"}
MUTATING_METHODS = {
    "append",
    "add",
    "update",
    "pop",
    "popitem",
    "clear",
    "remove",
    "discard",
    "extend",
    "insert",
    "setdefault",
    "appendleft",
}
LOCKISH = ("lock", "mutex", "cond")


def _is_lockish(name: Optional[str]) -> bool:
    if not name:
        return False
    leaf = name.rsplit(".", 1)[-1].lower()
    return any(k in leaf for k in LOCKISH)


def _module_mutables(tree: ast.Module) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in tree.body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None:
            continue
        is_mut = isinstance(value, (ast.Dict, ast.List, ast.Set)) or (
            isinstance(value, ast.Call)
            and (dotted_name(value.func) or "").rsplit(".", 1)[-1]
            in MUTABLE_CTORS
        )
        if not is_mut:
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                out[tgt.id] = node.lineno
    return out


def _uses_threads(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == "threading" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module == "threading":
                return True
    return False


class _FuncScanner(ast.NodeVisitor):
    """Per-function: mutations of module globals + lock context depth."""

    def __init__(self, mutables: Set[str]):
        self.mutables = mutables
        self.findings: List[Tuple[str, int]] = []  # (name, line)
        self._lock_depth = 0
        self.saw_acquire = False
        self.declared_global: Set[str] = set()

    def visit_Global(self, node: ast.Global) -> None:
        self.declared_global.update(node.names)

    def visit_With(self, node: ast.With) -> None:
        self._with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._with(node)

    def _with(self, node) -> None:
        lockish = any(
            _is_lockish(dotted_name(item.context_expr))
            or (
                isinstance(item.context_expr, ast.Call)
                and _is_lockish(dotted_name(item.context_expr.func))
            )
            for item in node.items
        )
        if lockish:
            self._lock_depth += 1
        for child in node.body:
            self.visit(child)
        if lockish:
            self._lock_depth -= 1

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        if name and name.endswith(".acquire"):
            self.saw_acquire = True
        if self._lock_depth == 0 and isinstance(node.func, ast.Attribute):
            base = node.func.value
            if (
                isinstance(base, ast.Name)
                and base.id in self.mutables
                and node.func.attr in MUTATING_METHODS
            ):
                self.findings.append((base.id, node.lineno))
        self.generic_visit(node)

    def _record_store(self, target: ast.AST, lineno: int) -> None:
        if isinstance(target, ast.Subscript) and isinstance(
            target.value, ast.Name
        ):
            if target.value.id in self.mutables:
                self.findings.append((target.value.id, lineno))
        elif isinstance(target, ast.Name):
            if (
                target.id in self.mutables
                and target.id in self.declared_global
            ):
                self.findings.append((target.id, lineno))

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._lock_depth == 0:
            for tgt in node.targets:
                self._record_store(tgt, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self._lock_depth == 0:
            self._record_store(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        if self._lock_depth == 0:
            for tgt in node.targets:
                self._record_store(tgt, node.lineno)
        self.generic_visit(node)

    # Do not descend into nested defs: they get their own scan.
    def visit_FunctionDef(self, node):  # noqa: D102
        pass

    def visit_AsyncFunctionDef(self, node):  # noqa: D102
        pass


def _lock_orders(
    tree: ast.Module,
) -> List[Tuple[str, str, int]]:
    """(outer, inner, line-of-inner) pairs from nested with-acquisitions."""
    pairs: List[Tuple[str, str, int]] = []

    def lock_names(node) -> List[str]:
        out = []
        for item in node.items:
            name = dotted_name(item.context_expr)
            if name is None and isinstance(item.context_expr, ast.Call):
                name = dotted_name(item.context_expr.func)
            if _is_lockish(name):
                out.append(name.rsplit(".", 1)[-1])
        return out

    def walk(node, held: List[str]):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            names = lock_names(node)
            for n in names:
                for h in held:
                    if h != n:
                        pairs.append((h, n, node.lineno))
            held = held + names
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    walk(tree, [])
    return pairs


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod, src in sorted(project.by_module().items()):
        tree = src.tree
        if tree is None or not isinstance(tree, ast.Module):
            continue
        if not _uses_threads(tree):
            continue
        mutables = _module_mutables(tree)
        if mutables:
            has_lock = any(
                _is_lockish(n) for n in _module_level_names(tree)
            )
            for node in tree.body:
                for fn in _functions_in(node):
                    # Repo convention: a ``*_locked`` function is called
                    # with the module lock already held (the name IS the
                    # contract) — its mutations are covered.
                    if fn.name.endswith("_locked"):
                        continue
                    scanner = _FuncScanner(set(mutables))
                    scanner.declared_global = set()
                    for child in fn.body:
                        scanner.visit(child)
                    if scanner.saw_acquire:
                        continue
                    for name, line in scanner.findings:
                        qualifier = (
                            "no module lock exists"
                            if not has_lock
                            else "not under any lock"
                        )
                        findings.append(
                            Finding(
                                check="lock-discipline",
                                path=src.path,
                                line=line,
                                message=(
                                    f"module-level mutable '{name}' "
                                    f"mutated in {fn.name}() "
                                    f"({qualifier}) in a threaded "
                                    "module; hold a lock or suppress "
                                    "with the single-threaded argument"
                                ),
                            )
                        )
        # acquisition order
        order_seen: Dict[Tuple[str, str], int] = {}
        for outer, inner, line in _lock_orders(tree):
            order_seen.setdefault((outer, inner), line)
        for (a, b), line in sorted(order_seen.items()):
            if (b, a) in order_seen and a < b:
                findings.append(
                    Finding(
                        check="lock-discipline",
                        path=src.path,
                        line=max(line, order_seen[(b, a)]),
                        message=(
                            f"locks '{a}' and '{b}' are acquired in both "
                            "orders in this module (deadlock "
                            "precondition); pick one order"
                        ),
                    )
                )
    return findings


def _module_level_names(tree: ast.Module) -> List[str]:
    out = []
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.append(tgt.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            out.append(node.target.id)
    return out


def _functions_in(node):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        yield node
        for child in node.body:
            yield from _functions_in(child)
    elif isinstance(node, ast.ClassDef):
        for child in node.body:
            yield from _functions_in(child)
