"""determinism-hygiene: plan- and digest-affecting modules stay seeded.

The resume/replay contract (docs/robustness.md) is that a fixed seed
reproduces the delivered stream bit-identically — which dies the moment
a shuffle-plan, journal, audit, or checkpoint code path draws from an
unseeded RNG or derives a seed from the clock. Inside
``DETERMINISM_MODULES`` this checker flags:

* the global stdlib RNG: ``random.random()``, ``random.shuffle()``, ...
  (a seeded ``random.Random(seed)`` instance is fine);
* the legacy global numpy RNG: ``np.random.rand/permutation/...``;
* unseeded generator construction: ``np.random.default_rng()`` /
  ``np.random.Generator(...)`` / ``random.Random()`` with no arguments;
* time/uuid-derived seeding: ``time.time()``/``time.time_ns()``/
  ``datetime.now()``/``uuid.uuid4()`` as an argument to anything
  seed/rng-named, or assigned to a ``*seed*`` variable.

Wall-clock *timestamps* (journal record ts, metrics) are fine — they
are identity/observability, not plan input — so bare ``time.time()``
is not flagged outside seeding positions. Modules outside the scope
(e.g. retry jitter) are intentionally unchecked.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_shuffling_data_loader_tpu.analysis.core import (
    Finding,
    dotted_name,
)
from ray_shuffling_data_loader_tpu.analysis.project import (
    DETERMINISM_MODULES,
    Project,
)

EXPLAIN = """\
determinism-hygiene: seeded-or-nothing in plan/digest code.

Shuffle plans, the journal, audit digests, and checkpoint cursors must
be pure functions of (seed, inputs): resume/replay proves equivalence
by comparing order-sensitive digests across runs. This checker flags
unseeded RNG use (global random/np.random, argless default_rng/Random)
and time-derived seeding inside those modules. Fix by threading the
plan seed (derive per-use streams with splitmix64/fold_in, the repo
idiom); if a use is genuinely non-plan (e.g. jitter on a retry that
never touches data order), move it out of scope or suppress with a
reason."""

GLOBAL_RANDOM_FNS = {
    "random",
    "randint",
    "randrange",
    "shuffle",
    "sample",
    "choice",
    "choices",
    "uniform",
    "getrandbits",
    "gauss",
    "normalvariate",
    "seed",
}
NP_GLOBAL_FNS = {
    "rand",
    "randn",
    "randint",
    "random",
    "random_sample",
    "shuffle",
    "permutation",
    "choice",
    "seed",
    "standard_normal",
    "uniform",
}
TIME_SOURCES = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.perf_counter",
    "datetime.now",
    "datetime.utcnow",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "uuid.uuid4",
    "uuid.uuid1",
}
SEEDY = ("seed", "rng", "random")


def _is_time_call(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name in TIME_SOURCES:
            return name
    return None


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod, src in sorted(project.by_module().items()):
        if mod not in DETERMINISM_MODULES:
            continue
        tree = src.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                parts = name.split(".")
                leaf = parts[-1]
                # global stdlib RNG: random.shuffle(...), etc.
                if (
                    len(parts) == 2
                    and parts[0] == "random"
                    and leaf in GLOBAL_RANDOM_FNS
                ):
                    findings.append(
                        Finding(
                            check="determinism-hygiene",
                            path=src.path,
                            line=node.lineno,
                            message=(
                                f"unseeded global RNG call {name}() in a "
                                "plan/digest-affecting module; use a "
                                "seeded random.Random / splitmix64 stream"
                            ),
                        )
                    )
                # legacy global numpy RNG: np.random.permutation(...)
                elif (
                    len(parts) >= 2
                    and parts[-2] == "random"
                    and parts[0] in ("np", "numpy")
                    and leaf in NP_GLOBAL_FNS
                ):
                    findings.append(
                        Finding(
                            check="determinism-hygiene",
                            path=src.path,
                            line=node.lineno,
                            message=(
                                f"global numpy RNG call {name}() in a "
                                "plan/digest-affecting module; use "
                                "np.random.Generator(np.random.PCG64("
                                "seed)) / default_rng(seed)"
                            ),
                        )
                    )
                # unseeded generator construction
                elif leaf in ("default_rng", "Random", "Generator") and (
                    not node.args and not node.keywords
                ):
                    findings.append(
                        Finding(
                            check="determinism-hygiene",
                            path=src.path,
                            line=node.lineno,
                            message=(
                                f"{name}() constructed without a seed in "
                                "a plan/digest-affecting module"
                            ),
                        )
                    )
                # time-derived seeding: seed-ish callee with a clock arg
                elif any(s in name.lower() for s in SEEDY):
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        t = _is_time_call(arg)
                        if t is not None:
                            findings.append(
                                Finding(
                                    check="determinism-hygiene",
                                    path=src.path,
                                    line=node.lineno,
                                    message=(
                                        f"time-derived seed: {t}() passed "
                                        f"to {name}() in a plan/digest-"
                                        "affecting module"
                                    ),
                                )
                            )
            elif isinstance(node, ast.Assign):
                t = _is_time_call(node.value)
                if t is None:
                    continue
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Name)
                        and "seed" in tgt.id.lower()
                    ):
                        findings.append(
                            Finding(
                                check="determinism-hygiene",
                                path=src.path,
                                line=node.lineno,
                                message=(
                                    f"time-derived seed: {tgt.id} = {t}() "
                                    "in a plan/digest-affecting module"
                                ),
                            )
                        )
    return findings
