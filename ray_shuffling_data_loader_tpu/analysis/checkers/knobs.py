"""knob-registry: every ``RSDL_*`` env read is declared; every public
knob is documented.

Harvest covers the idioms this codebase actually uses:

* direct reads/writes — ``os.environ.get/pop/setdefault``,
  ``os.environ[...]``, ``os.getenv`` — with a literal name or a
  module-level ``ENV_X = "RSDL_..."`` constant;
* f-string names (``os.environ.get(f"RSDL_T_{rank}")``) harvested as a
  prefix read;
* reader helpers: any package function whose body reads the environment
  through one of its parameters (``_env.read_flag``, ``retry``'s
  ``_env_int``/``_env_float``, ...) is discovered in a first pass, and
  its call sites with literal knob arguments are harvested in a second.

Checks, both directions ("registry and TUNING.md agree exactly"):
  1. every harvested read matches a registry entry (exact or declared
     prefix) — else *undeclared read*;
  2. every ``public`` registry knob appears in ``docs/TUNING.md`` —
     else *undocumented public knob* (``internal`` knobs may be
     documented but are not required to be);
  3. every ``RSDL_*`` token in ``docs/TUNING.md`` is a registry entry —
     else *documented but undeclared* (doc drift in the other
     direction);
  4. duplicate registry declarations;
  5. planner/registry agreement (ISSUE 20): every knob the plan
     compiler's ``TERM_KNOBS`` names is a registry entry flagged
     ``planned=True``, and every ``planned=True`` entry appears in
     ``TERM_KNOBS`` — the cost model and the registry cannot drift.
     Skipped when the project has no ``analysis/planner.py`` (fixture
     mini-repos share the global registry).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ray_shuffling_data_loader_tpu.analysis.core import (
    Finding,
    const_str,
    dotted_name,
    module_constants,
)
from ray_shuffling_data_loader_tpu.analysis.project import (
    TUNING_DOC,
    Project,
)
from ray_shuffling_data_loader_tpu.analysis import knob_registry

EXPLAIN = """\
knob-registry: the RSDL_* env surface is a declared, documented API.

Every `os.environ`/`os.getenv` read of an RSDL_* name must appear in the
central registry (analysis/knob_registry.py: name, kind, default,
public|internal) and every PUBLIC knob must appear in docs/TUNING.md;
every RSDL_* token TUNING.md mentions must be a registry entry. So an
undeclared read, an undocumented public knob, and a documented-but-
deleted knob all fail CI instead of drifting.

Registering a new knob: add a Knob(...) entry to knob_registry.py; if
scope="public", add a row to the right docs/TUNING.md table. Families
read with dynamic suffixes (RSDL_T_*, RSDL_MP_*) are prefix entries.
The doc side matches on the token, so `RSDL_T_*` in the doc covers a
prefix entry named RSDL_T_."""

KNOB_RE = re.compile(r"RSDL_[A-Z0-9_]*")
ENV_READ_ATTRS = {"get", "pop", "setdefault"}


def _env_name_node(call: ast.Call) -> Optional[ast.AST]:
    """The name argument if ``call`` is an env access
    (``os.environ.get/pop/setdefault`` or ``os.getenv``)."""
    fn = dotted_name(call.func)
    if fn is None or not call.args:
        return None
    if fn in ("os.getenv", "getenv"):
        return call.args[0]
    if isinstance(call.func, ast.Attribute) and (
        call.func.attr in ENV_READ_ATTRS
    ):
        if _is_environ(call.func.value):
            return call.args[0]
    return None


def _is_environ(node: ast.AST) -> bool:
    name = dotted_name(node)
    return name is not None and (
        name == "environ" or name.endswith(".environ")
    )


def _literal_or_const(
    node: ast.AST, consts: Dict[str, str]
) -> Tuple[Optional[str], bool]:
    """(name, is_prefix): resolve a knob-name expression. f-strings with
    a literal head resolve to (head, True)."""
    s = const_str(node)
    if s is not None:
        return s, False
    if isinstance(node, ast.Name) and node.id in consts:
        return consts[node.id], False
    if isinstance(node, ast.JoinedStr) and node.values:
        head = const_str(node.values[0])
        if head is not None:
            return head, True
    return None, False


def _find_reader_helpers(project: Project) -> Dict[str, int]:
    """{function name: parameter index} for package functions that read
    the environment through a parameter."""
    helpers: Dict[str, int] = {}
    for src in project.package_sources():
        tree = src.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = [a.arg for a in node.args.args]
            for sub in ast.walk(node):
                name_node = None
                if isinstance(sub, ast.Call):
                    name_node = _env_name_node(sub)
                elif isinstance(sub, ast.Subscript) and _is_environ(sub.value):
                    name_node = sub.slice
                if isinstance(name_node, ast.Name) and name_node.id in params:
                    helpers[node.name] = params.index(name_node.id)
                    break
    return helpers


def harvest_reads(project: Project) -> List[Tuple[str, str, int, bool]]:
    """All RSDL_* env accesses: (name, path, line, is_prefix)."""
    helpers = _find_reader_helpers(project)
    out: List[Tuple[str, str, int, bool]] = []

    def record(name_node, consts, path, lineno):
        name, is_prefix = _literal_or_const(name_node, consts)
        if name and name.startswith("RSDL_"):
            out.append((name, path, lineno, is_prefix))

    for src in project.sources.values():
        tree = src.tree
        if tree is None:
            continue
        consts = module_constants(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name_node = _env_name_node(node)
                if name_node is not None:
                    record(name_node, consts, src.path, node.lineno)
                    continue
                fn = dotted_name(node.func)
                if fn is not None:
                    tail = fn.rsplit(".", 1)[-1]
                    idx = helpers.get(tail)
                    if idx is not None and idx < len(node.args):
                        record(node.args[idx], consts, src.path, node.lineno)
            elif isinstance(node, ast.Subscript) and _is_environ(node.value):
                record(node.slice, consts, src.path, node.lineno)
    return out


def _registry_lines(project: Project) -> Dict[str, int]:
    """Declaration line per knob name, for finding locations."""
    import ray_shuffling_data_loader_tpu.analysis.knob_registry as kr

    path = kr.__file__
    lines: Dict[str, int] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                for m in re.finditer(r'"(RSDL_[A-Z0-9_]*)"', line):
                    lines.setdefault(m.group(1), i)
    except OSError:
        pass
    return lines


def _registry_relpath(project: Project) -> str:
    import os

    import ray_shuffling_data_loader_tpu.analysis.knob_registry as kr

    try:
        rel = os.path.relpath(kr.__file__, project.root)
        if not rel.startswith(".."):
            return rel.replace(os.sep, "/")
    except ValueError:
        pass
    return "ray_shuffling_data_loader_tpu/analysis/knob_registry.py"


PLANNER_RELPATH = "ray_shuffling_data_loader_tpu/analysis/planner.py"


def _harvest_term_knobs(project: Project) -> Optional[Dict[str, Tuple[str, int]]]:
    """The planner's module-level ``TERM_KNOBS`` dict literal as
    {term: (knob, lineno)}, or None when the project carries no
    planner source (fixture mini-repos)."""
    src = project.sources.get(PLANNER_RELPATH)
    if src is None or src.tree is None:
        return None
    for node in src.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "TERM_KNOBS"
            for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            return {}
        out: Dict[str, Tuple[str, int]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            term = const_str(k)
            knob = const_str(v)
            if term is not None and knob is not None:
                out[term] = (knob, v.lineno)
        return out
    return None


def check(project: Project) -> List[Finding]:
    registry = knob_registry.registry_for(project)
    findings: List[Finding] = []
    reg_path = _registry_relpath(project)
    reg_lines = _registry_lines(project)

    # 4. duplicate declarations
    seen: Set[str] = set()
    for knob in registry.knobs:
        if knob.name in seen:
            findings.append(
                Finding(
                    check="knob-registry",
                    path=reg_path,
                    line=reg_lines.get(knob.name, 1),
                    message=f"duplicate registry entry {knob.name}",
                )
            )
        seen.add(knob.name)

    # 1. undeclared reads
    for name, path, line, is_prefix in harvest_reads(project):
        if registry.lookup(name, is_prefix=is_prefix) is None:
            how = "prefix read" if is_prefix else "read"
            findings.append(
                Finding(
                    check="knob-registry",
                    path=path,
                    line=line,
                    message=(
                        f"undeclared env {how} {name}"
                        f"{'*' if is_prefix else ''}: add a Knob entry to "
                        "analysis/knob_registry.py (and a docs/TUNING.md "
                        "row if public)"
                    ),
                )
            )

    doc = project.doc_text(TUNING_DOC)
    doc_tokens: Set[str] = set()
    doc_token_lines: Dict[str, int] = {}
    if doc is not None:
        for i, line in enumerate(doc.splitlines(), 1):
            for m in KNOB_RE.finditer(line):
                tok = m.group(0)
                doc_tokens.add(tok)
                doc_token_lines.setdefault(tok, i)

    # 2. undocumented public knobs
    if doc is not None:
        for knob in registry.knobs:
            if knob.scope != "public":
                continue
            token = knob.name
            if token in doc_tokens:
                continue
            if knob.prefix and any(
                t.startswith(knob.name) for t in doc_tokens
            ):
                continue
            findings.append(
                Finding(
                    check="knob-registry",
                    path=reg_path,
                    line=reg_lines.get(knob.name, 1),
                    message=(
                        f"public knob {knob.name}"
                        f"{'*' if knob.prefix else ''} is not documented "
                        f"in {TUNING_DOC}"
                    ),
                )
            )

    # 5. planner <-> registry agreement (ISSUE 20)
    term_knobs = _harvest_term_knobs(project)
    if term_knobs is not None:
        emitted = {knob for knob, _ in term_knobs.values()}
        for term, (knob, line) in sorted(term_knobs.items()):
            entry = registry.lookup(knob)
            if entry is None:
                findings.append(
                    Finding(
                        check="knob-registry",
                        path=PLANNER_RELPATH,
                        line=line,
                        message=(
                            f"planner term {term!r} names {knob}, which "
                            "is not a registry entry"
                        ),
                    )
                )
            elif not entry.planned:
                findings.append(
                    Finding(
                        check="knob-registry",
                        path=PLANNER_RELPATH,
                        line=line,
                        message=(
                            f"planner term {term!r} names {knob}, which "
                            "is not flagged planned=True in the registry"
                        ),
                    )
                )
        for knob in registry.knobs:
            if knob.planned and knob.name not in emitted:
                findings.append(
                    Finding(
                        check="knob-registry",
                        path=reg_path,
                        line=reg_lines.get(knob.name, 1),
                        message=(
                            f"registry flags {knob.name} planned=True but "
                            "the planner's TERM_KNOBS emits no such term"
                        ),
                    )
                )

    # 3. documented-but-undeclared tokens
    if doc is not None:
        for tok in sorted(doc_tokens):
            # `RSDL_T_*` in the doc renders as token RSDL_T_ (the *
            # falls outside the match) -> prefix lookup.
            if registry.lookup(tok, is_prefix=tok.endswith("_")) is not None:
                continue
            findings.append(
                Finding(
                    check="knob-registry",
                    path=TUNING_DOC,
                    line=doc_token_lines.get(tok, 1),
                    message=(
                        f"{TUNING_DOC} documents {tok} but the registry "
                        "has no such knob (stale doc, or add the entry)"
                    ),
                )
            )
    return findings
