"""gate-integrity: env-gated planes stay off the module-level import
graph of the core data-path modules.

Builds the package's import graph with each edge classified as
*module-level* (executes when the importer is imported: top-level
``import``/``from`` statements, including ones inside module-level
``if``/``try`` blocks, plus the implicit parent-package edge Python adds
for every submodule import) or *lazy* (inside a function body,
``if TYPE_CHECKING:``, ``importlib.import_module``/``sys.modules`` in a
function — all fine). It then walks module-level edges from every core
module; any gated plane reached that way is a violation, reported at the
import statement that crosses into the plane.

The walk does not continue *through* a gated plane: planes may import
each other freely (e.g. ``phases`` -> ``trace``) because reaching the
first plane already requires passing a gate.
"""

from __future__ import annotations

import ast
from collections import deque
from typing import Dict, List, Set, Tuple

from ray_shuffling_data_loader_tpu.analysis.core import (
    Finding,
    SourceFile,
    is_type_checking_if,
)
from ray_shuffling_data_loader_tpu.analysis.project import (
    CORE_MODULES,
    GATED_PLANES,
    PACKAGE,
    Project,
)

EXPLAIN = """\
gate-integrity: the zero-overhead-off contract, structurally.

Env-gated planes (telemetry/{timeseries,events,stragglers,capacity,
critical,slo,export,audit,trace,phases,obs_server},
runtime/{journal,faults,elastic}) cost nothing when their gates are
unset — which is only true if importing a core data-path module
(shuffle, dataset, batch_queue, checkpoint, runtime/{tasks,actor,store,
transport,cluster}) never executes a plane's module body. This checker
builds the import graph and flags any module-level import path from a
core module into a gated plane.

Fix patterns (in preference order):
  * gate-then-import at the call site:
        if metrics.enabled():
            from ray_shuffling_data_loader_tpu.telemetry import events
  * a lazy proxy for hot attribute-style sites:
        from ray_shuffling_data_loader_tpu._lazy import lazy_module
        _audit = lazy_module("ray_shuffling_data_loader_tpu.telemetry.audit")
  * PEP 562 module __getattr__ for facade re-exports (see
    telemetry/__init__.py)
  * sys.modules.get(...) when the module must only be touched if some
    other path already loaded it (shutdown hooks).
The runtime twins of this structural check are the fresh-interpreter
zero-overhead tests (test_timeseries/test_capacity/test_elastic/
test_resume)."""


def _resolve_relative(module: str, is_pkg_init: bool, node: ast.ImportFrom):
    """Absolute module named by a ``from ... import`` statement."""
    if node.level == 0:
        return node.module
    # Package of the importing module.
    parts = module.split(".")
    if not is_pkg_init:
        parts = parts[:-1]
    up = node.level - 1
    if up:
        parts = parts[:-up] if up < len(parts) else []
    base = ".".join(parts)
    if node.module:
        return f"{base}.{node.module}" if base else node.module
    return base or None


def _collect_module_edges(
    src: SourceFile, known_modules: Set[str]
) -> List[Tuple[str, int]]:
    """(target_module, lineno) for every import that executes at module
    import time. Imports inside function bodies are lazy by definition;
    module-level ``if``/``try``/``with`` bodies still execute eagerly —
    except ``if TYPE_CHECKING:``."""
    tree = src.tree
    if tree is None:
        return []
    is_pkg_init = src.path.endswith("__init__.py")
    edges: List[Tuple[str, int]] = []

    def visit_block(body):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                # Class bodies DO execute at import time.
                if isinstance(node, ast.ClassDef):
                    visit_block(node.body)
                continue
            if isinstance(node, ast.Import):
                for alias in node.names:
                    edges.append((alias.name, node.lineno))
            elif isinstance(node, ast.ImportFrom):
                base = _resolve_relative(src.module, is_pkg_init, node)
                if base is None:
                    continue
                edges.append((base, node.lineno))
                for alias in node.names:
                    cand = f"{base}.{alias.name}"
                    if cand in known_modules:
                        edges.append((cand, node.lineno))
            elif isinstance(node, ast.If):
                if is_type_checking_if(node):
                    continue
                visit_block(node.body)
                visit_block(node.orelse)
            elif isinstance(node, ast.Try):
                visit_block(node.body)
                for h in node.handlers:
                    visit_block(h.body)
                visit_block(node.orelse)
                visit_block(node.finalbody)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                visit_block(node.body)
            elif isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                visit_block(node.body)
                visit_block(node.orelse)
    visit_block(tree.body)

    # Implicit parent-package edges: importing a.b.c executes a and a.b.
    mod = src.module
    parts = mod.split(".")
    for i in range(1, len(parts)):
        parent = ".".join(parts[:i])
        if parent in known_modules and parent != mod:
            edges.append((parent, 1))
    return edges


def check(project: Project) -> List[Finding]:
    by_module = project.by_module()
    known = set(by_module)
    core = {m for m in CORE_MODULES if m in known}
    planes = {p for p in GATED_PLANES if p in known}

    # module -> [(target, lineno)] restricted to in-package targets
    graph: Dict[str, List[Tuple[str, int]]] = {}
    for mod, src in by_module.items():
        tgts = []
        for name, lineno in _collect_module_edges(src, known):
            if name is None or not name.startswith(PACKAGE):
                continue
            # Normalize "from pkg.sub import x" where x is not a module:
            # the executed module is pkg.sub itself.
            while name not in known and "." in name:
                name = name.rsplit(".", 1)[0]
            if name in known and name != mod:
                tgts.append((name, lineno))
        graph[mod] = tgts

    # BFS along module-level edges from the cores; do not expand planes.
    reachable: Set[str] = set()
    origin: Dict[str, str] = {}  # module -> a core module that reaches it
    queue = deque()
    for c in core:
        reachable.add(c)
        origin[c] = c
        queue.append(c)
    while queue:
        mod = queue.popleft()
        if mod in planes:
            continue
        for tgt, _ in graph.get(mod, ()):
            if tgt not in reachable:
                reachable.add(tgt)
                origin[tgt] = origin[mod]
                queue.append(tgt)

    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for mod in sorted(reachable):
        if mod in planes:
            continue  # flagged at the edge below
        for tgt, lineno in graph.get(mod, ()):
            if tgt not in planes:
                continue
            src = by_module[mod]
            key = (src.path, lineno, tgt)
            if key in seen:
                continue
            seen.add(key)
            via = (
                ""
                if mod == origin[mod]
                else f" (reached from core module {origin[mod]})"
            )
            findings.append(
                Finding(
                    check="gate-integrity",
                    path=src.path,
                    line=lineno,
                    message=(
                        f"module-level import of env-gated plane '{tgt}' "
                        f"from '{mod}'{via}; gate it behind a "
                        "function-level lazy import (see --explain "
                        "gate-integrity)"
                    ),
                )
            )
    return findings
