"""Checker registry: name -> (runner, explain text).

Each checker is a function ``(project: Project) -> List[Finding]``. The
CLI composes them, applies per-line suppressions, and exit-codes on what
survives. Adding a checker: implement the module, register it here, add
a fixture test in ``tests/test_rsdl_lint.py`` and a catalog entry in
``docs/static-analysis.md``.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ray_shuffling_data_loader_tpu.analysis.core import (
    Finding,
    LintCrash,
    apply_suppressions,
    suppression_findings,
)
from ray_shuffling_data_loader_tpu.analysis.project import Project

from ray_shuffling_data_loader_tpu.analysis.checkers import (  # noqa: E402
    barriers,
    determinism,
    gates,
    knobs,
    locks,
    vocab,
)

Checker = Callable[[Project], List[Finding]]

_REGISTRY: Dict[str, Tuple[Checker, str]] = {
    "gate-integrity": (gates.check, gates.EXPLAIN),
    "knob-registry": (knobs.check, knobs.EXPLAIN),
    "vocabulary-drift": (vocab.check, vocab.EXPLAIN),
    "determinism-hygiene": (determinism.check, determinism.EXPLAIN),
    "lock-discipline": (locks.check, locks.EXPLAIN),
    "barrier-order": (barriers.check, barriers.EXPLAIN),
}

BAD_SUPPRESSION_EXPLAIN = """\
bad-suppression: a `# rsdl-lint: disable=CHECK` comment with no reason.
Suppressions are part of the audit trail: every one must say WHY the
finding is safe to ignore at that line —
    # rsdl-lint: disable=lock-discipline -- registered before any
    # worker thread starts
A reasonless disable is reported instead of honored."""


def all_checkers() -> List[str]:
    return list(_REGISTRY)


def get_checker(name: str) -> Optional[Tuple[Checker, str]]:
    if name == "bad-suppression":
        return (lambda project: [], BAD_SUPPRESSION_EXPLAIN)
    return _REGISTRY.get(name)


def run_checks(
    project: Project, select: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Run the selected (default: all) checkers plus suppression-syntax
    validation; return findings with suppressions applied, sorted by
    location. Checker crashes surface as :class:`LintCrash`."""
    names = list(select) if select else all_checkers()
    # bad-suppression is selectable but has no runner: the suppression
    # validation below always runs, so selecting it alone just scopes
    # the output to those findings.
    names = [n for n in names if n != "bad-suppression"]
    unknown = [n for n in names if n not in _REGISTRY]
    if unknown:
        raise LintCrash(f"unknown checker(s): {', '.join(unknown)}")
    findings: List[Finding] = []
    for name in names:
        runner, _ = _REGISTRY[name]
        try:
            found = runner(project)
        except LintCrash:
            raise
        except Exception as exc:  # checker bug -> crash, not "clean"
            raise LintCrash(f"checker {name} crashed: {exc!r}") from exc
        for f in found:
            if f.check != name:
                f.check = name
        findings.extend(found)
    for src in project.sources.values():
        findings.extend(suppression_findings(src))
        if src.tree is None:  # forces the parse; None == syntax error
            raise LintCrash(f"{src.path}: unparseable: {src.parse_error}")
    findings = apply_suppressions(findings, project.sources)
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.message))
    return findings
