"""barrier-order: spool flushes precede task-done / quiesce signaling.

The repo's observability contract is *"a resolved future implies its
worker-side records are on disk"*: the task worker flushes every spool
(trace, audit, metrics registry, events, stragglers, capacity) strictly
before putting the ``("done", ...)`` record on the result queue, and an
actor host flushes before deregistering itself. The driver-side
reconciler, the cluster metrics aggregator, and the straggler detector
all assume that ordering. This checker enforces it intra-function in
``runtime/tasks.py`` and ``runtime/actor.py``:

* any ``<queue>.put(("done", ...))`` call must be preceded, within the
  same enclosing statement block, by a flush call
  (``_flush_telemetry_spools`` / ``safe_flush`` / ``maybe_flush``);
* any ``os.unlink/os.remove`` of a ``*registry*`` path (actor
  deregistration — the moment the world may stop waiting for this
  process) must be preceded in its block by a flush call.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_shuffling_data_loader_tpu.analysis.core import (
    Finding,
    const_str,
    dotted_name,
)
from ray_shuffling_data_loader_tpu.analysis.project import (
    BARRIER_MODULES,
    FLUSH_CALL_NAMES,
    Project,
)

EXPLAIN = """\
barrier-order: flush-before-done, structurally.

Task workers must drain their telemetry spools BEFORE reporting a task
done, and actor hosts before deregistering: every consumer of the
spools (audit reconciler, metrics aggregation, straggler records)
relies on "future resolved => records visible". The checker walks
runtime/tasks.py and runtime/actor.py and requires a flush call
(_flush_telemetry_spools / safe_flush / maybe_flush) earlier in the
same statement block as each done-put / registry-unlink.

If you add a new completion signal (a new queue message, a new
deregistration path), flush first — or extend FLUSH_CALL_NAMES /
this checker if the flush moved behind a helper."""


def _is_flush_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Expr) or not isinstance(
        node.value, ast.Call
    ):
        return False
    name = dotted_name(node.value.func) or ""
    return name.rsplit(".", 1)[-1] in FLUSH_CALL_NAMES


def _done_put(node: ast.AST) -> Optional[int]:
    """lineno if the statement is ``something.put(("done", ...))``."""
    if not isinstance(node, ast.Expr) or not isinstance(
        node.value, ast.Call
    ):
        return None
    call = node.value
    if not isinstance(call.func, ast.Attribute) or call.func.attr != "put":
        return None
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Tuple) and arg.elts:
        if const_str(arg.elts[0]) == "done":
            return node.lineno
    return None


def _registry_unlink(node: ast.AST) -> Optional[int]:
    if not isinstance(node, ast.Expr) or not isinstance(
        node.value, ast.Call
    ):
        return None
    call = node.value
    name = dotted_name(call.func) or ""
    if name not in ("os.unlink", "os.remove"):
        return None
    if call.args:
        arg = call.args[0]
        text = dotted_name(arg) or (
            arg.id if isinstance(arg, ast.Name) else ""
        ) or ""
        if isinstance(arg, ast.Name):
            text = arg.id
        if "registry" in text.lower():
            return node.lineno
    return None


def _scan_block(body: List[ast.stmt], path: str, findings: List[Finding],
                flush_seen_above: bool) -> None:
    """Walk one statement list in order, recursing into compound
    statements; a flush earlier in THIS block (or an enclosing one)
    satisfies signals later in the block."""
    flushed = flush_seen_above
    for stmt in body:
        signal_line = _done_put(stmt)
        kind = "task-done put"
        if signal_line is None:
            signal_line = _registry_unlink(stmt)
            kind = "actor deregistration (registry unlink)"
        if signal_line is not None and not flushed:
            findings.append(
                Finding(
                    check="barrier-order",
                    path=path,
                    line=signal_line,
                    message=(
                        f"{kind} with no preceding telemetry spool flush "
                        "in this block; call _flush_telemetry_spools()/"
                        "safe_flush() first (resolved future => records "
                        "on disk)"
                    ),
                )
            )
        if _is_flush_call(stmt):
            flushed = True
        # Recurse into nested blocks with the current flush state —
        # but NOT into nested defs (ast.walk hands those to their own
        # scan with a fresh state).
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if sub:
                _scan_block(sub, path, findings, flushed)
        for handler in getattr(stmt, "handlers", []) or []:
            _scan_block(handler.body, path, findings, flushed)


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    by_module = project.by_module()
    for mod in sorted(BARRIER_MODULES):
        src = by_module.get(mod)
        if src is None:
            continue
        tree = src.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_block(node.body, src.path, findings, False)
    return findings
