"""vocabulary-drift: emitted metric names, ``rsdl_`` Prometheus
aliases, and event kinds must appear in ``docs/observability.md``.

Harvest sites:

* metric registrations — first literal argument of
  ``.counter(...)`` / ``.gauge(...)`` / ``.histogram(...)`` calls on a
  metrics-ish receiver (``metrics``/``_metrics``/``registry()``/...)
  and of ``safe_inc(...)`` calls;
* event kinds — first literal argument of ``emit_event(...)`` /
  ``events.emit(...)``;
* Prometheus aliases — string literals matching ``rsdl_[a-z0-9_]+``
  anywhere in package/tools code (the alias mapping is mechanical, so a
  hand-written alias in a tool is a vocabulary commitment too).

f-string names (``f"audit.{field}"``) are dynamic families; their
documented form carries the prose, so they are skipped here.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from ray_shuffling_data_loader_tpu.analysis.core import (
    Finding,
    const_str,
    dotted_name,
)
from ray_shuffling_data_loader_tpu.analysis.project import (
    OBSERVABILITY_DOC,
    PACKAGE,
    Project,
)

EXPLAIN = """\
vocabulary-drift: the observable surface is documented, mechanically.

Operators alert on metric names and event kinds; a renamed counter or a
new undocumented event kind silently breaks dashboards. This checker
harvests every literal metric registration (.counter/.gauge/.histogram/
safe_inc), every emit_event/events.emit kind, and every literal rsdl_*
Prometheus alias from package + tools code, and requires each token to
appear in docs/observability.md.

Registering a new metric or event kind: emit it AND add it to the right
vocabulary table in docs/observability.md in the same change. Dynamic
(f-string) families are exempt here — document the family's base name
where its prose lives."""

METRIC_RECEIVER_HINTS = ("metrics", "registry", "reg")
METRIC_FNS = {"counter", "gauge", "histogram"}
NAME_OK_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
EVENT_OK_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)?$")
ALIAS_RE = re.compile(r"^rsdl_[a-z0-9_]+$")

# Alias-looking literals that are infrastructure, not vocabulary.
ALIAS_IGNORE = {"rsdl_lint", "rsdl_top", "rsdl_profile"}


def _metric_receiver(call: ast.Call) -> bool:
    func = call.func
    if not isinstance(func, ast.Attribute):
        return False
    base = func.value
    text = dotted_name(base)
    if text is None and isinstance(base, ast.Call):
        text = dotted_name(base.func)
    if text is None:
        return False
    leaf = text.rsplit(".", 1)[-1].lstrip("_").lower()
    return any(h in leaf for h in METRIC_RECEIVER_HINTS)


def harvest(
    project: Project,
) -> List[Tuple[str, str, str, int]]:
    """(kind, token, path, line) for every vocabulary commitment.
    kind: 'metric' | 'event' | 'alias'."""
    out: List[Tuple[str, str, str, int]] = []
    for src in project.sources.values():
        top = src.path.split("/", 1)[0]
        if top not in (PACKAGE, "tools") and src.path != "bench.py":
            continue
        tree = src.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                fn = dotted_name(node.func)
                leaf = fn.rsplit(".", 1)[-1] if fn else None
                first = const_str(node.args[0]) if node.args else None
                if first is None:
                    continue
                if leaf in METRIC_FNS and _metric_receiver(node):
                    if NAME_OK_RE.match(first) or "_" in first:
                        out.append(("metric", first, src.path, node.lineno))
                elif leaf == "safe_inc":
                    out.append(("metric", first, src.path, node.lineno))
                elif leaf == "emit_event" or (
                    fn in ("events.emit",)
                    or (fn or "").endswith(".events.emit")
                ):
                    if EVENT_OK_RE.match(first) and "." in first:
                        out.append(("event", first, src.path, node.lineno))
            elif isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                if ALIAS_RE.match(node.value) and (
                    node.value not in ALIAS_IGNORE
                ):
                    out.append(
                        ("alias", node.value, src.path, node.lineno)
                    )
    return out


def check(project: Project) -> List[Finding]:
    doc = project.doc_text(OBSERVABILITY_DOC)
    if doc is None:
        return [
            Finding(
                check="vocabulary-drift",
                path=OBSERVABILITY_DOC,
                line=1,
                message=f"{OBSERVABILITY_DOC} is missing",
            )
        ]
    doc_words: Set[str] = set(re.findall(r"[A-Za-z0-9_.`]+", doc))
    doc_words |= {w.strip("`") for w in doc_words}
    # Expand the doc's alternation shorthand: `trial.start/done/failed`
    # documents trial.start, trial.done, AND trial.failed.
    for m in re.finditer(
        r"\b([a-z0-9_]+)\.([a-z0-9_]+)((?:/[a-z0-9_]+)+)", doc
    ):
        head = m.group(1)
        for tail in [m.group(2)] + m.group(3).lstrip("/").split("/"):
            doc_words.add(f"{head}.{tail}")

    findings: List[Finding] = []
    reported: Dict[Tuple[str, str], bool] = {}
    for kind, token, path, line in harvest(project):
        # Whole-token match ONLY: the tokenizer already splits at `{`
        # (so `queue.depth{epoch=E}` documents queue.depth) and the
        # alternation expansion covers `trial.start/done/failed`. A raw
        # substring fallback would let any prefix of a documented name
        # (e.g. a rename to `queue.dep`) pass silently.
        if token in doc_words:
            continue
        key = (kind, token)
        if key in reported:
            continue
        reported[key] = True
        what = {
            "metric": "metric name",
            "event": "event kind",
            "alias": "Prometheus alias",
        }[kind]
        findings.append(
            Finding(
                check="vocabulary-drift",
                path=path,
                line=line,
                message=(
                    f"emitted {what} '{token}' is not documented in "
                    f"{OBSERVABILITY_DOC}: add it to the vocabulary "
                    "tables (see --explain vocabulary-drift)"
                ),
            )
        )
    return findings
