"""The central ``RSDL_*`` knob registry (ISSUE 14).

Single source of truth for the env-var surface: every ``os.environ`` /
``os.getenv`` read of an ``RSDL_*`` name anywhere in the repo must match
an entry here (exact, or a declared ``prefix`` family), and every
``public`` entry must have a row in ``docs/TUNING.md`` — both enforced
by the ``knob-registry`` checker (``tools/rsdl_lint.py``), so the
registry, the code, and the doc cannot drift apart silently.

Scope semantics:

* ``public`` — a deploy-time tuning surface an operator may set;
  documented in TUNING.md, covered by compatibility expectations.
* ``internal`` — bench/test/harness plumbing (``RSDL_BENCH_*``,
  ``RSDL_T_*``, ...): may appear in docs but carries no compatibility
  promise and no documentation requirement.

``prefix=True`` declares a family: any name starting with ``name``
matches (used for the multiprocess/pod-harness plumbing families whose
suffixes are dynamic).

``planned=True`` marks a knob the plan compiler owns (ISSUE 20): with
``RSDL_PLAN=auto`` and the knob unset, the cost model picks its
effective value; setting the env var pins it. The ``knob-registry``
checker cross-checks this flag against the planner's ``TERM_KNOBS``
mapping in both directions, so cost model and registry cannot drift.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple


@dataclass(frozen=True)
class Knob:
    name: str
    kind: str  # flag | int | float | str | path | enum | spec | prefix
    default: str
    scope: str  # public | internal
    help: str = ""
    prefix: bool = False
    planned: bool = False  # owned by the plan compiler (RSDL_PLAN)


KNOBS: Tuple[Knob, ...] = (
    # -- runtime / store ----------------------------------------------------
    Knob("RSDL_RUNTIME_DIR", "path", "new session", "public",
         "join an existing session's runtime directory"),
    Knob("RSDL_SHM_DIR", "path", "/dev/shm", "public",
         "shared-memory store root"),
    Knob("RSDL_STORE_CAPACITY_BYTES", "int", "unset", "public",
         "absolute store budget before spill"),
    Knob("RSDL_STORE_CAPACITY_FRACTION", "float", "0.8", "public",
         "store budget as a fraction of shm"),
    Knob("RSDL_SPILL_DIR", "path", "disk tmp", "public",
         "where over-budget segments spill"),
    Knob("RSDL_ADVERTISE_HOST", "str", "auto", "public",
         "address other hosts dial for this host"),
    Knob("RSDL_CLUSTER_TOKEN", "str", "auto", "public",
         "cluster bearer token"),
    Knob("RSDL_SPAWN_READY_TIMEOUT_S", "float", "600", "public",
         "actor-spawn readiness deadline"),
    Knob("RSDL_DISABLE_LOCALITY", "flag", "off", "public",
         "turn off locality-aware scheduling"),
    Knob("RSDL_TCP_ZEROCOPY", "flag", "off", "public",
         "zero-copy cross-host fetch plane"),
    Knob("RSDL_TCP_STREAMS", "int", "1", "public",
         "striped connections per peer (zero-copy plane)"),
    Knob("RSDL_FETCH_WINDOW_DEPTH", "int", "4/8", "public",
         "window-pipelining depth", planned=True),
    Knob("RSDL_REDUCE_FETCH_OVERLAP", "enum", "auto", "public",
         "overlap reduce-side fetch with the gather"),
    # -- recovery / retry ---------------------------------------------------
    Knob("RSDL_CALL_RETRIES", "int", "3", "public",
         "actor-call retry budget (pre-send connection failures)"),
    Knob("RSDL_CALL_DEADLINE_S", "float", "60", "public",
         "per-actor-call deadline"),
    Knob("RSDL_CONNECT_MAX_BACKOFF_S", "float", "5", "public",
         "cap on the jittered connect backoff"),
    Knob("RSDL_STAGE_MAX_ATTEMPTS", "int", "3", "public",
         "driver-side bounded stage re-execution budget"),
    Knob("RSDL_PRODUCER_LIVENESS_S", "float", "2.0", "public",
         "producer-liveness poll slice for blocking queue reads"),
    # -- fault injection (chaos) -------------------------------------------
    Knob("RSDL_FAULTS", "spec", "off", "public",
         "fault-injection schedule site[/role]:kind:prob[@epoch][xN],..."),
    Knob("RSDL_FAULTS_SEED", "int", "0", "public",
         "determinism anchor for the fault schedule"),
    Knob("RSDL_FAULTS_DELAY_S", "float", "0.05", "public",
         "sleep for delay/stall fault kinds"),
    Knob("RSDL_FAULTS_WEDGE_S", "float", "30", "public",
         "sleep for the wedge fault kind"),
    # -- shuffle engine -----------------------------------------------------
    Knob("RSDL_INDEX_SHUFFLE", "enum", "auto", "public",
         "index-only steady-state schedule"),
    Knob("RSDL_HOST_PROBE", "enum", "on", "public",
         "once-per-process host bandwidth probe"),
    Knob("RSDL_DECODE_THREADS", "enum", "auto", "public",
         "Arrow per-read threads inside decode tasks"),
    Knob("RSDL_DECODE_ROWGROUPS", "enum", "off", "public",
         "row-group decode execution plan", planned=True),
    Knob("RSDL_DECODE_PUSHDOWN", "enum", "auto", "public",
         "column pushdown for decode", planned=True),
    Knob("RSDL_DECODE_CACHE_SHARED", "flag", "off", "public",
         "cross-epoch shared decode-cache tier"),
    Knob("RSDL_SHUFFLE_PLAN", "enum", "rowwise", "public",
         "seeded plan family (rowwise | block[:G])", planned=True),
    Knob("RSDL_SELECTIVE_READS", "enum", "off", "public",
         "RINAS-style selective schedule", planned=True),
    Knob("RSDL_DISABLE_NATIVE", "flag", "off", "public",
         "skip the C++ kernels"),
    Knob("RSDL_NATIVE_CACHE", "path", "repo dir", "public",
         "compiled kernel .so cache dir"),
    Knob("RSDL_NATIVE_THREADS", "int", "min(8, cores)", "public",
         "kernel thread count", planned=True),
    # -- self-tuning plan compiler (ISSUE 20) -------------------------------
    Knob("RSDL_PLAN", "enum", "off", "public",
         "cost-based plan compiler (auto | off): plans the planned=True "
         "knobs from footer stats; env-set knobs stay pinned"),
    # -- staging / resident -------------------------------------------------
    Knob("RSDL_DEVICE_DIRECT", "enum", "auto", "public",
         "device-direct delivery kill switch"),
    Knob("RSDL_RESIDENT_BUDGET_GB", "float", "measured", "public",
         "HBM budget override for fits_device"),
    Knob("RSDL_TPU_HBM_GB", "float", "16", "public",
         "per-device HBM for plugins without memory_stats"),
    # -- kernels (ops) ------------------------------------------------------
    Knob("RSDL_FLASH_BWD", "enum", "pallas", "public",
         "flash-attention VJP route (pallas | xla)"),
    # -- telemetry: trace / metrics / audit ---------------------------------
    Knob("RSDL_TRACE", "flag", "off", "public",
         "tracing gate"),
    Knob("RSDL_TRACE_DIR", "path", "unset", "public",
         "cross-process trace spool dir"),
    Knob("RSDL_TRACE_BUFFER", "int", "200000", "public",
         "per-process span buffer bound"),
    Knob("RSDL_TRACE_OUT", "path", "unset", "public",
         "default --trace-out for bench.py"),
    Knob("RSDL_METRICS", "flag", "off", "public",
         "master metrics gate (events/stragglers/capacity ride it)"),
    Knob("RSDL_METRICS_DIR", "path", "$RSDL_RUNTIME_DIR/metrics", "public",
         "metrics spool override"),
    Knob("RSDL_METRICS_OUT", "path", "unset", "public",
         "default --metrics-out for bench.py"),
    Knob("RSDL_AUDIT", "flag", "off", "public",
         "exactly-once digest layer gate"),
    Knob("RSDL_AUDIT_DIR", "path", "unset", "public",
         "audit spool dir (shared fs on multi-host)"),
    Knob("RSDL_AUDIT_STRICT", "flag", "off", "public",
         "raise AuditError on digest mismatch"),
    Knob("RSDL_AUDIT_KEY", "str", "key", "public",
         "audit key column"),
    Knob("RSDL_AUDIT_SAMPLE", "int", "4096", "public",
         "sampled keys for shuffle-quality metrics"),
    Knob("RSDL_EVENTS_DIR", "path", "$RSDL_RUNTIME_DIR/events", "public",
         "structured event-log spool override"),
    # -- telemetry: obs endpoint / temporal / decision ----------------------
    Knob("RSDL_OBS_PORT", "int", "off", "public",
         "live observability endpoint port"),
    Knob("RSDL_OBS_HOST", "str", "127.0.0.1", "public",
         "obs endpoint bind host"),
    Knob("RSDL_OBS_STALE_S", "float", "unset", "public",
         "drop spool sources older than this from aggregation"),
    Knob("RSDL_TS", "flag", "off", "public",
         "force the timeseries sampler headless"),
    Knob("RSDL_TS_PERIOD_S", "float", "2", "public",
         "sampler tick period"),
    Knob("RSDL_TS_SAMPLES", "int", "900", "public",
         "timeseries ring capacity"),
    Knob("RSDL_SLO_RULES", "spec", "default pack", "public",
         "alert rules (inline JSON or a file path)"),
    Knob("RSDL_STRAGGLER_K", "float", "unset", "public",
         "straggler budget multiplier over the stage median"),
    Knob("RSDL_STRAGGLER_MIN_S", "float", "unset", "public",
         "straggler budget floor"),
    # -- elasticity ---------------------------------------------------------
    Knob("RSDL_ELASTIC", "enum", "off", "public",
         "elastic control loop gate"),
    Knob("RSDL_ELASTIC_PERIOD_S", "float", "RSDL_TS_PERIOD_S", "public",
         "control-loop tick period"),
    Knob("RSDL_ELASTIC_MIN_WORKERS", "int", "1", "public",
         "autoscaler lower bound"),
    Knob("RSDL_ELASTIC_MAX_WORKERS", "int", "2x cores", "public",
         "autoscaler upper bound"),
    Knob("RSDL_ELASTIC_UP_THRESHOLD", "float", "0.5", "public",
         "scale-up sole-active share threshold"),
    Knob("RSDL_ELASTIC_DOWN_THRESHOLD", "float", "0.1", "public",
         "scale-down sole-active share threshold"),
    Knob("RSDL_ELASTIC_COOLDOWN_S", "float", "30", "public",
         "minimum spacing between scale decisions"),
    Knob("RSDL_DRAIN_DEADLINE_S", "float", "30", "public",
         "bounded wait for a draining agent"),
    Knob("RSDL_EVICT_HIGH_WATERMARK", "float", "0.85", "public",
         "evictor hysteresis: start demoting above"),
    Knob("RSDL_EVICT_LOW_WATERMARK", "float", "0.6", "public",
         "evictor hysteresis: stop below"),
    Knob("RSDL_EVICT_COOLDOWN_S", "float", "5", "public",
         "minimum spacing between eviction passes"),
    Knob("RSDL_EVICT_DROP_AGE_S", "float", "300", "public",
         "spill-tier drop age during a pressure pass"),
    # -- multi-job service (ISSUE 15) ---------------------------------------
    Knob("RSDL_SERVICE", "enum", "off", "public",
         "multi-tenant shuffle-service plane gate (auto | off)"),
    Knob("RSDL_JOB_NAME", "str", "job", "public",
         "stable name for auto-registered service jobs"),
    Knob("RSDL_JOB_ID", "str", "unset", "public",
         "ambient job id for processes joining a job (trainer ranks)"),
    Knob("RSDL_JOB_WEIGHT", "float", "1.0", "public",
         "fair-share scheduling weight for this process's jobs"),
    Knob("RSDL_SERVICE_ADMIT_FRAC", "float", "0.85", "public",
         "shm-used fraction above which new epoch windows wait"),
    Knob("RSDL_SERVICE_ADMIT_TIMEOUT_S", "float", "30", "public",
         "bounded admission wait before a window proceeds anyway"),
    Knob("RSDL_RUN_LEDGER", "path", "off", "public",
         "durable run-ledger NDJSON (1/on/true/auto = "
         "<runtime_dir>/runs/ledger.ndjson, anything else = explicit "
         "path)"),
    # -- suspend / resume ---------------------------------------------------
    Knob("RSDL_JOURNAL", "path", "off", "public",
         "driver write-ahead journal dir"),
    Knob("RSDL_JOURNAL_SYNC", "flag", "on", "public",
         "fsync-per-append toggle"),
    Knob("RSDL_RESUME", "enum", "off", "public",
         "resume mode (auto | redeliver)"),
    # -- tests / tools (documented) -----------------------------------------
    Knob("RSDL_TPU_TESTS", "flag", "off", "public",
         "enable the TPU-gated test files"),
    # -- continuous profiling plane (ISSUE 17) ------------------------------
    Knob("RSDL_PROFILE", "flag", "off", "public",
         "cluster-wide wall-clock sampling profiler (every RSDL "
         "process runs a sampler daemon thread)"),
    Knob("RSDL_PROFILE_HZ", "float", "67", "public",
         "sampling rate, clamped to [1, 500]; the off-round default "
         "avoids phase-locking with 1 s periodic work"),
    Knob("RSDL_PROFILE_DIR", "path", "<runtime_dir>/profiles", "public",
         "profile spool override (per-process profile-*.json "
         "aggregates; was the jax.profiler wrap knob, now "
         "RSDL_BENCH_XPROF_DIR)"),
    Knob("RSDL_PROFILE_TOP_N", "int", "20", "public",
         "default row count for /profile and rsdl_prof top tables"),
    # -- spool-federation plane (ISSUE 19) ----------------------------------
    Knob("RSDL_RELAY", "enum", "off", "public",
         "cross-host telemetry federation (auto | off): non-head hosts "
         "ship spool deltas to a driver-side sink over the authed "
         "transport"),
    Knob("RSDL_RELAY_PERIOD_S", "float", "0.5", "public",
         "shipper period between ships (flush barriers kick it sooner)"),
    Knob("RSDL_RELAY_MAX_BATCH_BYTES", "int", "4194304", "public",
         "per-ship payload cap; the rest goes next cycle"),
    Knob("RSDL_RELAY_MAX_LAG_BYTES", "int", "67108864", "public",
         "per-file backlog bound — past it the shipper drops forward "
         "to a line boundary and counts relay.dropped_bytes_total"),
    Knob("RSDL_STRESS_SEEDS", "int", "3", "internal",
         "seeds per stress-soak scenario"),
    Knob("RSDL_DRYRUN_MP", "enum", "on", "internal",
         "dryrun_multichip 2-process leg toggle"),
    # -- internal families (bench / harness plumbing) -----------------------
    Knob("RSDL_BENCH_", "prefix", "-", "internal",
         "bench.py workload/capture knobs (documented rows in TUNING.md "
         "carry no compatibility promise)", prefix=True),
    Knob("RSDL_SWEEP_", "prefix", "-", "internal",
         "trainer-sweep workload shape (read by tools/*.sh)", prefix=True),
    Knob("RSDL_T_", "prefix", "-", "internal",
         "2-process pod test harness plumbing", prefix=True),
    Knob("RSDL_MP_", "prefix", "-", "internal",
         "dryrun_multichip 2-process leg plumbing", prefix=True),
    Knob("RSDL_TEST_", "prefix", "-", "internal",
         "TPU-gated test harness plumbing (repo/tmp paths)", prefix=True),
    Knob("RSDL_PROBE", "str", "-", "internal",
         "bench backend-probe stdout marker (not an env read)"),
    Knob("RSDL_CI_TIER", "enum", "all", "internal",
         "run_ci_tests.sh tier selection (shell-read)"),
)


class KnobRegistry:
    def __init__(self, knobs: Tuple[Knob, ...]):
        self.knobs: Tuple[Knob, ...] = knobs
        self._exact = {k.name: k for k in knobs if not k.prefix}
        self._prefixes: List[Knob] = [k for k in knobs if k.prefix]

    def lookup(self, name: str, is_prefix: bool = False) -> Optional[Knob]:
        """Resolve a harvested read. ``is_prefix`` marks an f-string
        read whose literal head is ``name`` — it matches a prefix entry
        covering (or covered by) that head."""
        if not is_prefix:
            k = self._exact.get(name)
            if k is not None:
                return k
        for p in self._prefixes:
            if name.startswith(p.name):
                return p
            if is_prefix and p.name.startswith(name):
                return p
        return None


REGISTRY = KnobRegistry(KNOBS)


def registry_for(project) -> KnobRegistry:
    """The registry to lint ``project`` against. One repo, one registry
    today; the indirection keeps fixture tests honest about what they
    exercise."""
    return REGISTRY
