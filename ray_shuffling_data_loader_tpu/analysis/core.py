"""Shared lint machinery: findings, suppressions, parsed sources.

A :class:`Finding` is one violation at one location. Suppressions are
per-line comments with a *required* written reason::

    risky_call()  # rsdl-lint: disable=lock-discipline -- init-time only,
                  # no thread is alive yet

(the reason follows ``--``; a bare ``disable=`` with no reason is itself
a finding, ``bad-suppression`` — the policy is "suppressed WITH a
reason", never silently). A suppression names one or more
comma-separated checks, or ``all``. It applies to findings anchored on
its own line, or — when written as a standalone comment block — to the
first code line directly below it.
"""

from __future__ import annotations

import ast
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Dict, Iterable, List, Optional, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*rsdl-lint:\s*disable=([A-Za-z0-9_,\- ]+?)"
    r"(?:\s*--\s*(?P<reason>.+?))?\s*$"
)


class LintCrash(Exception):
    """Internal lint failure (exit code 3, never 1): a checker bug or an
    unreadable tree must be distinguishable from real findings."""


@dataclass
class Finding:
    check: str
    path: str  # repo-root-relative, '/'-separated
    line: int
    message: str
    col: int = 0
    suppressed: bool = False
    suppress_reason: Optional[str] = None

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_json(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "check": self.check,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }
        if self.suppressed:
            out["suppressed"] = True
            out["suppress_reason"] = self.suppress_reason
        return out

    @classmethod
    def from_json(cls, obj: Dict[str, object]) -> "Finding":
        return cls(
            check=str(obj["check"]),
            path=str(obj["path"]),
            line=int(obj["line"]),  # type: ignore[arg-type]
            col=int(obj.get("col", 0)),  # type: ignore[arg-type]
            message=str(obj["message"]),
            suppressed=bool(obj.get("suppressed", False)),
            suppress_reason=(
                str(obj["suppress_reason"])
                if obj.get("suppress_reason") is not None
                else None
            ),
        )


@dataclass
class Suppression:
    line: int
    checks: Tuple[str, ...]  # lowercase check names, or ("all",)
    reason: Optional[str]

    def covers(self, check: str) -> bool:
        return "all" in self.checks or check in self.checks


@dataclass
class SourceFile:
    """One parsed Python file: text, AST, and its suppression comments."""

    path: str  # repo-root-relative
    abspath: str
    text: str
    module: Optional[str] = None  # dotted module name, None outside pkgs
    _tree: Optional[ast.AST] = field(default=None, repr=False)
    _suppressions: Optional[Dict[int, List[Suppression]]] = field(
        default=None, repr=False
    )
    parse_error: Optional[str] = field(default=None, repr=False)

    @property
    def tree(self) -> Optional[ast.AST]:
        if self._tree is None and self.parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=self.path)
            except SyntaxError as exc:
                self.parse_error = f"{exc.msg} (line {exc.lineno})"
        return self._tree

    @property
    def suppressions(self) -> Dict[int, List[Suppression]]:
        if self._suppressions is None:
            self._suppressions = _parse_suppressions(self.text)
        return self._suppressions

    def lines(self) -> List[str]:
        return self.text.splitlines()


def _parse_suppressions(text: str) -> Dict[int, List[Suppression]]:
    """Tokenize so string literals containing the marker don't count."""
    out: Dict[int, List[Suppression]] = {}
    try:
        tokens = tokenize.generate_tokens(StringIO(text).readline)
        comments = [
            (tok.start[0], tok.string)
            for tok in tokens
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # Fall back to a line scan; a malformed file will surface its
        # own parse error elsewhere.
        comments = [
            (i + 1, line[line.index("#"):])
            for i, line in enumerate(text.splitlines())
            if "#" in line
        ]
    for lineno, comment in comments:
        m = SUPPRESS_RE.search(comment)
        if not m:
            continue
        checks = tuple(
            c.strip().lower() for c in m.group(1).split(",") if c.strip()
        )
        reason = m.group("reason")
        out.setdefault(lineno, []).append(
            Suppression(line=lineno, checks=checks, reason=reason)
        )
    return out


def suppression_findings(src: SourceFile) -> List[Finding]:
    """Reason-less suppressions are violations in their own right."""
    findings = []
    for lineno, sups in sorted(src.suppressions.items()):
        for sup in sups:
            if not sup.reason:
                findings.append(
                    Finding(
                        check="bad-suppression",
                        path=src.path,
                        line=lineno,
                        message=(
                            "suppression without a reason: write "
                            "'# rsdl-lint: disable=CHECK -- <why this is "
                            "safe here>'"
                        ),
                    )
                )
    return findings


def _candidate_lines(src: SourceFile, line: int) -> Iterable[int]:
    """The finding's own line, plus any immediately-preceding run of
    pure comment lines (the standalone-comment suppression form)."""
    yield line
    lines = src.lines()
    i = line - 1  # 1-based -> the line above, 0-indexed: lines[i - 1]
    while i >= 1:
        stripped = lines[i - 1].strip()
        if stripped.startswith("#"):
            yield i
            i -= 1
        else:
            break


def apply_suppressions(
    findings: Iterable[Finding], sources: Dict[str, SourceFile]
) -> List[Finding]:
    """Mark findings covered by a suppression (with a reason) on the
    same line or in the comment block directly above it."""
    out = []
    for f in findings:
        src = sources.get(f.path)
        if src is not None:
            done = False
            for lineno in _candidate_lines(src, f.line):
                for sup in src.suppressions.get(lineno, []):
                    if sup.reason and sup.covers(f.check):
                        f.suppressed = True
                        f.suppress_reason = sup.reason
                        done = True
                        break
                if done:
                    break
        out.append(f)
    return out


# ---------------------------------------------------------------------------
# Small AST helpers shared by checkers
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'os.environ.get' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_constants(tree: ast.AST) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` string constants (the
    ``ENV_FAULTS = "RSDL_FAULTS"`` idiom)."""
    out: Dict[str, str] = {}
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            val = const_str(node.value)
            if isinstance(tgt, ast.Name) and val is not None:
                out[tgt.id] = val
    return out


def iter_function_defs(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def is_type_checking_if(node: ast.AST) -> bool:
    """``if TYPE_CHECKING:`` / ``if typing.TYPE_CHECKING:`` blocks run
    never at runtime — imports inside them are not real edges."""
    if not isinstance(node, ast.If):
        return False
    test = node.test
    return (
        isinstance(test, ast.Name) and test.id == "TYPE_CHECKING"
    ) or (
        isinstance(test, ast.Attribute) and test.attr == "TYPE_CHECKING"
    )
