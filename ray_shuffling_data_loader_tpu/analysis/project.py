"""The repo model the checkers share: file discovery, module naming,
and the invariant configuration (which modules are core, which are
gated planes, where the docs live).

Everything is expressed relative to a *root* directory so the same
checkers run against this repo and against the fixture mini-repos the
test suite builds in a tmp dir.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

from ray_shuffling_data_loader_tpu.analysis.core import SourceFile

PACKAGE = "ray_shuffling_data_loader_tpu"

# Directories (relative to root) whose .py files are scanned. Order is
# presentation order only.
CODE_DIRS = (PACKAGE, "tools", "benchmarks", "examples", "tests")
CODE_FILES = ("bench.py", "__graft_entry__.py")
SKIP_DIR_NAMES = {"__pycache__", ".git", "build", "dist"}
# The analysis package lints itself: its sources are scanned like any
# other (suppression-syntax validation included). Checkers whose scope
# is module-name-keyed (determinism, barriers) never match it; the
# graph/harvest checkers treat it as ordinary non-core code.

# --- gate-integrity configuration ------------------------------------------

# Env-gated planes: importing a core module must not execute these
# module bodies. (metrics/_env are NOT here: they ARE the cached-boolean
# gate every site checks, deliberately cheap and eagerly importable.)
GATED_PLANES = {
    f"{PACKAGE}.telemetry.{m}"
    for m in (
        "timeseries",
        "events",
        "stragglers",
        "capacity",
        "critical",
        "slo",
        "export",
        "audit",
        "trace",
        "phases",
        "obs_server",
        "runledger",
        "profiler",
        "relay",
    )
} | {
    f"{PACKAGE}.runtime.{m}"
    for m in ("journal", "faults", "elastic", "service", "plan")
} | {
    # Self-tuning plan compiler (ISSUE 20): RSDL_PLAN=auto|on.
    f"{PACKAGE}.analysis.planner",
}

# Core data-path modules: the zero-overhead-off contract is theirs.
CORE_MODULES = {
    f"{PACKAGE}.shuffle",
    f"{PACKAGE}.dataset",
    f"{PACKAGE}.batch_queue",
    f"{PACKAGE}.checkpoint",
    f"{PACKAGE}.runtime.tasks",
    f"{PACKAGE}.runtime.actor",
    f"{PACKAGE}.runtime.store",
    f"{PACKAGE}.runtime.transport",
    f"{PACKAGE}.runtime.cluster",
}

# --- determinism-hygiene configuration -------------------------------------

# Plan- or digest-affecting modules: anything nondeterministic here can
# break the bit-identical resume/replay digest contract.
DETERMINISM_MODULES = {
    f"{PACKAGE}.shuffle",
    f"{PACKAGE}.checkpoint",
    f"{PACKAGE}.utils",  # plan-family parsing / decode-plan resolution
    f"{PACKAGE}.runtime.journal",
    f"{PACKAGE}.telemetry.audit",
}

# --- barrier-order configuration -------------------------------------------

# Files whose task-done / quiesce signaling must be preceded by spool
# flushes (module names; the checker matches per enclosing function).
BARRIER_MODULES = {
    f"{PACKAGE}.runtime.tasks",
    f"{PACKAGE}.runtime.actor",
}
FLUSH_CALL_NAMES = {
    "_flush_telemetry_spools",
    "safe_flush",
    "maybe_flush",
}

# --- docs -------------------------------------------------------------------

TUNING_DOC = os.path.join("docs", "TUNING.md")
OBSERVABILITY_DOC = os.path.join("docs", "observability.md")


@dataclass
class Project:
    root: str
    _sources: Optional[Dict[str, SourceFile]] = field(
        default=None, repr=False
    )
    _docs: Dict[str, Optional[str]] = field(default_factory=dict, repr=False)

    # -- discovery -----------------------------------------------------------

    def _iter_paths(self) -> Iterator[str]:
        for name in CODE_FILES:
            p = os.path.join(self.root, name)
            if os.path.isfile(p):
                yield p
        for d in CODE_DIRS:
            top = os.path.join(self.root, d)
            if not os.path.isdir(top):
                continue
            for dirpath, dirnames, filenames in os.walk(top):
                dirnames[:] = sorted(
                    n for n in dirnames if n not in SKIP_DIR_NAMES
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)

    def relpath(self, abspath: str) -> str:
        return os.path.relpath(abspath, self.root).replace(os.sep, "/")

    def module_name(self, relpath: str) -> Optional[str]:
        """Dotted module name for package files, None for scripts."""
        parts = relpath.split("/")
        if parts[0] != PACKAGE:
            return None
        if parts[-1] == "__init__.py":
            parts = parts[:-1]
        else:
            parts[-1] = parts[-1][: -len(".py")]
        return ".".join(parts)

    @property
    def sources(self) -> Dict[str, SourceFile]:
        if self._sources is None:
            out: Dict[str, SourceFile] = {}
            for abspath in self._iter_paths():
                rel = self.relpath(abspath)
                try:
                    with open(abspath, "r", encoding="utf-8") as f:
                        text = f.read()
                except OSError:
                    continue
                out[rel] = SourceFile(
                    path=rel,
                    abspath=abspath,
                    text=text,
                    module=self.module_name(rel),
                )
            self._sources = out
        return self._sources

    def package_sources(self) -> List[SourceFile]:
        return [s for s in self.sources.values() if s.module is not None]

    def by_module(self) -> Dict[str, SourceFile]:
        return {
            s.module: s for s in self.sources.values() if s.module is not None
        }

    def doc_text(self, relpath: str) -> Optional[str]:
        key = relpath.replace(os.sep, "/")
        if key not in self._docs:
            p = os.path.join(self.root, relpath)
            try:
                with open(p, "r", encoding="utf-8") as f:
                    self._docs[key] = f.read()
            except OSError:
                self._docs[key] = None
        return self._docs[key]
