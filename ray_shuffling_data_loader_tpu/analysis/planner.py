"""Cost-based shuffle-plan compiler + between-epoch re-planner (ISSUE 20).

The repo grew ~60 ``RSDL_*`` knobs whose correct settings are
shape-dependent (ROADMAP item 3): blocks/file >= 2R for block-plan
quality, selective-vs-materialized by prunability *and* cache fit,
decode threads vs cores, fetch-window depth vs the store budget.
PRs 16-19 built the telemetry that can choose them; this module closes
the loop. :func:`compile_plan` runs once per ``shuffle()`` on the
driver: a **footer-stats pass** (row-group counts and sizes plus
schema column widths — the same no-data-read inputs ``_group_owners``
already plans from) feeds a small explicit cost model that resolves
every planner-owned knob into a
:class:`~ray_shuffling_data_loader_tpu.runtime.plan.ResolvedPlan`.

Override semantics (the refactor's contract): an env-set knob **pins**
its term — the planner records the env value with ``source="env"`` and
never touches it; an unset knob gets the planned default. The driver
threads effective values through stage-task *arguments* (workers' env
snapshots date from pool spawn — the PR 12 lesson), so planned and
hand-set runs execute identically for identical terms.

:func:`replan` is the second half: at each epoch boundary the driver
feeds it the live ``/critical`` + ``/capacity`` + timeseries signals
(the elastic loop proved signal->actuator at this cadence) and it
adjusts the *mutable-mid-run* subset — fetch-window depth, decode
row-group threads, selective engagement — emitting one
``plan.replanned`` event per adjustment with before/after terms so
``tools/epoch_report.py`` and the run ledger can attribute throughput
deltas to decisions. Env-pinned terms are never re-planned.

Gate: ``RSDL_PLAN=auto|on`` (``shuffle.py`` checks the env *before*
importing this plane; ``GATED_PLANES`` entry, fresh-interpreter
zero-overhead test in ``tests/test_planner.py``).
"""

from __future__ import annotations

import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_shuffling_data_loader_tpu.runtime.plan import (
    MUTABLE_TERMS,
    PlanTerm,
    ResolvedPlan,
    SOURCE_ENV,
    SOURCE_PLANNED,
    SOURCE_REPLANNED,
)

# Term -> knob mapping: every planner-emitted term names the registry
# knob it owns. rsdl_lint's knob-registry checker cross-checks this
# literal against the ``planned=True`` entries in
# analysis/knob_registry.py — drift between cost model and registry is
# a lint failure, in both directions.
TERM_KNOBS = {
    "plan": "RSDL_SHUFFLE_PLAN",
    "selective": "RSDL_SELECTIVE_READS",
    "columns": "RSDL_DECODE_PUSHDOWN",
    "decode_rowgroup_threads": "RSDL_DECODE_ROWGROUPS",
    "fetch_window_depth": "RSDL_FETCH_WINDOW_DEPTH",
    "native_threads": "RSDL_NATIVE_THREADS",
}

# Cost-model constants. Each is a *measured* anchor, not a free
# parameter: the quality bound and window clamps come from BENCHLOG
# r11/r12 and the r7 fetch-depth sweep; the budget fraction mirrors
# the decode-cache auto policy's "fits comfortably" margin.
QUALITY_BLOCKS_PER_FILE = 2  # blocks/file >= 2R (ROADMAP item 3)
WINDOW_BUDGET_FRAC = 0.25  # in-flight windows' share of the store budget
WINDOW_DEPTH_MIN = 1
WINDOW_DEPTH_MAX = 8  # measured flat 2..8 on loopback (BENCHLOG r7)
WINDOW_DEPTH_DEFAULT = 4
FOOTER_SAMPLE_CAP = 64  # strided footer sample for huge file lists
DECODED_HEADROOM = 1.15  # same planning headroom as _est_decoded_bytes
SHM_HIGH_WATER = 0.85  # matches RSDL_EVICT_HIGH_WATERMARK's default
SHM_HEADROOM = 0.5  # below this, deepening windows is safe
NATIVE_THREADS_CAP = 8  # gathers saturate DRAM past this (native/__init__)


def _env_set(name: str) -> bool:
    return bool((os.environ.get(name) or "").strip())


def _cores() -> int:
    return os.cpu_count() or 1


# -- footer-stats pass -------------------------------------------------------


def footer_stats(
    filenames: Sequence[str],
    columns: Optional[Sequence[str]] = None,
    narrow_to_32: bool = False,
) -> Dict[str, Any]:
    """No-data-read dataset shape from Parquet footers: per-file
    row-group counts, total rows, and a decoded-bytes estimate from
    schema column widths (narrowed widths when the run narrows).
    Footers are process-cached (``file_row_group_sizes``); a strided
    sample caps the sweep on huge file lists — group counts and schema
    are uniform across a generated dataset, so the sample generalizes.
    OSError from an unreadable footer degrades to unknown (None
    fields): every downstream term has a safe default."""
    import importlib

    # The package __init__ re-exports the shuffle FUNCTION over the
    # module name, so attribute-style imports resolve to the function.
    _shuffle = importlib.import_module(
        "ray_shuffling_data_loader_tpu.shuffle"
    )

    files = list(filenames)
    stride = max(1, len(files) // FOOTER_SAMPLE_CAP)
    sampled = files[::stride][:FOOTER_SAMPLE_CAP]
    groups: List[int] = []
    rows_sampled = 0
    try:
        for f in sampled:
            sizes = _shuffle.file_row_group_sizes(f)
            groups.append(len(sizes))
            rows_sampled += int(sum(sizes))
    except OSError:
        return {"files": len(files), "groups_min": None, "rows": None,
                "bytes_per_row": None, "est_decoded_bytes": None}
    rows_total = int(rows_sampled * (len(files) / max(1, len(sampled))))
    bytes_per_row: Optional[float] = None
    try:
        pf, _, _ = _shuffle._open_parquet_file(sampled[0])
        schema = pf.schema_arrow
        want = {str(c) for c in columns} if columns else None
        width = 0
        for fld in schema:
            if want is not None and fld.name not in want:
                continue
            dt = _shuffle._np_dtype_of(fld)
            if dt is None:
                continue
            itemsize = dt.itemsize
            if narrow_to_32 and itemsize == 8:
                itemsize = 4
            width += itemsize
        if width:
            bytes_per_row = float(width)
    except Exception:
        bytes_per_row = None
    est = (
        rows_total * bytes_per_row * DECODED_HEADROOM
        if bytes_per_row is not None
        else None
    )
    return {
        "files": len(files),
        "files_sampled": len(sampled),
        "groups_min": min(groups) if groups else None,
        "groups_max": max(groups) if groups else None,
        "rows": rows_total,
        "bytes_per_row": bytes_per_row,
        "est_decoded_bytes": est,
    }


def _store_budget() -> Optional[int]:
    """The store's capacity budget (bytes) — the same number the
    capacity ledger watermarks against. None when budgeting is off."""
    try:
        from ray_shuffling_data_loader_tpu import runtime as _runtime

        return _runtime.get_context().store.capacity_bytes
    except Exception:
        return None


# -- the cost model ----------------------------------------------------------


def compile_plan(
    filenames: Sequence[str],
    *,
    num_reducers: int,
    num_trainers: int = 1,
    num_epochs: int = 1,
    start_epoch: int = 0,
    columns: Optional[Sequence[str]] = None,
    device_layout: Optional[dict] = None,
    narrow_to_32: bool = False,
    cache_decoded: bool = True,
) -> ResolvedPlan:
    """Resolve every planner-owned knob once, driver-side.

    Terms and their models (each lands verbatim in ``plan.chosen``):

    * **plan** — ``block:G`` with ``G = groups_min // (2R)`` whenever
      the quality bound ``blocks/file >= 2R`` is satisfiable
      (``ceil(g/G) >= 2R`` holds for that G by construction); a
      dataset whose files carry fewer than ``2R`` row groups cannot
      meet the bound at any granularity, so it stays ``rowwise``.
    * **selective** — engage only when the plan is prunable (block)
      AND the run will NOT ride the cross-epoch decode cache (the
      run's ``cache_decoded`` argument gating ``_decode_cache_auto``;
      cache off means nothing amortizes): with a hot cache the
      materialized/index path amortizes one decode across epochs,
      which beats re-decoding selections every epoch; without it,
      selective's zero map materialization wins (BENCHLOG r11/r12).
    * **columns** — project to the staging layout's column set when
      the layout proves the touchable set and neither the caller nor
      ``RSDL_DECODE_PUSHDOWN`` said otherwise (audit-key append stays
      with ``_pushdown_columns``).
    * **decode_rowgroup_threads** — the fair-share rule
      (``cores // concurrent`` when idle cores exist, else 1) computed
      over the *wider* of the two decode stages (map files vs
      selective reducers), so neither site oversubscribes.
    * **fetch_window_depth** — deepest window pipeline whose total
      in-flight residency (``R`` concurrent reducers x depth windows
      of ``est_bytes/(F*R)``) stays under ``WINDOW_BUDGET_FRAC`` of
      the store budget, clamped to the measured-flat [1, 8] range.
    * **native_threads** — kernel threads fair-shared across the
      reducers that gather concurrently, capped at the DRAM-saturation
      point (8).
    """
    files = list(filenames)
    R = max(1, int(num_reducers))
    cores = _cores()
    stats = footer_stats(files, columns=columns, narrow_to_32=narrow_to_32)
    budget = _store_budget()
    terms: Dict[str, PlanTerm] = {}

    def term(name, value, source, why):
        terms[name] = PlanTerm(
            name=name, knob=TERM_KNOBS[name], value=value,
            source=source, why=why,
        )

    from ray_shuffling_data_loader_tpu.utils import shuffle_plan_spec

    # plan family / granularity
    if _env_set("RSDL_SHUFFLE_PLAN"):
        plan = shuffle_plan_spec()
        term("plan", plan, SOURCE_ENV, "pinned by RSDL_SHUFFLE_PLAN")
    else:
        g = stats.get("groups_min")
        bound = QUALITY_BLOCKS_PER_FILE * R
        if g is not None and g >= bound:
            G = max(1, g // bound)
            plan = ("block", G)
            term(
                "plan", plan, SOURCE_PLANNED,
                f"block:{G}: blocks/file {-(-g // G)} >= 2R={bound} "
                f"(min {g} groups/file)",
            )
        else:
            plan = ("rowwise", 0)
            term(
                "plan", plan, SOURCE_PLANNED,
                f"rowwise: min {g} groups/file cannot meet "
                f"blocks/file >= 2R={bound} at any granularity",
            )

    # selective engagement
    import importlib

    # The package __init__ re-exports the shuffle FUNCTION over the
    # module name, so attribute-style imports resolve to the function.
    _shuffle = importlib.import_module(
        "ray_shuffling_data_loader_tpu.shuffle"
    )

    if _env_set("RSDL_SELECTIVE_READS"):
        engaged, reason = _shuffle.selective_reads_decision(plan)
        term("selective", bool(engaged), SOURCE_ENV, reason)
    else:
        prunable = plan[0] == "block"
        # The cache-amortization argument only exists when the run's
        # decode cache is ON (``cache_decoded`` is a shuffle() call
        # argument, not a knob): with it off, a "cache-friendly" size
        # amortizes nothing and selective wins on any prunable plan.
        cache_friendly = False
        if prunable and cache_decoded:
            try:
                cache_friendly = _shuffle._decode_cache_auto(
                    files, num_epochs - start_epoch, narrow_to_32, columns
                )
            except Exception:
                cache_friendly = False
        if not prunable:
            why = "declined: rowwise plan is not prunable (selective " \
                  "would re-read every group ~R times)"
        elif cache_friendly:
            why = "declined: decoded dataset fits the cross-epoch " \
                  "decode cache — one decode amortized beats per-epoch " \
                  "selective re-reads"
        else:
            why = "engaged: block plan prunes for real and the decoded " \
                  "dataset will not be cache-resident"
        term("selective", prunable and not cache_friendly,
             SOURCE_PLANNED, why)

    # column projection
    projection: Optional[List[str]] = None
    if _env_set("RSDL_DECODE_PUSHDOWN"):
        term("columns", None, SOURCE_ENV, "pinned by RSDL_DECODE_PUSHDOWN")
    elif columns is not None:
        term("columns", [str(c) for c in columns], SOURCE_ENV,
             "caller-provided projection")
    elif device_layout is not None and device_layout.get("columns"):
        projection = [str(c) for c in device_layout["columns"]]
        term("columns", list(projection), SOURCE_PLANNED,
             "staging layout proves the touchable column set")
    else:
        term("columns", None, SOURCE_PLANNED,
             "full decode: no layout or caller projection to prove "
             "the touchable set")

    # decode row-group threads (fair share over the wider decode stage)
    from ray_shuffling_data_loader_tpu.utils import decode_rowgroup_threads

    decode_conc = min(cores, max(1, max(len(files), R)))
    if _env_set("RSDL_DECODE_ROWGROUPS"):
        value = decode_rowgroup_threads(decode_conc)
        term("decode_rowgroup_threads", value, SOURCE_ENV,
             "pinned by RSDL_DECODE_ROWGROUPS")
    else:
        value = cores // decode_conc if cores >= 2 * decode_conc else 1
        term(
            "decode_rowgroup_threads", max(1, value), SOURCE_PLANNED,
            f"fair share: {cores} cores / {decode_conc} concurrent "
            "decode tasks",
        )

    # reduce fetch-window depth vs the store budget
    if _env_set("RSDL_FETCH_WINDOW_DEPTH"):
        from ray_shuffling_data_loader_tpu.runtime.store import (
            fetch_window_depth,
        )

        term("fetch_window_depth", fetch_window_depth(default=4),
             SOURCE_ENV, "pinned by RSDL_FETCH_WINDOW_DEPTH")
    else:
        est = stats.get("est_decoded_bytes")
        if est and budget and files:
            window_bytes = max(1.0, est / (len(files) * R))
            conc_reducers = min(R, cores)
            depth = int(
                (WINDOW_BUDGET_FRAC * budget)
                / (window_bytes * max(1, conc_reducers))
            )
            depth = max(WINDOW_DEPTH_MIN, min(WINDOW_DEPTH_MAX, depth))
            term(
                "fetch_window_depth", depth, SOURCE_PLANNED,
                f"{conc_reducers} reducers x depth windows of "
                f"~{int(window_bytes)}B within "
                f"{WINDOW_BUDGET_FRAC:.0%} of the {budget}B budget",
            )
        else:
            term("fetch_window_depth", WINDOW_DEPTH_DEFAULT,
                 SOURCE_PLANNED,
                 "default: dataset size or store budget unknown")

    # native kernel threads
    if _env_set("RSDL_NATIVE_THREADS"):
        from ray_shuffling_data_loader_tpu import native as _native

        term("native_threads", _native.num_threads(), SOURCE_ENV,
             "pinned by RSDL_NATIVE_THREADS")
    else:
        conc_reducers = max(1, min(R, cores))
        value = max(1, min(NATIVE_THREADS_CAP, cores // conc_reducers))
        term(
            "native_threads", value, SOURCE_PLANNED,
            f"fair share: {cores} cores / {conc_reducers} concurrent "
            f"reducers, capped at {NATIVE_THREADS_CAP}",
        )

    model = {
        "num_reducers": R,
        "num_trainers": int(num_trainers),
        "num_epochs": int(num_epochs),
        "cores": cores,
        "store_budget_bytes": budget,
        "stats": stats,
    }
    return ResolvedPlan(
        plan=plan, projection=projection, terms=terms, model=model
    )


# -- between-epoch re-planner ------------------------------------------------


def _live_signals() -> Dict[str, Any]:
    """Live signals from whichever telemetry planes are armed —
    ``sys.modules`` only (the re-planner must never be the reason a
    dark plane loads; same rule as the run ledger). Absent planes
    simply contribute nothing and the re-planner holds."""
    out: Dict[str, Any] = {}
    pkg = "ray_shuffling_data_loader_tpu."
    capacity = sys.modules.get(pkg + "telemetry.capacity")
    if capacity is not None:
        try:
            out["shm_used_frac"] = (capacity.view() or {}).get(
                "shm_used_frac"
            )
        except Exception:
            pass
    critical = sys.modules.get(pkg + "telemetry.critical")
    if critical is not None:
        try:
            analysis = critical.analyze()
            current = analysis.get("current") or {}
            out["critical_path"] = current.get("critical_path")
            out["sole_share"] = current.get("sole_share")
            stalls = analysis.get("stall_by_cause") or {}
            if stalls:
                out["stall_by_cause"] = stalls
        except Exception:
            pass
    timeseries = sys.modules.get(pkg + "telemetry.timeseries")
    if timeseries is not None:
        try:
            rates = getattr(timeseries, "rates", None)
            if callable(rates):
                out["rates"] = rates()
        except Exception:
            pass
    return out


def replan(rplan: ResolvedPlan, *, epoch: int) -> List[Dict[str, Any]]:
    """Adjust the mutable-mid-run terms between epochs from live
    signals. Rules (each bounded, each an explicit ``plan.replanned``
    event with before/after so the ledger can attribute the delta):

    * shm over the high watermark -> halve the fetch-window depth
      (windows are the in-flight residency the planner sized), and
      engage selective on a prunable plan (drops the materialized
      map's store footprint entirely);
    * reduce-dominant epoch with shm headroom -> double the window
      depth (the reduce is starving on fetches, and residency has
      room), up to the measured-flat cap;
    * map(decode)-dominant epoch -> double decode row-group threads
      up to the core count (the planner's fair share assumed every
      stage task runs at once; a decode-bound run has idle cores).

    Env-pinned terms are never touched — the operator's pin outranks
    the re-planner exactly as it outranks the compiler."""
    signals = _live_signals()
    if not signals:
        return []
    changes: List[Dict[str, Any]] = []

    def mutate(name: str, value: Any, reason: str) -> None:
        t = rplan.terms.get(name)
        if (
            t is None
            or name not in MUTABLE_TERMS
            or t.source == SOURCE_ENV
            or t.value == value
        ):
            return
        changes.append(
            {"term": name, "before": t.value, "after": value,
             "reason": reason}
        )
        t.value = value
        t.source = SOURCE_REPLANNED
        t.why = reason

    shm = signals.get("shm_used_frac")
    path = signals.get("critical_path")
    depth = rplan.term_value("fetch_window_depth")
    if shm is not None and shm >= SHM_HIGH_WATER:
        if isinstance(depth, int) and depth > WINDOW_DEPTH_MIN:
            mutate(
                "fetch_window_depth", max(WINDOW_DEPTH_MIN, depth // 2),
                f"shm {shm:.0%} >= {SHM_HIGH_WATER:.0%} watermark: "
                "shed in-flight window residency",
            )
        if rplan.plan[0] == "block" and not rplan.term_value("selective"):
            mutate(
                "selective", True,
                f"shm {shm:.0%} >= {SHM_HIGH_WATER:.0%} watermark: "
                "selective schedule drops map materialization",
            )
    elif path == "reduce" and (shm is None or shm < SHM_HEADROOM):
        if isinstance(depth, int) and depth < WINDOW_DEPTH_MAX:
            mutate(
                "fetch_window_depth", min(WINDOW_DEPTH_MAX, depth * 2),
                "reduce-dominant epoch with shm headroom: deepen the "
                "fetch pipeline",
            )
    if path == "map":
        threads = rplan.term_value("decode_rowgroup_threads")
        cores = _cores()
        if isinstance(threads, int) and threads < cores:
            mutate(
                "decode_rowgroup_threads", min(cores, threads * 2),
                "map(decode)-dominant epoch: grant decode more of the "
                "idle cores",
            )
    if changes:
        rplan.replans += len(changes)
        from ray_shuffling_data_loader_tpu import telemetry as _telemetry
        from ray_shuffling_data_loader_tpu.telemetry import (
            metrics as _metrics,
        )

        for change in changes:
            _telemetry.emit_event(
                "plan.replanned", epoch=epoch, term=change["term"],
                before=str(change["before"]), after=str(change["after"]),
                reason=change["reason"],
            )
            _metrics.safe_inc("plan.replans", term=change["term"])
    return changes
