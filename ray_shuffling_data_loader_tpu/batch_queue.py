"""Epoch-windowed batch delivery queue.

The delivery transport between the shuffle engine (producer) and trainer
ranks (consumers). Functional parity with the reference's
``BatchQueue``/``_QueueActor`` pair (``batch_queue.py:24-355`` client,
``batch_queue.py:383-509`` actor), rebuilt on this framework's actor runtime:

* one named async actor process holds a ``num_epochs × num_trainers`` grid of
  ``asyncio.Queue``;
* the queue carries only :class:`~.runtime.ObjectRef` handles (or small test
  payloads) — bulk reducer outputs stay in the shared-memory store
  (the refs-in-queue design, reference ``dataset.py:195-196``);
* **epoch-window backpressure**: ``new_epoch`` admits a new epoch only after
  the oldest in-flight epoch's producers have signalled done AND trainers
  have ``task_done``-acked every batch (reference ``batch_queue.py:395-418``);
* ``producer_done`` enqueues a ``None`` in-band sentinel per (epoch, rank)
  (reference ``batch_queue.py:420-422``).
"""

from __future__ import annotations

import asyncio
import collections
import os
from collections.abc import Iterable
from typing import Any, Dict, List, Optional

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics


class Empty(Exception):
    pass


class Full(Exception):
    pass


class ProducerDiedError(Exception):
    """A blocking consumer ``get``/``get_batch`` found the queue empty
    and the registered producer process dead — the epoch can never
    complete, so the consumer unblocks with a structured error instead
    of hanging forever (the pre-PR-3 behavior). Carries ``(epoch,
    rank)`` so a trainer can decide to resume the epoch with a fresh
    driver (the shuffle is deterministic per ``(seed, epoch)``)."""

    def __init__(self, epoch: int, rank: int):
        super().__init__(
            f"batch-queue producer died before finishing epoch {epoch} "
            f"(consumer rank {rank}); the epoch cannot complete"
        )
        self.epoch = epoch
        self.rank = rank

    def __reduce__(self):
        return (ProducerDiedError, (self.epoch, self.rank))


def _emit_producer_died(epoch: int, rank: int) -> None:
    """Structured event-log record of a producer-liveness trip (the
    consumer side is the only place that *detects* the death)."""
    try:
        from ray_shuffling_data_loader_tpu import telemetry

        telemetry.emit_event("producer.died", epoch=epoch, rank=rank)
    except Exception:
        pass


def _liveness_interval_s() -> float:
    """How long a blocking consumer waits between producer-liveness
    checks — the detection bound for :class:`ProducerDiedError`.
    Clamped to >= 50 ms: a zero/negative setting would turn every
    blocking get into a tight RPC spin against the queue actor."""
    try:
        value = float(os.environ.get("RSDL_PRODUCER_LIVENESS_S", "2.0"))
    except ValueError:
        return 2.0
    return max(0.05, value)


DEFAULT_QUEUE_NAME = "BatchQueue"


class _QueueActor:
    """Server side. Runs on a single-threaded asyncio loop inside its own
    process — the same concurrency model as the reference's Ray async actor,
    so no locks are needed."""

    def __init__(self, max_epochs, num_epochs, num_trainers, maxsize):
        self.max_epochs = max_epochs
        self.num_epochs = num_epochs
        self.num_trainers = num_trainers
        self.maxsize = maxsize
        self.curr_epochs = collections.deque()
        self.queues: List[List[asyncio.Queue]] = [
            [asyncio.Queue(maxsize) for _ in range(num_trainers)]
            for _ in range(num_epochs)
        ]
        self.producer_done_events: List[List[asyncio.Event]] = [
            [asyncio.Event() for _ in range(num_trainers)]
            for _ in range(num_epochs)
        ]
        # Space wakeups for batched producers: every consume sets the
        # event; a waiting put_batch wakes, re-checks room, and re-arms.
        # Event.set() resolves ALL current waiters (a later clear() does
        # not revoke them), so with several blocked producers none can
        # miss the wakeup — each re-checks room in its own loop turn.
        self.space_events: List[List[asyncio.Event]] = [
            [asyncio.Event() for _ in range(num_trainers)]
            for _ in range(num_epochs)
        ]
        # Producer-liveness supervision (PR 3): the shuffle driver
        # registers its pid; a blocking consumer whose queue stays empty
        # asks producer_alive() and unblocks with ProducerDiedError when
        # the producer died mid-epoch. The queue actor always runs on
        # the producer's host (rank 0 spawns it), so a pid probe is a
        # valid liveness check.
        self._producer_pid: Optional[int] = None
        # Delivery-granularity accounting (ISSUE 8): device-direct
        # delivery enqueues up to three refs per reducer (head remainder
        # / packed body / tail remainder) where the legacy path enqueued
        # one — the lifetime item total makes the actual ref traffic
        # visible in /status and /metrics instead of leaving queue depth
        # as the only (ambiguous) signal.
        self._items_enqueued = 0
        # Idempotent re-publish (ISSUE 13): journaled deliver threads tag
        # each reducer publication with its reducer index. The cursor per
        # (epoch, rank) is the next seq this actor will accept; a resumed
        # driver re-publishing a reducer that already landed (its crash
        # fell between this publish and the journal's cursor append) is
        # dropped whole, so the trainer never sees duplicate rows even
        # when the queue actor outlived the driver.
        self._delivery_seq: Dict[Tuple[int, int], int] = {}
        self._republish_dropped = 0

    def register_producer(self, pid: int) -> None:
        self._producer_pid = int(pid)

    def producer_alive(self, epoch: int) -> bool:
        """Can epoch ``epoch`` still make progress? True when the
        producer already signalled done for every rank (sentinels are in
        band — consumers will drain them), when no producer registered
        (bare queue uses keep the old block-forever semantics), or when
        the registered producer pid is alive."""
        if all(e.is_set() for e in self.producer_done_events[epoch]):
            return True
        if self._producer_pid is None:
            return True
        try:
            os.kill(self._producer_pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True
        return True

    async def new_epoch(self, epoch: int):
        # Admission control: with max_epochs epochs in flight, wait for the
        # oldest to fully drain — producers signalled done (no more batches
        # can appear) and trainers acked every delivered batch. This is the
        # sole source of backpressure (per-queue maxsize defaults to
        # unbounded), matching reference batch_queue.py:395-418.
        if len(self.curr_epochs) == self.max_epochs:
            first_epoch = self.curr_epochs.popleft()
            await asyncio.gather(
                *(e.wait() for e in self.producer_done_events[first_epoch])
            )
            await asyncio.gather(
                *(q.join() for q in self.queues[first_epoch])
            )
        self.curr_epochs.append(epoch)

    async def producer_done(self, rank: int, epoch: int):
        await self.queues[epoch][rank].put(None)
        self.producer_done_events[epoch][rank].set()

    async def wait_until_all_epochs_done(self):
        last = self.num_epochs - 1
        await asyncio.gather(
            *(e.wait() for e in self.producer_done_events[last])
        )
        await asyncio.gather(*(q.join() for q in self.queues[last]))

    def size(self) -> int:
        return sum(q.qsize() for row in self.queues for q in row)

    def qsize(self, rank: int, epoch: int) -> int:
        return self.queues[epoch][rank].qsize()

    def empty(self, rank: int, epoch: int) -> bool:
        return self.queues[epoch][rank].empty()

    def full(self, rank: int, epoch: int) -> bool:
        return self.queues[epoch][rank].full()

    async def put(self, rank, epoch, item, timeout=None):
        try:
            await asyncio.wait_for(self.queues[epoch][rank].put(item), timeout)
        except asyncio.TimeoutError:
            raise Full from None
        self._items_enqueued += 1

    async def put_batch(self, rank, epoch, items, timeout=None, seq=None):
        # All-or-nothing: wait until the queue has room for EVERY item,
        # then enqueue atomically (single-threaded event loop, no awaits
        # between puts). A timeout therefore leaves the queue untouched —
        # the reference's sequential awaited puts can time out half-way
        # with no way to tell the caller what landed
        # (reference ``batch_queue.py:480-488`` is all-or-nothing only for
        # the nowait variant).
        if seq is not None and seq < self._delivery_seq.get(
            (int(epoch), int(rank)), 0
        ):
            # Idempotent re-publish (ISSUE 13): this reducer's refs
            # already landed before the producer's journal cursor did.
            # False tells the producer so it can free the re-published
            # refs — nothing will ever consume them.
            self._republish_dropped += 1
            return False
        queue = self.queues[epoch][rank]
        items = list(items)
        if self.maxsize > 0 and len(items) > self.maxsize:
            raise Full(
                f"Cannot ever add {len(items)} items to a queue with "
                f"maxsize {self.maxsize}."
            )
        loop = asyncio.get_running_loop()
        deadline = None if timeout is None else loop.time() + timeout
        space = self.space_events[epoch][rank]
        while True:
            # Room check and enqueue in ONE synchronous block — no await
            # between them, so a concurrent producer scheduled in the gap
            # cannot steal the room and force a partial enqueue.
            if not (
                self.maxsize > 0
                and queue.qsize() + len(items) > self.maxsize
            ):
                for item in items:
                    queue.put_nowait(item)
                self._items_enqueued += len(items)
                if seq is not None:
                    # Advance only after the enqueue landed: a Full
                    # timeout leaves both queue and cursor untouched.
                    self._delivery_seq[(int(epoch), int(rank))] = seq + 1
                return True
            # Event-driven wait: armed (cleared) atomically with the failed
            # room check — no await separates them, so a consume landing
            # after the check sets the event and the wait returns at once.
            space.clear()
            if deadline is None:
                await space.wait()
            else:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise Full
                try:
                    await asyncio.wait_for(space.wait(), remaining)
                except asyncio.TimeoutError:
                    raise Full from None

    async def get(self, rank, epoch, timeout=None):
        try:
            item = await asyncio.wait_for(
                self.queues[epoch][rank].get(), timeout
            )
        except asyncio.TimeoutError:
            raise Empty from None
        self.space_events[epoch][rank].set()
        return item

    async def get_batch(self, rank, epoch, timeout=None):
        # Block for one item, then opportunistically drain whatever else has
        # already arrived (reference batch_queue.py:468-475). ``timeout``
        # bounds the initial blocking get (Empty on expiry) so the client
        # can interleave producer-liveness checks.
        queue = self.queues[epoch][rank]
        try:
            first = await asyncio.wait_for(queue.get(), timeout)
        except asyncio.TimeoutError:
            raise Empty from None
        batch = [first]
        while True:
            try:
                batch.append(queue.get_nowait())
            except asyncio.QueueEmpty:
                break
        self.space_events[epoch][rank].set()
        return batch

    def put_nowait(self, rank, epoch, item):
        self.queues[epoch][rank].put_nowait(item)
        self._items_enqueued += 1

    def put_nowait_batch(self, rank, epoch, items):
        if (
            self.maxsize > 0
            and len(items) + self.qsize(rank, epoch) > self.maxsize
        ):
            raise Full(
                f"Cannot add {len(items)} items to queue of size "
                f"{self.qsize(rank, epoch)} and maxsize {self.maxsize}."
            )
        for item in items:
            self.queues[epoch][rank].put_nowait(item)
        self._items_enqueued += len(items)

    def get_nowait(self, rank, epoch):
        item = self.queues[epoch][rank].get_nowait()
        self.space_events[epoch][rank].set()
        return item

    def get_nowait_batch(self, rank, epoch, num_items=None):
        if num_items is None:
            num_items = self.qsize(rank, epoch)
        if num_items > self.qsize(rank, epoch):
            raise Empty(
                f"Cannot get {num_items} items from queue of size "
                f"{self.qsize(rank, epoch)}."
            )
        out = [self.queues[epoch][rank].get_nowait() for _ in range(num_items)]
        self.space_events[epoch][rank].set()
        return out

    def task_done(self, rank, epoch, num_items: int = 1):
        for _ in range(num_items):
            self.queues[epoch][rank].task_done()
        # Room is qsize-based so task_done frees none, but waking here is
        # harmless (waiters re-check) and covers consumers that ack late.
        self.space_events[epoch][rank].set()

    def restore_delivery_cursors(self, cursors: Dict[str, int]) -> None:
        """Seed the idempotency cursors on a FRESH actor from a journal
        (a resumed driver whose previous queue actor died with it).
        Max-merged — an actor that survived the driver keeps its own,
        possibly further-advanced, cursors."""
        for key, seq in cursors.items():
            e, r = key.split("/")
            k = (int(e), int(r))
            self._delivery_seq[k] = max(
                self._delivery_seq.get(k, 0), int(seq)
            )

    def status_snapshot(self) -> Dict[str, Any]:
        """Live window state for the obs plane's /status page: the
        admission window (in-flight epochs), per-``(epoch, rank)`` queue
        depths for those epochs, and producer liveness — one cheap
        synchronous read on the actor loop."""
        alive = True
        if self._producer_pid is not None:
            try:
                os.kill(self._producer_pid, 0)
            except ProcessLookupError:
                alive = False
            except PermissionError:
                pass
        return {
            "in_flight_epochs": list(self.curr_epochs),
            "num_epochs": self.num_epochs,
            "num_trainers": self.num_trainers,
            "producer_pid": self._producer_pid,
            "producer_alive": alive,
            "items_enqueued_total": self._items_enqueued,
            "republish_dropped_total": self._republish_dropped,
            "depth_total": self.size(),
            "depths": {
                f"{epoch}/{rank}": q.qsize()
                for epoch in self.curr_epochs
                for rank, q in enumerate(self.queues[epoch])
            },
        }

    def metrics_snapshot(self) -> Dict[str, float]:
        """Live per-``(epoch, rank)`` queue depths in the metrics-registry
        key vocabulary — polled by the driver's metrics sampler through a
        registered source (:func:`telemetry.metrics.register_source`).
        Only in-flight epochs (the admission window) are keyed
        individually, bounding the series to ``max_epochs x trainers``."""
        out: Dict[str, float] = {}
        for epoch in self.curr_epochs:
            for rank, q in enumerate(self.queues[epoch]):
                out[
                    _metrics.format_key(
                        "queue.depth", {"epoch": epoch, "rank": rank}
                    )
                ] = float(q.qsize())
        out["queue.depth.total"] = float(self.size())
        out["queue.items_enqueued.total"] = float(self._items_enqueued)
        out["queue.republish_dropped.total"] = float(
            self._republish_dropped
        )
        return out


class BatchQueue:
    """Client-side handle; sync and async, single and batched operations.

    API parity with reference ``BatchQueue`` (``batch_queue.py:24-355``),
    with the Ray actor replaced by a named runtime actor. Create on rank 0
    with ``connect=False``; other ranks discover it by name with
    exponential-backoff retry (``connect=True``).
    """

    def __init__(
        self,
        num_epochs: int,
        num_trainers: int,
        max_concurrent_epochs: int,
        maxsize: int = 0,
        name: Optional[str] = None,
        connect: bool = False,
        connect_retries: int = 5,
    ) -> None:
        runtime.ensure_initialized()
        self._metrics_source: Optional[str] = None
        if connect:
            assert name is not None
            self.actor = runtime.connect_actor(name, num_retries=connect_retries)
        else:
            self.actor = runtime.spawn_actor(
                _QueueActor,
                max_concurrent_epochs,
                num_epochs,
                num_trainers,
                maxsize,
                name=name,
            )
            # The creating process IS the producer (rank 0 drives the
            # shuffle); registering its pid arms the consumer-side
            # liveness supervision (ProducerDiedError instead of an
            # unbounded hang when this process dies mid-epoch).
            self.actor.call("register_producer", os.getpid())
            if _metrics.enabled():
                # Cross-process metrics source: the sampler thread pulls
                # the actor's live per-(epoch, rank) depths into every
                # global_snapshot. Dropped automatically if the actor dies
                # (source failure limit), and explicitly on shutdown().
                actor = self.actor
                self._metrics_source = (
                    f"batch_queue:{name or DEFAULT_QUEUE_NAME}-{id(self)}"
                )
                _metrics.register_source(
                    self._metrics_source,
                    lambda: actor.call("metrics_snapshot"),
                )
            if os.environ.get("RSDL_OBS_PORT"):
                # Obs-plane status provider: the /status page asks the
                # queue actor for its admission-window snapshot on a
                # short-timeout one-shot connection (a wedged actor must
                # slow one scrape, not hang the endpoint thread forever).
                try:
                    from ray_shuffling_data_loader_tpu.telemetry import (
                        obs_server,
                    )

                    status_actor = self.actor

                    def _queue_status() -> Dict[str, Any]:
                        return status_actor.call_with_timeout(
                            "status_snapshot", timeout=2.0
                        )

                    obs_server.register_status_provider(
                        "batch_queue", _queue_status
                    )
                except Exception:
                    pass

    def __getstate__(self):
        return {"actor": self.actor}

    def __setstate__(self, state):
        self.actor = state["actor"]
        # The metrics source is owned by the creating process only.
        self._metrics_source = None

    def ready(self) -> None:
        """Block until the queue actor is up (reference ``batch_queue.py:67``)."""
        self.actor.wait_ready()

    def new_epoch(self, epoch: int) -> None:
        """Admit a new epoch, blocking on the epoch window."""
        self.actor.call("new_epoch", epoch)

    def producer_done(self, rank: int, epoch: int) -> None:
        """Fire-and-forget, like the un-``ray.get``-ed call at reference
        ``batch_queue.py:94``."""
        self.actor.call_oneway("producer_done", rank, epoch)

    def task_done(self, rank: int, epoch: int, num_items: int = 1) -> None:
        self.actor.call_oneway("task_done", rank, epoch, num_items)

    def wait_until_all_epochs_done(self) -> None:
        self.actor.call("wait_until_all_epochs_done")

    def __len__(self) -> int:
        return self.actor.call("size")

    def size(self, rank: int, epoch: int) -> int:
        return self.actor.call("qsize", rank, epoch)

    def qsize(self, rank: int, epoch: int) -> int:
        return self.size(rank, epoch)

    def empty(self, rank: int, epoch: int) -> bool:
        return self.actor.call("empty", rank, epoch)

    def full(self, rank: int, epoch: int) -> bool:
        return self.actor.call("full", rank, epoch)

    def put(self, rank, epoch, item, block=True, timeout=None) -> None:
        if not block:
            try:
                self.actor.call("put_nowait", rank, epoch, item)
            except asyncio.QueueFull:
                raise Full from None
        else:
            if timeout is not None and timeout < 0:
                raise ValueError("'timeout' must be a non-negative number")
            self.actor.call("put", rank, epoch, item, timeout)

    def put_batch(
        self, rank, epoch, items, block=True, timeout=None, seq=None
    ):
        """Returns False when the actor dropped a ``seq``-tagged
        re-publish below its idempotency cursor — the caller still owns
        the never-to-be-consumed refs and must free them."""
        if not block:
            try:
                self.actor.call("put_nowait_batch", rank, epoch, list(items))
            except asyncio.QueueFull:
                raise Full from None
        else:
            if timeout is not None and timeout < 0:
                raise ValueError("'timeout' must be a non-negative number")
            return self.actor.call(
                "put_batch", rank, epoch, list(items), timeout, seq
            )

    def restore_delivery_cursors(self, cursors: Dict[str, int]) -> None:
        """Seed the actor's idempotency cursors from a journal (max-
        merged; see ``_QueueActor.restore_delivery_cursors``)."""
        self.actor.call("restore_delivery_cursors", dict(cursors))

    async def put_async(self, rank, epoch, item, block=True, timeout=None):
        if not block:
            try:
                await self.actor.call_async("put_nowait", rank, epoch, item)
            except asyncio.QueueFull:
                raise Full from None
        else:
            if timeout is not None and timeout < 0:
                raise ValueError("'timeout' must be a non-negative number")
            await self.actor.call_async("put", rank, epoch, item, timeout)

    def get(self, rank, epoch, block=True, timeout=None) -> Any:
        if not block:
            try:
                return self.actor.call("get_nowait", rank, epoch)
            except asyncio.QueueEmpty:
                raise Empty from None
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        if timeout is not None:
            # Caller-bounded wait keeps its exact pre-PR-3 semantics
            # (Empty on expiry).
            return self.actor.call("get", rank, epoch, timeout)
        # Unbounded wait becomes a supervised wait: block in liveness-
        # interval slices; a dead producer with an empty queue raises
        # ProducerDiedError instead of hanging forever.
        interval = _liveness_interval_s()
        while True:
            try:
                return self.actor.call("get", rank, epoch, interval)
            except Empty:
                if not self.actor.call("producer_alive", epoch):
                    _emit_producer_died(epoch, rank)
                    raise ProducerDiedError(epoch, rank) from None

    async def get_async(self, rank, epoch, block=True, timeout=None) -> Any:
        if not block:
            try:
                return await self.actor.call_async("get_nowait", rank, epoch)
            except asyncio.QueueEmpty:
                raise Empty from None
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        return await self.actor.call_async("get", rank, epoch, timeout)

    def get_batch(self, rank: int, epoch: int) -> List[Any]:
        # Supervised like get(): the batch wait blocks in bounded slices
        # and surfaces ProducerDiedError when the producer died with the
        # queue drained (this is the trainer-side ShufflingDataset path,
        # so a killed driver can no longer wedge every rank forever).
        interval = _liveness_interval_s()
        while True:
            try:
                return self.actor.call("get_batch", rank, epoch, interval)
            except Empty:
                if not self.actor.call("producer_alive", epoch):
                    _emit_producer_died(epoch, rank)
                    raise ProducerDiedError(epoch, rank) from None

    def put_nowait(self, rank, epoch, item) -> None:
        return self.put(rank, epoch, item, block=False)

    def put_nowait_batch(self, rank, epoch, items) -> None:
        if not isinstance(items, Iterable):
            raise TypeError("Argument 'items' must be an Iterable")
        try:
            self.actor.call("put_nowait_batch", rank, epoch, list(items))
        except asyncio.QueueFull:
            raise Full from None

    def get_nowait(self, rank, epoch) -> Any:
        return self.get(rank, epoch, block=False)

    def get_nowait_batch(self, rank, epoch, num_items=None) -> List[Any]:
        if num_items is not None:
            if not isinstance(num_items, int):
                raise TypeError("Argument 'num_items' must be an int")
            if num_items < 0:
                raise ValueError("'num_items' must be nonnegative")
        try:
            return self.actor.call("get_nowait_batch", rank, epoch, num_items)
        except asyncio.QueueEmpty:
            raise Empty from None

    def shutdown(self, force: bool = False, grace_period_s: int = 5) -> None:
        """Graceful-then-forceful actor termination (reference
        ``batch_queue.py:333-355``)."""
        if self._metrics_source is not None:
            _metrics.unregister_source(self._metrics_source)
            self._metrics_source = None
        if os.environ.get("RSDL_OBS_PORT"):
            try:
                from ray_shuffling_data_loader_tpu.telemetry import (
                    obs_server,
                )

                obs_server.unregister_status_provider("batch_queue")
            except Exception:
                pass
        if self.actor:
            self.actor.terminate(force=force, grace_period_s=grace_period_s)
        self.actor = None


def connect_queue(name: str = DEFAULT_QUEUE_NAME, num_retries: int = 5):
    """Discover an existing queue by name (reference
    ``connect_queue_actor``, ``batch_queue.py:358-380``)."""
    runtime.ensure_initialized()
    return runtime.connect_actor(name, num_retries=num_retries)
