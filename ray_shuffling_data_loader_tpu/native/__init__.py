"""ctypes bindings for the C++ data-plane kernels (``kernels.cc``).

The reference's native layer is Ray core (C++) plus pandas/pyarrow; this
package is the standalone equivalent for the shuffle pipeline's host-side
hot ops: permutation gathers, fused concat+gather, stable group-by
partitioning, and narrowing casts (see ``kernels.cc`` for the
reference-file citations per op).

Loading strategy:

1. try a prebuilt ``librsdl_native.so`` next to this file;
2. else build it once with ``g++ -O3 -shared -fPIC -pthread`` into a
   per-user cache dir (no pip/cmake involved);
3. else (no toolchain / build failure) every wrapper silently falls back
   to an equivalent numpy expression — correctness never depends on the
   native build, only throughput does.

Set ``RSDL_DISABLE_NATIVE=1`` to force the numpy paths (used by tests to
compare both implementations).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import tempfile
import threading
from typing import List, Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "kernels.cc")
_LIB_BASENAME = "librsdl_native.so"

_lib = None
_lib_lock = threading.Lock()
_load_attempted = False

ENV_THREADS = "RSDL_NATIVE_THREADS"


def _threads_from_env() -> int:
    """Kernel thread count: ``RSDL_NATIVE_THREADS`` when set (clamped
    ≥ 1), else the old heuristic — gathers are memory-bound, so a
    handful of threads saturates DRAM and more just adds spawn cost."""
    env = os.environ.get(ENV_THREADS, "").strip()
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, min(8, (os.cpu_count() or 1)))


# Read once at import (the knob is a process-level setting, like the
# telemetry gates); tools that sweep thread counts pass n_threads= per
# call instead of mutating the env.
_NUM_THREADS = _threads_from_env()


def num_threads() -> int:
    """The resolved default kernel thread count (``RSDL_NATIVE_THREADS``)."""
    return _NUM_THREADS


def refresh_threads_from_env() -> None:
    """Re-read ``RSDL_NATIVE_THREADS`` (tests)."""
    global _NUM_THREADS
    _NUM_THREADS = _threads_from_env()


def set_num_threads(n: Optional[int]) -> None:
    """Set the process default kernel thread count. The planner's
    delivery path for its ``native_threads`` term: stage tasks apply
    the planned value on entry (env snapshots date from pool spawn, so
    the env-read default can't carry it). None is a no-op."""
    global _NUM_THREADS
    if n is not None:
        _NUM_THREADS = max(1, int(n))


def _resolve_threads(n_threads: Optional[int]) -> int:
    return _NUM_THREADS if n_threads is None else max(1, int(n_threads))


# Thread-slice floor shared with the C side's parallel_for cap: one
# thread per ~524k rows. Below ~1 ms of per-slice work the std::thread
# spawn cost dominates and threading is a measured LOSS (the r7 sweep at
# 372k rows ran 0.6-0.9x serial uncapped); the parallel group scatter
# engages only when at least two such slices exist.
_MIN_ROWS_PER_THREAD = 1 << 19


def _build_lib() -> Optional[str]:
    """Compile kernels.cc into a cached .so; returns its path or None."""
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return None
    cache_dir = os.environ.get("RSDL_NATIVE_CACHE") or os.path.join(
        tempfile.gettempdir(), f"rsdl-native-{os.getuid()}"
    )
    out = os.path.join(cache_dir, f"{digest}-{_LIB_BASENAME}")
    if os.path.exists(out):
        return out
    os.makedirs(cache_dir, exist_ok=True)
    tmp = out + f".build-{os.getpid()}"
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-pthread",
        _SRC,
        "-o",
        tmp,
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120
        )
        os.rename(tmp, out)  # atomic publish for concurrent builders
        return out
    except (subprocess.SubprocessError, OSError) as exc:
        print(
            f"[rsdl.native] build failed, using numpy fallbacks: {exc}",
            file=sys.stderr,
        )
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_i64 = ctypes.c_int64
    c_int = ctypes.c_int
    p = ctypes.c_void_p
    lib.rsdl_take.argtypes = [p, p, p, c_i64, c_i64, c_i64, c_int]
    lib.rsdl_take.restype = c_int
    lib.rsdl_take_multi.argtypes = [p, p, c_i64, p, p, c_i64, c_i64, c_int]
    lib.rsdl_take_multi.restype = c_int
    lib.rsdl_cast_i64_i32.argtypes = [p, p, c_i64, c_int]
    lib.rsdl_cast_i64_i32_checked.argtypes = [p, p, c_i64, c_int]
    lib.rsdl_cast_i64_i32_checked.restype = c_int
    lib.rsdl_cast_f64_f32.argtypes = [p, p, c_i64, c_int]
    lib.rsdl_group_rows.argtypes = [p, p, p, c_i64, c_i64, p]
    lib.rsdl_scatter.argtypes = [p, p, p, c_i64, c_i64, c_i64, c_int]
    lib.rsdl_scatter.restype = c_int
    lib.rsdl_group_plan.argtypes = [p, c_i64, c_i64, c_int, p, p]
    lib.rsdl_group_rows_multi_mt.argtypes = [
        p, p, p, c_i64, p, c_i64, p, c_int, c_i64
    ]
    lib.rsdl_abi_version.restype = c_int
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("RSDL_DISABLE_NATIVE"):
            return None
        # Lazy second candidate: only compile when no prebuilt .so loads.
        for get_candidate in (
            lambda: os.path.join(_HERE, _LIB_BASENAME),
            _build_lib,
        ):
            candidate = get_candidate()
            if candidate and os.path.exists(candidate):
                try:
                    lib = _declare(ctypes.CDLL(candidate))
                    if lib.rsdl_abi_version() == 5:
                        _lib = lib
                        break
                except (OSError, AttributeError):
                    # Unloadable or stale/ABI-mismatched .so (e.g. a symbol
                    # missing from an old build): keep the numpy fallbacks.
                    continue
        return _lib


def native_available() -> bool:
    return _get_lib() is not None


def _ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.c_void_p)


def _rows_contig(arr: np.ndarray) -> Optional[int]:
    """Bytes per row if arr is C-contiguous (row = one index-0 slice)."""
    if not arr.flags.c_contiguous:
        return None
    return int(arr.dtype.itemsize * int(np.prod(arr.shape[1:], dtype=np.int64)))


def _check_bounds(idx: np.ndarray, n: int) -> bool:
    """True if idx is safe for the unchecked C gathers; raises on
    out-of-range exactly like numpy. Non-integer index arrays (bool masks,
    floats) and negative indices route to the numpy fallback, which
    implements their semantics."""
    if len(idx) == 0 or not np.issubdtype(idx.dtype, np.integer):
        return False
    lo, hi = int(idx.min()), int(idx.max())
    if hi >= n or lo < -n:
        raise IndexError(
            f"index out of bounds for axis 0 with size {n}: [{lo}, {hi}]"
        )
    return lo >= 0


def _out_ok(out: Optional[np.ndarray], shape, dtype) -> bool:
    """Strict ``out=`` contract: providing a destination that cannot hold
    the result is a caller bug and raises — silently falling back to a
    fresh array would publish an untouched (zero) segment in the
    direct-to-store write paths."""
    if out is None:
        return False
    if (
        out.shape != tuple(shape)
        or out.dtype != dtype
        or not out.flags.c_contiguous
        or not out.flags.writeable
    ):
        raise ValueError(
            f"out= mismatch: need {tuple(shape)} {dtype} C-contiguous "
            f"writable, got {out.shape} {out.dtype} "
            f"(contig={out.flags.c_contiguous}, "
            f"writable={out.flags.writeable})"
        )
    return True


def take(
    arr: np.ndarray,
    idx: np.ndarray,
    out: Optional[np.ndarray] = None,
    n_threads: Optional[int] = None,
) -> np.ndarray:
    """``arr[idx]`` along axis 0 (multi-threaded when native is loaded).

    ``out``: pre-allocated destination (e.g. a writable store-segment view
    from ``ObjectStore.create_columns``) — the gather lands directly in
    shared memory, skipping the copy-out a fresh array would need.
    ``n_threads`` overrides the ``RSDL_NATIVE_THREADS`` default.

    Bounds are checked INSIDE the kernel (free per row): the old Python
    ``idx.min()/idx.max()`` pre-scan cost two full single-threaded
    passes per call, a fixed term that measurably capped multi-core
    scaling. The rare failure (out-of-range raises, negative indices
    fall back) re-derives exact numpy semantics off the hot path."""
    lib = _get_lib()
    row_bytes = _rows_contig(arr)
    idx_arr = np.asarray(idx)
    shape = (len(idx_arr), *arr.shape[1:])
    if (
        lib is not None
        and row_bytes is not None
        and arr.size != 0
        and len(idx_arr) != 0
        and np.issubdtype(idx_arr.dtype, np.integer)
    ):
        idx_c = np.ascontiguousarray(idx_arr, dtype=np.int64)
        if not _out_ok(out, shape, arr.dtype):
            out = np.empty(shape, dtype=arr.dtype)
        rc = lib.rsdl_take(
            _ptr(arr), _ptr(out), _ptr(idx_c), len(idx_c), row_bytes,
            len(arr), _resolve_threads(n_threads),
        )
        if rc == 0:
            return out
        try:
            _check_bounds(idx_arr, len(arr))  # IndexError if truly OOB
        except IndexError:
            # The kernel may have partially written ``out`` before the
            # bad index was hit; restore the fresh-segment invariant
            # (direct-to-store destinations start zeroed) before
            # surfacing the error — error-path only, never a hot cost.
            out[...] = 0
            raise
        np.take(arr, idx_arr, axis=0, out=out)  # negative-index semantics
        return out
    if _out_ok(out, shape, arr.dtype):
        np.take(arr, idx_arr, axis=0, out=out)
        return out
    return arr[idx]


def scatter(
    src: np.ndarray,
    idx: np.ndarray,
    out: np.ndarray,
    n_threads: Optional[int] = None,
) -> np.ndarray:
    """``out[idx] = src`` along axis 0 — the write-side inverse of
    :func:`take`, multi-threaded when native is loaded.

    The overlapped reduce's hot op: each arriving partition window lands
    at its permuted output rows (``idx`` = a slice of the inverted epoch
    permutation) while later windows are still in flight over DCN — the
    C call releases the GIL, so the scatter uses every core concurrently
    with the prefetch threads' socket reads.

    ``idx`` values must be UNIQUE (permutation-derived): numpy resolves
    duplicate destinations last-write-wins, but across kernel threads
    the winner would be racy — callers with possibly-duplicated indices
    must use the numpy assignment directly. Non-integer / negative /
    out-of-range indices fall back to (or raise like) numpy; on the
    out-of-range raise, already-scattered rows of ``out`` keep their
    new values (``out`` accumulates across calls in the overlapped
    reduce, so "restore" has no meaning here — the failing task aborts
    its pending segment instead)."""
    src = np.asarray(src)
    idx_arr = np.asarray(idx)
    if len(src) != len(idx_arr):
        raise ValueError(
            f"scatter length mismatch: {len(src)} rows vs {len(idx_arr)} "
            "indices"
        )
    lib = _get_lib()
    row_bytes = _rows_contig(src)
    if (
        lib is None
        or row_bytes is None
        or row_bytes != _rows_contig(out)
        or src.dtype != out.dtype
        or src.shape[1:] != out.shape[1:]
        or not out.flags.writeable
        or src.size == 0
        or not np.issubdtype(idx_arr.dtype, np.integer)
    ):
        out[idx_arr] = src
        return out
    idx_c = np.ascontiguousarray(idx_arr, dtype=np.int64)
    rc = lib.rsdl_scatter(
        _ptr(src), _ptr(out), _ptr(idx_c), len(idx_c), row_bytes,
        len(out), _resolve_threads(n_threads),
    )
    if rc != 0:
        # Out-of-range raises (like numpy); negative indices fall back
        # to numpy's wraparound semantics — both off the hot path.
        _check_bounds(idx_arr, len(out))
        out[idx_arr] = src
    return out


def _take_multi_sparse(
    parts: Sequence[np.ndarray],
    idx: np.ndarray,
    out: Optional[np.ndarray],
) -> np.ndarray:
    """Numpy sparse multi-part gather: partition ``idx`` by source part
    (one searchsorted over the part offsets) and scatter each part's rows
    into place — never materializes the concatenated source. Used when the
    fused C++ kernel is unavailable yet the gather is sparse enough that a
    full concat would dominate the cost."""
    offsets = np.zeros(len(parts) + 1, dtype=np.int64)
    np.cumsum([len(p) for p in parts], out=offsets[1:])
    idx = idx.astype(np.int64, copy=False)
    shape = (len(idx), *parts[0].shape[1:])
    if not _out_ok(out, shape, parts[0].dtype):
        out = np.empty(shape, dtype=parts[0].dtype)
    part_id = np.searchsorted(offsets, idx, side="right") - 1
    local = idx - offsets[part_id]
    for p in range(len(parts)):
        sel = np.nonzero(part_id == p)[0]
        if len(sel):
            out[sel] = parts[p][local[sel]]
    return out


def take_multi(
    parts: Sequence[np.ndarray],
    idx: np.ndarray,
    out: Optional[np.ndarray] = None,
    n_threads: Optional[int] = None,
) -> np.ndarray:
    """``np.concatenate(parts)[idx]`` without materializing the concat.

    The reduce-stage hot path: `parts` are one column's partitions from all
    mappers, `idx` the epoch permutation over their concatenated rows.
    ``out`` lands the gather directly in a pre-allocated destination.

    Bounds are checked INSIDE the fused kernel (free per row, like
    take/scatter): the old Python ``idx.min()/idx.max()`` pre-scan cost
    two full single-threaded passes per call on this — the hottest —
    kernel (ROADMAP 2b residual). The numpy fallback paths still
    pre-validate (they need the answer to pick sparse vs concat anyway).
    """
    if not parts:
        raise ValueError("need at least one part to concatenate")
    template = parts[0]
    parts = [p for p in parts if len(p)]
    if not parts:
        return template[idx]  # empty concat: numpy raises/returns likewise
    lib = _get_lib()
    row_bytes = _rows_contig(parts[0])
    same = all(
        _rows_contig(p) == row_bytes
        and p.dtype == parts[0].dtype
        and p.shape[1:] == parts[0].shape[1:]
        for p in parts
    )
    total = sum(len(p) for p in parts)
    idx_arr = np.asarray(idx)
    is_int_idx = (
        len(idx_arr) != 0 and np.issubdtype(idx_arr.dtype, np.integer)
    )
    # Strategy: the fused kernel skips materializing the concat but pays a
    # per-row part lookup; a DENSE gather (idx covers ~all rows, the
    # reduce path) only wins fused when threads amortize that — on few
    # cores a sequential concat (pure memcpy) + one gather is fastest.
    # A SPARSE gather (idx << total rows, the steady-state index-schedule
    # path) must never materialize the concat: the copy would dwarf the
    # gather itself. Sparse paths assume parts[0]'s dtype/shape for every
    # part, so mixed-dtype parts must keep going through the concat
    # (numpy promotes there; the sparse scatter would silently truncate).
    compat = all(
        p.dtype == parts[0].dtype and p.shape[1:] == parts[0].shape[1:]
        for p in parts
    )
    maybe_sparse = compat and len(parts) > 1 and 2 * len(idx_arr) < total
    threads = _resolve_threads(n_threads)
    if (
        lib is not None
        and row_bytes is not None
        and same
        and len(parts) > 1
        and (threads >= 4 or maybe_sparse)
        and is_int_idx
    ):
        idx_c = np.ascontiguousarray(idx_arr, dtype=np.int64)
        offsets = np.zeros(len(parts) + 1, dtype=np.int64)
        np.cumsum([len(p) for p in parts], out=offsets[1:])
        ptrs = (ctypes.c_void_p * len(parts))(*[p.ctypes.data for p in parts])
        shape = (len(idx_c), *parts[0].shape[1:])
        if not _out_ok(out, shape, parts[0].dtype):
            out = np.empty(shape, dtype=parts[0].dtype)
        # rsdl_take_multi dispatches typed inner loops for widths 1/2/4/8
        # internally; rc != 0 means an index fell outside [0, total) and
        # the slow path below re-derives exact numpy semantics.
        rc = lib.rsdl_take_multi(
            ptrs, _ptr(offsets), len(parts), _ptr(out), _ptr(idx_c),
            len(idx_c), row_bytes, threads,
        )
        if rc == 0:
            return out
        try:
            _check_bounds(idx_arr, total)  # IndexError if truly OOB
        except IndexError:
            # Restore the fresh-segment invariant of direct-to-store
            # destinations before surfacing the error (error-path only).
            out[...] = 0
            raise
        # Negative indices: numpy wraparound semantics via the concat.
        np.take(np.concatenate(parts), idx_arr, axis=0, out=out)
        return out
    in_bounds = _check_bounds(idx_arr, total)  # raises when truly OOB
    if maybe_sparse and in_bounds:
        return _take_multi_sparse(parts, idx_arr, out)
    base = parts[0] if len(parts) == 1 else np.concatenate(parts)
    return take(base, idx, out=out, n_threads=n_threads)


def narrow_i64_checked(
    arr: np.ndarray, n_threads: Optional[int] = None
) -> Optional[np.ndarray]:
    """Range-checked ``int64 -> int32`` in ONE fused pass (the numpy route
    costs three: max scan, min scan, astype). Returns the int32 array, or
    None when any value falls outside int32 range — the caller decides how
    to fail. Falls back to the three-pass numpy check without the .so."""
    if arr.dtype != np.int64:
        # Not an assert: stripped under PYTHONOPTIMIZE, and a wrong dtype
        # reaching the C kernel reads past the buffer.
        raise TypeError(f"narrow_i64_checked expects int64, got {arr.dtype}")
    lib = _get_lib()
    if lib is not None and arr.flags.c_contiguous and arr.size:
        out = np.empty(arr.shape, dtype=np.int32)
        ok = lib.rsdl_cast_i64_i32_checked(
            _ptr(arr), _ptr(out), arr.size, _resolve_threads(n_threads)
        )
        return out if ok else None
    if arr.size and (
        arr.max() > np.iinfo(np.int32).max or arr.min() < np.iinfo(np.int32).min
    ):
        return None
    return arr.astype(np.int32)


def narrow(
    arr: np.ndarray, dtype, n_threads: Optional[int] = None
) -> np.ndarray:
    """``arr.astype(dtype)`` with fast paths for the staging casts
    (int64→int32, float64→float32)."""
    dtype = np.dtype(dtype)
    if arr.dtype == dtype:
        return arr
    lib = _get_lib()
    threads = _resolve_threads(n_threads)
    if lib is not None and arr.flags.c_contiguous and arr.size:
        out = np.empty(arr.shape, dtype=dtype)
        if arr.dtype == np.int64 and dtype == np.int32:
            lib.rsdl_cast_i64_i32(_ptr(arr), _ptr(out), arr.size, threads)
            return out
        if arr.dtype == np.float64 and dtype == np.float32:
            lib.rsdl_cast_f64_f32(_ptr(arr), _ptr(out), arr.size, threads)
            return out
    return arr.astype(dtype)


def group_rows(
    arr: np.ndarray,
    assignment: np.ndarray,
    num_groups: int,
    n_threads: Optional[int] = None,
):
    """Stable partition of rows by ``assignment`` (the map-stage op).

    Returns ``(grouped, offsets)`` where ``grouped`` has ``arr``'s rows
    reordered so group ``g`` occupies ``grouped[offsets[g]:offsets[g+1]]``,
    preserving input order within a group. Single-pass counting scatter vs
    the argsort+gather equivalent.
    """
    grouped, offsets = group_rows_multi(
        {"": arr}, assignment, num_groups, n_threads=n_threads
    )
    return grouped[""], offsets


def group_rows_multi(
    columns: dict,
    assignment: np.ndarray,
    num_groups: int,
    out: Optional[dict] = None,
    n_threads: Optional[int] = None,
):
    """:func:`group_rows` over several equal-length columns sharing one
    assignment. The numpy fallback argsorts the assignment ONCE and gathers
    each column, matching the native path's per-column O(n) cost.

    With ``n_threads > 1`` (the ``RSDL_NATIVE_THREADS`` default) and
    enough rows, the scatter runs the two-pass parallel kernel: one
    (thread, group) histogram + prefix-sum plan per batch, then an
    independent typed scatter per contiguous input range — bit-identical
    to the serial kernel because thread ranges are contiguous and the
    plan orders their output spans by thread id (stability preserved).

    ``out``: dict of pre-allocated destinations per column (e.g. writable
    store-segment views) — the partition scatter writes shared memory
    directly; the map stage's only full data pass."""
    lib = _get_lib()
    arrs = list(columns.values())
    assignment = np.asarray(assignment)
    if len(assignment) and (
        int(assignment.min()) < 0 or int(assignment.max()) >= num_groups
    ):
        raise ValueError(
            f"assignment values must be in [0, {num_groups}); got "
            f"[{assignment.min()}, {assignment.max()}]"
        )
    native_ok = (
        lib is not None
        and arrs
        and arrs[0].size > 0
        and all(_rows_contig(a) is not None for a in arrs)
    )
    # One histogram pass for the whole batch, shared by every column.
    counts = np.bincount(assignment, minlength=num_groups)
    offsets = np.zeros(num_groups + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    def _dst(name, arr):
        if out is None:
            return None
        if name not in out:
            raise KeyError(f"out= missing destination for column {name!r}")
        return out[name]

    if not native_ok:
        order = np.argsort(assignment, kind="stable")
        result = {}
        for k, v in columns.items():
            dst = _dst(k, v)
            if _out_ok(dst, v.shape, v.dtype):
                np.take(v, order, axis=0, out=dst)
                result[k] = dst
            else:
                result[k] = v[order]
        return result, offsets
    assignment = np.ascontiguousarray(assignment, dtype=np.int32)
    n = len(assignment)
    # Cap threads so every contiguous slice is worth its spawn (shared
    # policy with the C parallel_for — see _MIN_ROWS_PER_THREAD).
    threads = min(
        _resolve_threads(n_threads), max(1, n // _MIN_ROWS_PER_THREAD)
    )
    dsts = {}
    for name, arr in columns.items():
        dst = _dst(name, arr)
        if not _out_ok(dst, arr.shape, arr.dtype):
            dst = np.empty_like(arr)
        dsts[name] = dst
    if threads > 1:
        # Two-pass parallel stable scatter: ONE (thread, group) cursor
        # plan for the batch, then one multi-column kernel call — threads
        # spawn once and sweep every column over their input range.
        plan = np.empty(threads * num_groups, dtype=np.int64)
        group_starts = np.ascontiguousarray(offsets[:num_groups])
        lib.rsdl_group_plan(
            _ptr(assignment), n, num_groups, threads,
            _ptr(group_starts), _ptr(plan),
        )
        arrs_list = list(columns.values())
        dst_list = [dsts[name] for name in columns]
        src_ptrs = (ctypes.c_void_p * len(arrs_list))(
            *[a.ctypes.data for a in arrs_list]
        )
        dst_ptrs = (ctypes.c_void_p * len(dst_list))(
            *[d.ctypes.data for d in dst_list]
        )
        itemsizes = np.array(
            [_rows_contig(a) for a in arrs_list], dtype=np.int64
        )
        lib.rsdl_group_rows_multi_mt(
            src_ptrs, dst_ptrs, _ptr(itemsizes), len(arrs_list),
            _ptr(assignment), n, _ptr(plan), threads, num_groups,
        )
    else:
        for name, arr in columns.items():
            cursors = offsets[:num_groups].copy()  # C kernel advances these
            lib.rsdl_group_rows(
                _ptr(arr), _ptr(dsts[name]), _ptr(assignment), len(arr),
                _rows_contig(arr), _ptr(cursors),
            )
    return dsts, offsets
